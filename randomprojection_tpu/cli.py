"""Command-line interface: ``python -m randomprojection_tpu <cmd>``.

Subcommands (the reference's constructor-kwargs surface, exposed as flags —
SURVEY.md §6 config/flag system):

- ``jl-dim``        JL minimum dimension for (n, eps)
- ``info``          devices / backends / native-component status
- ``project``       project a .npy/.npz matrix, streamed, with checkpoint
- ``bench``         the north-star data-resident metric (JSON line)
- ``stream-bench``  host-streamed throughput (the PCIe-bound number;
                    kept separate per SURVEY.md §7)
- ``topk-bench``    SimHash top-k serving queries/s, direct vs the
                    ``TopKServer`` micro-batcher
- ``recover``       durable index lifecycle: snapshot status + checksum
                    verification, and the subprocess SIGKILL recovery
                    smoke (``--smoke``)
- ``doctor``        per-batch critical-path report from a telemetry
                    JSONL file (alias: ``report``) — stage waterfall,
                    bubbles, degraded-event audit, tripwire status
- ``lint``          rplint: AST + flow-sensitive checks of the pipeline's
                    invariants (span balance, event-registry drift,
                    hot-path host syncs incl. one call deep, thread
                    hygiene + shutdown protocol, determinism, silent
                    swallows, Pallas DMA discipline, cross-thread
                    shared-state races, lock-order deadlocks), with
                    ``--baseline`` diffing / ``--update-baseline``
                    rewriting for incremental adoption and ``--sarif``
                    output for CI annotation
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

import numpy as np

# the live MetricsServer while a --metrics-port command runs (set and
# cleared by main(); commands with per-run registries register them as
# scrape sources through _register_metrics_source, and the live-smoke
# harness reads the bound port from here)
_METRICS_SERVER = None


def _register_metrics_source(fn) -> None:
    """Attach a snapshot source (e.g. ``stats.registry.snapshot``) to
    the live metrics endpoint when one is running; no-op otherwise."""
    server = _METRICS_SERVER
    if server is not None:
        server.add_source(fn)


def _add_common(p):
    p.add_argument("--backend", default="auto",
                   choices=["auto", "numpy", "jax"])
    p.add_argument("--prefetch-batches", type=int, default=0,
                   help="prefetch depth: run source production (hashing, "
                        "reads) and early H2D upload on a background "
                        "worker thread, keeping up to this many batches "
                        "queued ahead of the consumer (0 = synchronous)")
    p.add_argument("--ingest-workers", type=int, default=0,
                   help="staged multi-worker ingest: a pool of this many "
                        "hash workers producing disjoint batches "
                        "(reassembled in row order, bit-identical to "
                        "serial) feeding a dedicated prep/H2D uploader "
                        "stage; 0/1 = single-worker (see "
                        "--prefetch-batches).  The queue depth between "
                        "the uploader and the consumer is "
                        "--prefetch-batches (default 2 when staged)")
    p.add_argument("--hash-threads", type=int, default=None,
                   help="worker threads for the C++ murmur3 batch hasher "
                        "(sets RP_HASH_THREADS; output is bit-identical "
                        "at any count; default: hardware concurrency)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--precision", default=None,
                   choices=["default", "high", "highest", "split2"],
                   help="jax backend MXU precision mode")
    p.add_argument("--materialization", default=None,
                   choices=["dense", "lazy"],
                   help="jax backend: 'lazy' = in-kernel mask (TPU only)")
    p.add_argument("--transform-dma", default=None,
                   choices=["auto", "on", "off"],
                   help="jax backend, lazy kernel: x-tile routing — "
                        "'auto' (default) = manual double-buffered "
                        "HBM->VMEM DMA (the r14 default route), 'off' "
                        "pins the single-buffered automatic tiling")
    p.add_argument("--dispatch-steps", type=_positive_int, default=None,
                   metavar="K",
                   help="jax backend, lazy kernel: chain K row-blocks of "
                        "each transform through ONE traced dispatch "
                        "(call-boundary host gaps amortize by 1/K; "
                        "results bit-identical to K separate dispatches; "
                        "host-upload buffers are donated where XLA can "
                        "alias them)")
    _add_observability(p)
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace here")
    p.add_argument("--debug-nans", action="store_true",
                   help="jax.config jax_debug_nans: fail fast on NaN/Inf "
                        "produced by any jitted computation")
    p.add_argument("--disable-jit", action="store_true",
                   help="jax.config jax_disable_jit: run op-by-op for "
                        "debugging (orders slower)")


def _add_observability(p):
    """Flags shared by every workload subcommand (``project``,
    ``stream-bench`` via ``_add_common``, and ``bench``): logging level
    and the process-wide structured event log."""
    p.add_argument("--log-level", default="warning",
                   choices=["debug", "info", "warning", "error"])
    p.add_argument("--telemetry-jsonl", default=None, metavar="PATH",
                   help="append structured telemetry events (versioned "
                        "JSONL schema — see utils/telemetry.py) for every "
                        "pipeline stage, dispatch, commit, degraded retry "
                        "and per-batch tracing span to this file "
                        "(analyze with the 'doctor' subcommand)")
    p.add_argument("--openmetrics", default=None, metavar="PATH",
                   help="after the run, write an OpenMetrics/Prometheus "
                        "text exposition of the process metrics registry "
                        "(counters, gauges, stage-wall histograms) to "
                        "this file — pure text, no HTTP server")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve a LIVE OpenMetrics endpoint at "
                        "http://127.0.0.1:PORT/metrics for the duration "
                        "of the command (0 = ephemeral port, printed to "
                        "stderr): process registry counters/gauges, "
                        "latency histograms WITH p50/p90/p99/p99.9 "
                        "summaries, and a rolling LiveAggregator window "
                        "of span walls + time-weighted queue depth fed "
                        "by an in-process telemetry subscriber; poll it "
                        "with 'doctor --live HOST:PORT'")
    p.add_argument("--health", nargs="?", const="", default=None,
                   metavar="SPEC",
                   help="run the health plane (utils/health.py) for the "
                        "duration of the command: SLO burn-rate, stall-"
                        "watchdog, queue-pinning and degraded-spike "
                        "detectors over the live event stream, emitting "
                        "firing/cleared health.* events and (with "
                        "--metrics-port) answering GET /health (200 ok / "
                        "503 while a critical detector fires).  SPEC sets "
                        "latency targets and tuning, comma-separated: a "
                        "bare number = default p99 target in ms, "
                        "'label=ms' = per-label target, and the reserved "
                        "keys budget/fast/slow/fire/clear/stall/tick "
                        "tune windows and thresholds (e.g. "
                        "'25,tenant-a=10,fast=2,slow=10').  No SPEC = "
                        "no latency targets; the non-SLO detectors still "
                        "run")
    p.add_argument("--flight-dump", default=None, metavar="PATH",
                   help="keep an always-on in-memory flight recorder "
                        "(ring of the last 2048 events/spans) and dump "
                        "it atomically to PATH as a self-describing "
                        "postmortem JSON on SIGTERM/SIGABRT, unhandled "
                        "exception, or stall-watchdog trip — analyze "
                        "with 'doctor --postmortem PATH'")


def _positive_int(v: str) -> int:
    i = int(v)
    if i < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return i


def _density_arg(v: str) -> float:
    f = float(v)
    if not 0.0 < f <= 1.0:
        raise argparse.ArgumentTypeError(f"density must be in (0, 1], got {v}")
    return f


def _backend_options(args) -> dict:
    opts = {}
    if getattr(args, "precision", None):
        opts["precision"] = args.precision
    if getattr(args, "materialization", None):
        opts["materialization"] = args.materialization
    tdma = getattr(args, "transform_dma", None)
    if tdma in ("on", "off"):
        opts["transform_dma"] = tdma == "on"
    if getattr(args, "dispatch_steps", None):
        opts["dispatch_steps"] = args.dispatch_steps
    return opts


def build_parser():
    p = argparse.ArgumentParser(
        prog="randomprojection_tpu",
        description="TPU-native random projection framework",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    q = sub.add_parser("jl-dim", help="JL minimum dimension")
    q.add_argument("--n-samples", type=int, required=True)
    q.add_argument("--eps", type=float, default=0.1)

    q = sub.add_parser("info", help="environment / backend status")

    q = sub.add_parser("project", help="project a matrix from disk")
    q.add_argument("--input", required=True, help=".npy (dense) or .npz CSR")
    q.add_argument("--output", required=True, help="output .npy path")
    q.add_argument("--kind", default="gaussian",
                   choices=["gaussian", "sparse", "sign", "countsketch"])
    q.add_argument("--n-components", default="auto",
                   help="int or 'auto' (JL bound)")
    q.add_argument("--eps", type=float, default=0.1)
    q.add_argument("--density", default="auto")
    q.add_argument("--batch-rows", type=int, default=65536)
    q.add_argument("--pipeline-depth", type=_positive_int, default=2,
                   help="batches kept in flight on the jax backend "
                        "(double buffering); results are depth-invariant")
    q.add_argument("--checkpoint", default=None,
                   help="cursor path for resume")
    _add_common(q)

    q = sub.add_parser("bench", help="data-resident north-star metric")
    q.add_argument("--preset", default="smoke", choices=["smoke", "full"])
    q.add_argument("--d", type=int, default=4096,
                   help="input dimension for the headline modes")
    q.add_argument("--k", type=int, default=256,
                   help="output dimension for the headline modes")
    q.add_argument("--density", type=_density_arg, default=1.0 / 3.0,
                   help="mask density for the headline modes")
    q.add_argument("--transform-dma", default="auto",
                   choices=["auto", "on", "off"],
                   help="fused-kernel x routing for the lazy modes: "
                        "'auto' = kernel default (manual double-buffered "
                        "DMA since r14), 'off' pins the single-buffered "
                        "automatic tiling — the A/B lever for attributing "
                        "a rate delta to the DMA pipeline")
    q.add_argument("--dispatch-steps", type=_positive_int, default=None,
                   metavar="K",
                   help="anti-cache steps chained through one traced "
                        "dispatch (overrides the preset; call-boundary "
                        "host gaps amortize by 1/K)")
    _add_observability(q)

    q = sub.add_parser(
        "doctor", aliases=["report"],
        help="per-batch critical-path report from a telemetry JSONL file",
        description="Reconstruct per-batch timelines from the tracing "
                    "spans in a --telemetry-jsonl file and print the "
                    "critical-path waterfall (per-stage bound fraction + "
                    "pipeline bubbles), queue-depth summary, the "
                    "degraded-event audit (VMEM-OOM retries, dense "
                    "fallbacks, clamps) and the regression-tripwire "
                    "status from the newest committed bench record.  "
                    "Tolerates crashed runs: torn tails and orphaned "
                    "spans are counted, not fatal.  With --live "
                    "HOST:PORT it instead polls a --metrics-port "
                    "endpoint and renders a refreshing live view.",
    )
    q.add_argument("telemetry", nargs="?", metavar="TELEMETRY_JSONL",
                   help="event file written by --telemetry-jsonl "
                        "(omit with --live)")
    q.add_argument("--json", action="store_true",
                   help="print the report as one JSON object instead of "
                        "the rendered text (with --live: one JSON line "
                        "per poll)")
    q.add_argument("--live", default=None, metavar="HOST:PORT",
                   help="poll the live metrics endpoint a --metrics-port "
                        "run is serving and render a refreshing terminal "
                        "view: queue depths, rolling per-stage span "
                        "walls, serve-latency quantiles, active health "
                        "verdicts, degraded-counter rates (including "
                        "per-subscriber drop rates)")
    q.add_argument("--postmortem", default=None, metavar="DUMP",
                   help="render a flight-recorder dump (--flight-dump "
                        "PATH of a crashed/killed run) instead of a "
                        "telemetry file: the final seconds — last-known "
                        "per-stage activity, spans in flight at death, "
                        "detectors firing at death, counter snapshot")
    q.add_argument("--interval", type=float, default=1.0,
                   help="--live poll interval in seconds")
    q.add_argument("--iterations", type=int, default=0, metavar="N",
                   help="--live: stop after N polls (0 = until "
                        "interrupted)")

    q = sub.add_parser(
        "lint",
        help="rplint: AST + flow-sensitive invariant checks "
             "(rules RP01-RP14)",
        description="Run the project's static-analysis pass "
                    "(randomprojection_tpu/analysis/rplint.py) over the "
                    "installed package: span balance, telemetry.EVENTS "
                    "registry drift, host syncs in hot-path loops "
                    "(syntactic AND one call deep), thread/queue "
                    "hygiene and flow-sensitive shutdown protocol, "
                    "ops/ determinism, silently-swallowed exceptions, "
                    "Pallas DMA copy/wait/budget discipline, "
                    "cross-thread shared-state races (thread roles + "
                    "lock regions on a shared CFG), lock-order "
                    "deadlock analysis, resource-lifecycle pairing "
                    "(every acquire released on every path out), "
                    "durable-commit discipline (tmp/flush/fsync/replace "
                    "plus manifest-last ordering), and degraded-path "
                    "contracts (every fallback rung doctor-visible and "
                    "memoized).  Exit codes: 0 = no unsuppressed "
                    "finding (none outside the baseline when one is "
                    "given), 1 = findings, 2 = internal error "
                    "(unreadable target, malformed baseline, analysis "
                    "crash) — a partial run never reports success.  "
                    "Findings are suppressed per line by an inline "
                    "`# rplint: allow[RPxx] — reason` pragma.  Pure "
                    "stdlib AST analysis: never imports or executes the "
                    "code it checks.",
    )
    q.add_argument("paths", nargs="*", metavar="PATH",
                   help="specific files to lint (default: the whole "
                        "package plus the registry drift check)")
    q.add_argument("--json", action="store_true",
                   help="emit the stable findings record as one JSON "
                        "object: rplint version, per-finding rule id / "
                        "path / line / message / severity / pragma "
                        "state, counts, unresolvable-emit tally")
    q.add_argument("--baseline", default=None, metavar="JSON",
                   help="a prior `lint --json` record: fail only on "
                        "findings NOT in it (matched on rule+path+"
                        "message, so line drift never re-flags a "
                        "baselined finding)")
    q.add_argument("--update-baseline", action="store_true",
                   help="rewrite the --baseline file in place from the "
                        "fresh lint record (prunes stale entries, "
                        "accepts current findings; exit 0) — the "
                        "workflow for adopting intended new findings "
                        "instead of hand-editing the JSON")
    q.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write the findings as a SARIF 2.1.0 log "
                        "to PATH so CI and editors can annotate them "
                        "inline")
    q.add_argument("--jobs", type=int, default=None, metavar="N",
                   help="lint files across N worker processes (default: "
                        "min(8, cpu count); 1 = serial). Findings stay "
                        "in deterministic path order either way")

    q = sub.add_parser(
        "recover",
        help="durable index lifecycle: snapshot status, checksum "
             "verification, and the process-kill recovery smoke",
        description="Inspect a durable SimHash index snapshot / ingest "
                    "directory (durable.py): validate the manifest "
                    "version, verify every chunk's SHA-256 payload "
                    "checksum, check that chunk row ranges tile exactly "
                    "once, and list orphan spill files a crash left "
                    "behind — JSON status on stdout, non-zero exit on "
                    "corruption.  --smoke instead runs the subprocess "
                    "SIGKILL fault matrix at toy shapes (kill at "
                    "mid-batch, post-yield pre-ack and "
                    "mid-snapshot-rename; restart; assert the recovered "
                    "index is bit-identical to an uninterrupted run).",
    )
    q.add_argument("dir", nargs="?", metavar="DIR",
                   help="snapshot / durable-ingest directory to inspect")
    q.add_argument("--smoke", action="store_true",
                   help="run the crash-recovery fault matrix in a "
                        "temporary directory (or DIR when given) and "
                        "exit non-zero unless every kill point recovers "
                        "bit-identically")
    # harness child entry: one deterministic toy ingest into DIR,
    # honoring RP_DURABLE_KILL kill points (used by --smoke and tests)
    q.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    q.add_argument("--rows", type=_positive_int, default=192,
                   help="harness rows (child/smoke)")
    q.add_argument("--batch-rows", type=_positive_int, default=32,
                   help="harness rows per batch (child/smoke)")
    q.add_argument("--d", type=_positive_int, default=16,
                   help="harness input dimension (child/smoke)")
    q.add_argument("--bits", type=_positive_int, default=64,
                   help="harness SimHash code bits (child/smoke)")
    q.add_argument("--seed", type=int, default=0)
    _add_observability(q)

    q = sub.add_parser(
        "topk-bench",
        help="SimHash top-k serving throughput (direct vs micro-batched)",
        description="Build a random SimHashIndex and measure query_topk "
                    "queries/s two ways: direct per-request calls, and "
                    "through the TopKServer micro-batcher that coalesces "
                    "concurrent requests into one tile dispatch.",
    )
    q.add_argument("--index-codes", type=_positive_int, default=1 << 18,
                   help="rows in the resident code index")
    q.add_argument("--code-bytes", type=_positive_int, default=32,
                   help="packed code width (bytes/row; 32 = 256 bits)")
    q.add_argument("--m", type=_positive_int, default=16,
                   help="neighbors per query")
    q.add_argument("--queries", type=_positive_int, default=4096,
                   help="total queries per measurement")
    q.add_argument("--request-rows", type=_positive_int, default=64,
                   help="query rows per client request")
    q.add_argument("--clients", type=_positive_int, default=8,
                   help="concurrent client threads for the server mode")
    q.add_argument("--server-batch", type=_positive_int, default=8192,
                   help="TopKServer max coalesced rows per dispatch")
    q.add_argument("--server-delay-ms", type=float, default=2.0,
                   help="TopKServer max wait for stragglers once a "
                        "request is in hand")
    q.add_argument("--topk-impl", default="auto",
                   choices=["auto", "fused", "scan"],
                   help="query_topk device path: 'auto' (default) serves "
                        "via the fused Pallas kernel where plannable, "
                        "'scan' pins the retained lax.scan reference path")
    q.add_argument("--shards", type=int, default=0,
                   help="also measure the sharded tier: row-shard the "
                        "corpus over this many shard devices "
                        "(serving.ShardedSimHashIndex; 0 = skip)")
    q.add_argument("--replicas", type=_positive_int, default=1,
                   help="replica groups for the sharded tier; coalesced "
                        "batches route round-robin across them "
                        "(serving.ShardedTopKServer)")
    q.add_argument("--probes", default="", metavar="P1,P2,...",
                   help="also measure the multi-probe LSH candidate "
                        "tier (ann.LSHSimHashIndex) at each probe "
                        "count: recall@m vs brute force, candidate "
                        "fraction and q/s per point — the recall/q-s "
                        "tradeoff curve (empty = skip)")
    q.add_argument("--lsh-bands", type=int, default=0,
                   help="LSH band count (0 = auto: min(8, bits/band))")
    q.add_argument("--lsh-band-bits", type=int, default=0,
                   help="LSH bits per band key (0 = auto: min(16, bits))")
    q.add_argument("--probe-path", default="auto",
                   choices=["auto", "host", "device"],
                   help="LSH candidate generation path: 'device' runs "
                        "the fused on-device probe→gather→re-rank "
                        "program, 'host' pins the host CSR-walk rung, "
                        "'auto' picks device on a real accelerator only")
    q.add_argument("--adaptive", action="store_true",
                   help="adaptive per-query probing on the device path: "
                        "each --probes value becomes the per-query "
                        "ceiling and the record carries probes-used "
                        "histograms")
    q.add_argument("--candidate-budget", type=int, default=0,
                   help="adaptive per-query candidate budget "
                        "(0 = uncapped)")
    q.add_argument("--hbm-budget", type=int, default=0, metavar="BYTES",
                   help="also measure the tiered hot/cold residency "
                        "path (ISSUE 19 / r21): serve the same corpus "
                        "through an index whose HBM budget is capped "
                        "at this many bytes, cold chunks streaming in "
                        "under the hot-tier kernel — reports hot-hit "
                        "fraction, cold-fetch p99/overlap and q/s vs "
                        "the resident run above (0 = skip)")
    q.add_argument("--cold-tier", default="host",
                   choices=["host", "disk"],
                   help="where --hbm-budget's cold chunks live: pinned "
                        "host RAM, or memmap-backed spill files in the "
                        "r11 checksummed format")
    q.add_argument("--seed", type=int, default=0)
    _add_observability(q)

    q = sub.add_parser(
        "loadgen",
        help="open-loop load generator -> per-label tail-latency SLO "
             "record (topk_slo)",
        description="Drive a ShardedTopKServer with an OPEN-loop "
                    "arrival schedule (Poisson or bursty, mixed request "
                    "sizes, fixed client labels — fully determined by "
                    "--seed, so the identical seed reproduces the "
                    "identical schedule) and emit a 'topk_slo' record "
                    "carrying per-client-label p50/p90/p99/p99.9 "
                    "latency tables, rejects, and the schedule digest.  "
                    "Unlike topk-bench's closed-loop clients, a slow "
                    "server here does NOT slow its own offered load — "
                    "queueing collapse shows up in the tail instead of "
                    "hiding in the rate.",
    )
    q.add_argument("--index-codes", type=_positive_int, default=1 << 14,
                   help="rows in the resident code index")
    q.add_argument("--code-bytes", type=_positive_int, default=32,
                   help="packed code width (bytes/row)")
    q.add_argument("--m", type=_positive_int, default=16,
                   help="neighbors per query")
    q.add_argument("--shards", type=_positive_int, default=1,
                   help="row-shard the corpus over this many shard "
                        "devices (serving.ShardedSimHashIndex)")
    q.add_argument("--replicas", type=_positive_int, default=1,
                   help="replica groups; coalesced batches route "
                        "round-robin across them")
    q.add_argument("--topk-impl", default="auto",
                   choices=["auto", "fused", "scan"],
                   help="query_topk device path per shard")
    q.add_argument("--probes", default="0", metavar="P|label=P,...",
                   help="serve through the multi-probe LSH candidate "
                        "tier (ann.LSHShardedSimHashIndex): a bare int "
                        "P probes P buckets per band for ALL labels "
                        "(0 = exact scan tier); 'label=P,...' pairs set "
                        "a PER-LABEL probe policy (unlisted labels use "
                        "the tier default; P=0 pins a label onto the "
                        "exact path) — the mixed quality classes the "
                        "per-label SLO record expresses")
    q.add_argument("--rate", type=float, default=50.0, metavar="QPS",
                   help="mean offered request rate (requests/s)")
    q.add_argument("--duration", type=float, default=5.0, metavar="SEC",
                   help="schedule length in seconds")
    q.add_argument("--arrival", default="poisson",
                   choices=["poisson", "bursty"],
                   help="arrival process: memoryless Poisson, or a "
                        "mean-preserving on/off burst cycle "
                        "(--burst-factor/--burst-fraction/--burst-period)")
    q.add_argument("--request-rows", default="16,64,256",
                   metavar="R1,R2,...",
                   help="request-size mix: query rows drawn uniformly "
                        "from this comma list")
    q.add_argument("--labels", default="tenant-a,tenant-b",
                   metavar="L1,L2,...",
                   help="client labels assigned (seeded-random) per "
                        "request; the record carries one SLO table per "
                        "label")
    q.add_argument("--burst-factor", type=float, default=8.0,
                   help="bursty: ON-phase rate multiplier")
    q.add_argument("--burst-fraction", type=float, default=0.125,
                   help="bursty: fraction of each period that is ON")
    q.add_argument("--burst-period", type=float, default=1.0,
                   metavar="SEC", help="bursty: cycle period")
    q.add_argument("--server-batch", type=_positive_int, default=8192,
                   help="ShardedTopKServer max coalesced rows/dispatch")
    q.add_argument("--server-delay-ms", type=float, default=2.0,
                   help="ShardedTopKServer straggler wait")
    q.add_argument("--max-pending", type=_positive_int, default=8192,
                   help="submit-queue bound (requests); beyond it "
                        "submissions are shed and counted as rejects")
    q.add_argument("--seed", type=int, default=0)
    q.add_argument("--settle", type=float, default=0.0, metavar="SEC",
                   help="keep the process (and its --health/"
                        "--metrics-port planes) alive this long after "
                        "the drain — the recovery window in which a "
                        "fired SLO burn-rate detector clears and "
                        "GET /health flips back to 200 (the health-"
                        "smoke watches exactly this)")
    q.add_argument("--out", default=None, metavar="PATH",
                   help="also write the topk_slo record (one JSON "
                        "object) to this file — the bench artifact "
                        "ROADMAP #4/#5 scenarios reuse")
    _add_observability(q)

    q = sub.add_parser("stream-bench", help="host-streamed throughput")
    q.add_argument("--rows", type=int, default=262144)
    q.add_argument("--d", type=int, default=4096)
    q.add_argument("--k", type=int, default=256)
    q.add_argument("--batch-rows", type=int, default=16384,
                   help="rows per streamed batch; host RSS is ~2 batches "
                        "regardless of --rows (the source synthesizes "
                        "batches on demand)")
    q.add_argument("--kind", default="gaussian",
                   choices=["gaussian", "sparse", "sign", "countsketch"])
    q.add_argument("--density", default="auto")
    q.add_argument("--eps", type=float, default=0.1)
    q.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="input dtype: bfloat16 halves the h2d bytes "
                        "(bf16 in -> bf16 out policy)")
    _add_common(q)

    return p


def cmd_jl_dim(args):
    from randomprojection_tpu import johnson_lindenstrauss_min_dim

    print(johnson_lindenstrauss_min_dim(args.n_samples, eps=args.eps))


def cmd_info(args):
    from randomprojection_tpu.backends import available_backends
    from randomprojection_tpu.native.build import load_murmur3

    info = {"backends": list(available_backends()),
            "native_murmur3": load_murmur3() is not None}
    try:
        import jax

        info["jax_devices"] = [str(d) for d in jax.devices()]
        info["default_backend"] = jax.default_backend()
    except Exception as e:  # pragma: no cover - degraded envs
        info["jax_error"] = str(e)
    print(json.dumps(info, indent=1))


def _make_estimator(args):
    import randomprojection_tpu as rp

    k = args.n_components
    if k != "auto":
        k = int(k)
    common = dict(random_state=args.seed, backend=args.backend)
    opts = _backend_options(args)
    if opts:
        common["backend_options"] = opts
    if args.kind != "sparse" and getattr(args, "density", "auto") != "auto":
        # refuse rather than silently drop: only the sparse kind has a
        # density parameter
        raise SystemExit(f"--density is not supported for --kind {args.kind}")
    if args.kind == "gaussian":
        return rp.GaussianRandomProjection(k, eps=args.eps, **common)
    if args.kind == "sparse":
        density = args.density if args.density == "auto" else float(args.density)
        return rp.SparseRandomProjection(k, eps=args.eps, density=density, **common)
    if args.kind == "sign":
        if k == "auto":
            raise SystemExit("--kind sign requires an explicit --n-components")
        return rp.SignRandomProjection(k, **common)
    if k == "auto":
        raise SystemExit("--kind countsketch requires an explicit --n-components")
    if opts:
        # refuse rather than silently drop: CountSketch has no precision/
        # materialization knobs (the MXU path is already split2-exact)
        raise SystemExit(
            "--precision/--materialization are not supported for "
            "--kind countsketch"
        )
    return rp.CountSketch(k, random_state=args.seed, backend=args.backend)


def _wrap_prefetch(source, est, args, stats):
    """Wrap ``source`` in the requested ingest pipeline: a staged
    multi-worker pool (``--ingest-workers >= 2``) or a single prefetch
    worker (``--prefetch-batches``); production (and the estimator's
    early-H2D ``prepare_batch``) moves off the consumer thread either
    way."""
    depth = getattr(args, "prefetch_batches", 0)
    workers = getattr(args, "ingest_workers", 0)
    if workers >= 2:
        from randomprojection_tpu.streaming import StagedIngestSource

        return StagedIngestSource(
            source, workers=workers, depth=depth or 2,
            prepare=est.prepare_batch, stats=stats,
        )
    if not depth:
        return source
    from randomprojection_tpu.streaming import PrefetchSource

    return PrefetchSource(
        source, depth=depth, prepare=est.prepare_batch, stats=stats
    )


def cmd_project(args):
    import os

    import scipy.sparse as sp

    from randomprojection_tpu.streaming import (
        ArraySource,
        StreamCursor,
        stream_to_array,
        stream_to_memmap,
    )
    from randomprojection_tpu.utils.observability import (
        StreamStats,
        profile_trace,
    )

    if args.input.endswith(".npz"):
        X = sp.load_npz(args.input).tocsr()
    else:
        from randomprojection_tpu.utils.validation import restore_void_dtype

        # restore bf16 arrays whose .npy header degraded to raw void
        X = restore_void_dtype(np.load(args.input, mmap_mode="r"))
    source = ArraySource(X, args.batch_rows)
    stats = StreamStats(log_every=10)
    _register_metrics_source(stats.registry.snapshot)
    # np.save appends .npy itself; normalize once so the JSON summary and
    # the memmap path always name the file that actually exists
    out_path = args.output if args.output.endswith(".npy") else args.output + ".npy"

    if args.checkpoint is None:
        est = _make_estimator(args).fit_source(source)
        with profile_trace(args.profile_dir):
            Y = stream_to_array(
                est, _wrap_prefetch(source, est, args, stats), stats=stats,
                pipeline_depth=args.pipeline_depth,
            )
        if sp.issparse(Y):
            Y = Y.toarray()
        np.save(out_path, Y)
        print(json.dumps({"output": out_path, "shape": list(Y.shape),
                          "dtype": str(Y.dtype), **stats.summary()}))
        _write_openmetrics(args, stats.registry.snapshot())
        return

    # Checkpointed runs write through an on-disk .npy memmap so every
    # committed batch is durable: a mid-run crash resumes from the cursor
    # into the same file, and a completed run is never silently overwritten.
    # A fingerprint sidecar pins the run configuration — input data,
    # estimator parameters, output path: resuming with anything different
    # would silently mix two projections in one file.  Built from the raw
    # CLI args (not the fitted estimator) so every refusal below fires
    # before any device work or matrix materialization.
    fingerprint = {
        "input": os.path.abspath(args.input),
        "kind": args.kind, "n_components": str(args.n_components),
        "eps": args.eps,
        "seed": args.seed, "density": str(getattr(args, "density", "auto")),
        "backend": args.backend, "batch_rows": args.batch_rows,
        "precision": getattr(args, "precision", None),
        "materialization": getattr(args, "materialization", None),
        "n_rows": source.n_rows, "n_features": source.n_features,
        "output": os.path.abspath(out_path),
    }
    meta_path = args.checkpoint + ".meta.json"
    rows_done = (
        StreamCursor.load(args.checkpoint).rows_done
        if os.path.exists(args.checkpoint)
        else 0
    )
    if rows_done > 0 and not os.path.exists(meta_path):
        raise SystemExit(
            f"checkpoint {args.checkpoint} has partial progress but no "
            f"{meta_path} fingerprint; cannot prove the resume parameters "
            f"match the original run — delete the checkpoint to restart"
        )
    if rows_done > 0:
        with open(meta_path) as f:
            recorded = json.load(f)
        if recorded != fingerprint:
            diff = {
                kk: (recorded.get(kk), fingerprint.get(kk))
                for kk in sorted(set(recorded) | set(fingerprint))
                if recorded.get(kk) != fingerprint.get(kk)
            }
            raise SystemExit(
                f"checkpoint {args.checkpoint} was written by a run with "
                f"different parameters {diff} (recorded, requested); "
                f"resuming would mix two projections in one output — "
                f"delete the checkpoint to restart"
            )
    if rows_done >= source.n_rows and rows_done > 0:
        raise SystemExit(
            f"checkpoint {args.checkpoint} records a completed run "
            f"(rows_done={rows_done}); refusing to overwrite {out_path} — "
            f"delete the checkpoint file to re-project from scratch"
        )
    est = _make_estimator(args).fit_source(source)
    if rows_done == 0:
        with open(meta_path, "w") as f:
            json.dump(fingerprint, f)
    try:
        with profile_trace(args.profile_dir):
            out = stream_to_memmap(
                est, _wrap_prefetch(source, est, args, stats), out_path,
                checkpoint_path=args.checkpoint, stats=stats,
                pipeline_depth=args.pipeline_depth,
            )
    except ValueError as e:
        raise SystemExit(str(e))
    print(json.dumps({"output": out_path, "shape": list(out.shape),
                      "dtype": str(out.dtype), **stats.summary()}))
    _write_openmetrics(args, stats.registry.snapshot())


def _write_openmetrics(args, *extra_snapshots) -> None:
    """Write the OpenMetrics exposition when ``--openmetrics PATH`` was
    given: the process-wide registry (backend dispatches, hash paths,
    degraded retries) merged with any per-run registries (the stream's
    ``StreamStats``).  Consumes the flag, so ``main``'s fallback write
    (for commands without their own stats) fires at most once.  A file
    write, never stdout — the bench's final-line compact-digest contract
    must stay intact."""
    path = getattr(args, "openmetrics", None)
    if not path:
        return
    from randomprojection_tpu.utils import telemetry

    with open(path, "w") as f:
        f.write(
            telemetry.to_openmetrics(
                telemetry.registry().snapshot(), *extra_snapshots
            )
        )
    args.openmetrics = None


def _cmd_doctor_live(args) -> int:
    """``doctor --live HOST:PORT``: poll the live metrics endpoint and
    render a refreshing terminal view (see utils/metrics_server.py)."""
    import time

    from randomprojection_tpu.utils import metrics_server

    host, _, port_s = args.live.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        port = -1
    if not host or not 0 < port < 65536:
        raise SystemExit(
            f"--live wants HOST:PORT (e.g. 127.0.0.1:9100), got "
            f"{args.live!r}"
        )
    if args.interval <= 0:
        raise SystemExit(f"--interval must be > 0, got {args.interval}")
    prev = None
    poll = 0
    consecutive_failures = 0
    while True:
        poll += 1
        try:
            text = metrics_server.fetch_metrics(
                host, port, timeout=max(args.interval, 1.0)
            )
        except OSError as e:
            # a FIRST-poll failure means the endpoint was never there;
            # later ones are tolerated briefly — one timed-out scrape
            # (the serving process momentarily compile/GIL-bound) must
            # not kill a dashboard that has been live for hours
            consecutive_failures += 1
            if poll == 1 or consecutive_failures >= 5:
                raise SystemExit(
                    f"live endpoint {args.live} unreachable"
                    + (
                        f" ({consecutive_failures} consecutive "
                        "failed polls)" if poll > 1 else ""
                    )
                    + f": {e} — is the serving process running with "
                    "--metrics-port?"
                )
            print(
                f"live doctor: poll #{poll} failed ({e}); retrying",
                file=sys.stderr,
            )
            if args.iterations and poll >= args.iterations:
                return 0
            time.sleep(args.interval)
            continue
        consecutive_failures = 0
        plain, labeled = metrics_server.parse_openmetrics(text)
        if args.json:
            print(metrics_server.live_snapshot_json(plain, labeled))
        else:
            if sys.stdout.isatty() and poll > 1:
                print("\x1b[2J\x1b[H", end="")
            print(
                metrics_server.render_live(
                    plain, labeled, prev, interval_s=args.interval,
                    endpoint=args.live, poll=poll,
                ),
                end="", flush=True,
            )
        prev = plain
        if args.iterations and poll >= args.iterations:
            return 0
        time.sleep(args.interval)


def cmd_doctor(args):
    import os

    from randomprojection_tpu.utils.trace_report import (
        build_report,
        render_report,
    )

    if getattr(args, "live", None):
        return _cmd_doctor_live(args)
    if getattr(args, "postmortem", None):
        from randomprojection_tpu.utils.trace_report import (
            build_postmortem,
            render_postmortem,
        )

        if not os.path.exists(args.postmortem):
            raise SystemExit(
                f"no such flight-recorder dump: {args.postmortem}"
            )
        try:
            with open(args.postmortem) as f:
                dump = json.load(f)
            pm = build_postmortem(dump)
        except (ValueError, KeyError, TypeError) as e:
            raise SystemExit(
                f"unreadable flight-recorder dump {args.postmortem}: {e}"
            )
        if args.json:
            print(json.dumps(pm))
        else:
            print(render_postmortem(pm), end="")
        return
    if not args.telemetry:
        raise SystemExit(
            "doctor wants a TELEMETRY_JSONL file, --postmortem DUMP, "
            "or --live HOST:PORT"
        )
    if not os.path.exists(args.telemetry):
        raise SystemExit(f"no such telemetry file: {args.telemetry}")
    try:
        report = build_report(args.telemetry)
    except (ValueError, KeyError, TypeError) as e:
        # a torn FINAL line is tolerated by the reader; reaching here
        # means a torn MIDDLE line (or payloads of the wrong shape) —
        # the file is corrupt, not merely truncated
        raise SystemExit(f"corrupt telemetry file {args.telemetry}: {e}")
    # regression-tripwire status rides along: the newest committed bench
    # record carries its own round-over-round verdict (benchmark.py)
    from randomprojection_tpu import benchmark

    try:
        newest = benchmark.newest_committed_bench()
        if newest is None:
            report["tripwire"] = {"error": "no committed BENCH_r*.json"}
        else:
            rec = benchmark.load_bench_record(newest)
            # regressions stays None (not []) when the record predates
            # the tripwire: "no verdict recorded" must render differently
            # from "tripwire ran and found nothing"
            report["tripwire"] = {
                "baseline": os.path.basename(newest),
                "regressions": rec.get("regressions"),
                "regressions_vs": rec.get("regressions_vs"),
                "regressions_skipped": rec.get("regressions_skipped"),
            }
    except (ValueError, OSError, KeyError) as e:  # pragma: no cover
        report["tripwire"] = {"error": f"bench record unreadable: {e}"}
    if args.json:
        print(json.dumps(report))
    else:
        print(render_report(report), end="")


def cmd_lint(args):
    """rplint over the package (or explicit paths); returns the exit
    code — 0 clean, 1 on unsuppressed (non-baselined) findings, 2 on an
    internal error — so `make lint` / `make lint-ci` and the tier-1
    suite gate on a clean tree and can never mistake a crashed partial
    run for success."""
    from randomprojection_tpu.analysis import rplint

    argv = list(args.paths)
    if args.json:
        argv.append("--json")
    if args.baseline is not None:
        argv += ["--baseline", args.baseline]
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.sarif is not None:
        argv += ["--sarif", args.sarif]
    if args.jobs is not None:
        argv += ["--jobs", str(args.jobs)]
    return rplint.main(argv)


def cmd_recover(args):
    """Durable-lifecycle operations (see ``durable.py``): snapshot
    status + checksum verification (default), the subprocess SIGKILL
    recovery smoke (``--smoke``), and the deterministic harness child
    ingest (``--child``, used by the smoke and the test suite)."""
    import tempfile

    from randomprojection_tpu import durable

    if args.child:
        if not args.dir:
            raise SystemExit("recover --child requires DIR")
        summary = durable.demo_ingest(
            args.dir, rows=args.rows, batch_rows=args.batch_rows,
            d=args.d, bits=args.bits, seed=args.seed,
        )
        print(json.dumps(summary))
        return 0
    if args.smoke:
        made_tmp = args.dir is None
        workdir = args.dir or tempfile.mkdtemp(prefix="rp_recover_smoke_")
        verdict = durable.crash_smoke(
            workdir, rows=args.rows, batch_rows=args.batch_rows,
            d=args.d, bits=args.bits, seed=args.seed,
        )
        if made_tmp and verdict["ok"]:
            # clean pass: don't leak snapshot copies into TMPDIR; a
            # failing run keeps the directory for forensics (named in
            # the verdict via the per-case paths)
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 1
    if not args.dir:
        raise SystemExit("recover requires DIR (or --smoke)")
    status = durable.verify_snapshot(args.dir)
    print(json.dumps(status))
    return 0 if status["ok"] else 1


def cmd_bench(args):
    from randomprojection_tpu.benchmark import emit_bench_output, run

    # full record first, then the ≤2 KB compact digest as the FINAL line —
    # same tail-safe contract as the repo-root bench.py entry point
    emit_bench_output(run(
        args.preset, k=args.k, d=args.d, density=args.density,
        transform_dma={"auto": None, "on": True, "off": False}[
            args.transform_dma
        ],
        dispatch_steps=args.dispatch_steps,
    ))


def cmd_topk_bench(args):
    """Top-k serving throughput, direct vs micro-batched (the r9 serving
    path): the direct mode issues one ``query_topk`` per ``request-rows``
    request back-to-back; the server mode has ``--clients`` threads
    submit the same requests concurrently through a ``TopKServer``,
    which coalesces them into ``--server-batch``-row tile dispatches.
    Query values are distinct per request (sliced from one pregenerated
    pool) so this box's device call cache cannot serve repeats."""
    import threading
    import time

    from randomprojection_tpu.models.sketch import SimHashIndex, TopKServer

    rng = np.random.default_rng(args.seed)
    codes = rng.integers(
        0, 256, size=(args.index_codes, args.code_bytes), dtype=np.uint8
    )
    n_requests = -(-args.queries // args.request_rows)
    pool = rng.integers(
        0, 256, size=(n_requests * args.request_rows, args.code_bytes),
        dtype=np.uint8,
    )
    requests = [
        pool[i * args.request_rows : (i + 1) * args.request_rows]
        for i in range(n_requests)
    ]
    index = SimHashIndex(codes, topk_impl=args.topk_impl)
    index.query_topk(requests[0], args.m)  # warm compile

    t0 = time.perf_counter()
    for req in requests:
        index.query_topk(req, args.m)
    direct_elapsed = time.perf_counter() - t0
    direct_qps = len(requests) * args.request_rows / direct_elapsed

    server = TopKServer(
        index, args.m, max_batch=args.server_batch,
        max_delay_s=args.server_delay_ms / 1e3,
    )
    server.query(requests[0])  # warm the coalesced-bucket compile

    def client(reqs, out):
        futs = [server.submit(r) for r in reqs]
        out.extend(f.result() for f in futs)

    per_client = [requests[i :: args.clients] for i in range(args.clients)]
    results: list = [[] for _ in range(args.clients)]
    threads = [
        threading.Thread(
            target=client, args=(per_client[i], results[i]), daemon=True
        )
        for i in range(args.clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server_elapsed = time.perf_counter() - t0
    server.close()
    server_qps = len(requests) * args.request_rows / server_elapsed

    sharded = None
    if args.shards:
        from randomprojection_tpu.serving import (
            ShardedSimHashIndex,
            ShardedTopKServer,
        )

        groups = [
            ShardedSimHashIndex(
                codes, n_shards=args.shards, topk_impl=args.topk_impl
            )
            for _ in range(args.replicas)
        ]
        sh_server = ShardedTopKServer(
            groups, args.m, max_batch=args.server_batch,
            max_delay_s=args.server_delay_ms / 1e3,
        )
        sh_server.query(requests[0])  # warm every shard's bucket
        pre = [g.stats() for g in groups]
        sh_results: list = [[] for _ in range(args.clients)]

        def sh_client(reqs, out):
            # client() above is bound to the plain server; this one
            # submits the same request stream to the sharded tier
            futs = [sh_server.submit(r) for r in reqs]
            out.extend(f.result() for f in futs)

        sh_threads = [
            threading.Thread(
                target=sh_client, args=(per_client[i], sh_results[i]),
                daemon=True,
            )
            for i in range(args.clients)
        ]
        t0 = time.perf_counter()
        for t in sh_threads:
            t.start()
        for t in sh_threads:
            t.join()
        sh_elapsed = time.perf_counter() - t0
        sh_stats = sh_server.stats()
        sh_server.close()
        post = [g.stats() for g in groups]
        sharded = {
            "shards": args.shards,
            "replicas": args.replicas,
            "queries_per_s": round(
                len(requests) * args.request_rows / sh_elapsed, 1
            ),
            "merges": sum(
                b["merges"] - a["merges"] for a, b in zip(pre, post)
            ),
            "merge_wall_s": round(sum(
                b["merge_wall_s"] - a["merge_wall_s"]
                for a, b in zip(pre, post)
            ), 6),
            "replica_batches": sh_stats["replica_batches"],
        }

    lsh = None
    if args.probes.strip():
        from randomprojection_tpu.ann import LSHSimHashIndex
        from randomprojection_tpu.models.sketch import topk_bruteforce

        try:
            probe_counts = [
                int(v) for v in args.probes.split(",") if v.strip()
            ]
        except ValueError:
            probe_counts = []
        if not probe_counts or any(p < 1 for p in probe_counts):
            raise SystemExit(
                f"--probes wants a comma list of positive ints, got "
                f"{args.probes!r}"
            )
        from randomprojection_tpu.ops.probe_kernels import interpret_default
        from randomprojection_tpu.utils import telemetry as _telemetry

        lsh_index = LSHSimHashIndex(
            codes,
            bands=args.lsh_bands or None,
            band_bits=args.lsh_band_bits or None,
            topk_impl=args.topk_impl,
            probe_path=args.probe_path,
            adaptive=bool(args.adaptive),
            candidate_budget=args.candidate_budget or None,
        )
        # exact truth for recall@m: brute force over the same corpus
        # (host reference — the documented tie order)
        ref_rows = min(len(requests), 4) * args.request_rows
        true_d, true_i = topk_bruteforce(pool[:ref_rows], codes, args.m)
        # warm the re-rank compile buckets before any timed loop
        lsh_index.query_topk(pool[:ref_rows], args.m,
                             probes=probe_counts[0])
        reg = _telemetry.registry()
        lsh_curve = []
        for p in probe_counts:
            gd, gi = lsh_index.query_topk(pool[:ref_rows], args.m,
                                          probes=p)
            hits = 0
            for row_got, row_true in zip(gi, true_i):
                hits += np.intersect1d(row_got, row_true).size
            # per-tile wall split (ISSUE 16): host-probe work (CSR walk
            # + dedup on the host rung; upload prep on the device rung)
            # vs dispatch wall — hist_sum deltas over the timed loop
            h0 = reg.hist_sum("index.lsh.probe.host_s")
            s0 = reg.hist_sum("index.lsh.probe.dispatch_s")
            u0 = reg.hist_quantiles("index.lsh.adaptive.probes_used")
            t0 = time.perf_counter()
            for req in requests:
                lsh_index.query_topk(req, args.m, probes=p)
            elapsed = time.perf_counter() - t0
            point = {
                "probes": p,
                "recall_at_m": round(hits / true_i.size, 4),
                "queries_per_s": round(
                    len(requests) * args.request_rows / elapsed, 1
                ),
                "probe_host_s": round(
                    reg.hist_sum("index.lsh.probe.host_s") - h0, 6
                ),
                "probe_dispatch_s": round(
                    reg.hist_sum("index.lsh.probe.dispatch_s") - s0, 6
                ),
            }
            if args.adaptive:
                u1 = reg.hist_quantiles("index.lsh.adaptive.probes_used")
                if u1 is not None:
                    n0 = u0["count"] if u0 else 0
                    s_0 = u0["sum"] if u0 else 0.0
                    point["probes_used"] = {
                        "count": u1["count"] - n0,
                        "mean": round(
                            (u1["sum"] - s_0)
                            / max(u1["count"] - n0, 1), 3
                        ),
                        # cumulative-histogram estimates (log2 buckets)
                        "p50": u1.get("p50"),
                        "p99": u1.get("p99"),
                    }
            lsh_curve.append(point)
        lsh = {
            "bands": lsh_index.band_plan.bands,
            "band_bits": lsh_index.band_plan.band_bits,
            "fallback_density": lsh_index.fallback_density,
            "probe_path": args.probe_path,
            "probe_path_resolved": (
                "device" if lsh_index._lsh_probe_device(args.probe_path)
                else "host"
            ),
            "adaptive": bool(args.adaptive),
            "candidate_budget": args.candidate_budget or None,
            # interpreter wall-splits are correctness-grade only: never
            # a tripwire baseline (r6–r14 convention)
            "wall_split_suspect": bool(interpret_default()),
            "curve": lsh_curve,
            **{f"lsh_{k}": v for k, v in lsh_index.lsh_stats().items()},
        }

    tiered = None
    if args.hbm_budget:
        import shutil
        import tempfile

        from randomprojection_tpu.ops import topk_kernels
        from randomprojection_tpu.utils import telemetry as _tel

        # same corpus, ingested in 8 chunks so the budget splits it
        # into a real hot/cold set; answers must stay bit-identical to
        # the resident index above (the documented merge order)
        chunk_rows = -(-args.index_codes // 8)
        cold_dir = tempfile.mkdtemp(prefix="rp_tier_bench_") \
            if args.cold_tier == "disk" else None
        t_index = SimHashIndex(
            codes[:0], topk_impl=args.topk_impl,
            hbm_budget_bytes=args.hbm_budget,
            cold_tier=args.cold_tier, cold_dir=cold_dir,
        )
        try:
            for lo in range(0, args.index_codes, chunk_rows):
                t_index.add(codes[lo : lo + chunk_rows])
            rd, ri = index.query_topk(requests[0], args.m)
            td, ti = t_index.query_topk(requests[0], args.m)  # + warm
            parity_ok = bool((td == rd).all() and (ti == ri).all())
            reg = _tel.registry()
            h0 = reg.counter("index.tier.hot_rows")
            c0 = reg.counter("index.tier.cold_rows")
            f0 = reg.counter("index.tier.fetches")
            fb0 = reg.counter("index.tier.fallbacks")
            w0 = reg.hist_sum("index.tier.fetch_s")
            o0 = reg.hist_sum("index.tier.overlap_s")
            t0 = time.perf_counter()
            for req in requests:
                t_index.query_topk(req, args.m)
            t_elapsed = time.perf_counter() - t0
            hot = reg.counter("index.tier.hot_rows") - h0
            cold = reg.counter("index.tier.cold_rows") - c0
            fq = reg.hist_quantiles("index.tier.fetch_s")
            chunk_tiers = [
                c["tier"] for c in t_index._tier.residency()["chunks"]
            ]
            tiered = {
                "hbm_budget_bytes": args.hbm_budget,
                "cold_tier": args.cold_tier,
                "over_budget_factor": round(
                    args.index_codes * args.code_bytes / args.hbm_budget,
                    2,
                ),
                "hot_chunks": sum(
                    1 for t in chunk_tiers if t == "hot"
                ),
                "cold_chunks": sum(
                    1 for t in chunk_tiers if t != "hot"
                ),
                "queries_per_s": round(
                    len(requests) * args.request_rows / t_elapsed, 1
                ),
                "slowdown_vs_direct": round(
                    direct_qps
                    / (len(requests) * args.request_rows / t_elapsed),
                    3,
                ),
                "hot_hit_fraction": (
                    round(hot / (hot + cold), 4) if (hot + cold) else None
                ),
                "cold_fetches": reg.counter("index.tier.fetches") - f0,
                "cold_fetch_wall_s": round(
                    reg.hist_sum("index.tier.fetch_s") - w0, 6
                ),
                "cold_fetch_overlapped_s": round(
                    reg.hist_sum("index.tier.overlap_s") - o0, 6
                ),
                "cold_fetch_p99_s": (
                    round(fq["p99"], 6)
                    if fq and fq.get("p99") is not None else None
                ),
                "fallbacks": reg.counter("index.tier.fallbacks") - fb0,
                "parity_ok": parity_ok,
                "timing_suspect": bool(topk_kernels.interpret_default()),
            }
        finally:
            t_index.close()
            if cold_dir is not None:
                shutil.rmtree(cold_dir, ignore_errors=True)

    print(json.dumps({
        "metric": f"simhash top-k serving queries/s (m={args.m}, "
                  f"{args.index_codes} codes)",
        "index_codes": args.index_codes,
        "code_bytes": args.code_bytes,
        "m": args.m,
        "request_rows": args.request_rows,
        "requests": len(requests),
        "clients": args.clients,
        "topk_impl": index._chunk_impl(
            args.request_rows, index._chunks[0].b.shape[0],
            min(args.m, args.index_codes),
        ),
        "direct_queries_per_s": round(direct_qps, 1),
        "server_queries_per_s": round(server_qps, 1),
        "server_speedup": round(server_qps / direct_qps, 2),
        "server_batch": args.server_batch,
        "server_delay_ms": args.server_delay_ms,
        **{f"server_{k}": v for k, v in server.stats().items()},
        **({"sharded": sharded} if sharded else {}),
        **({"lsh": lsh} if lsh else {}),
        **({"tiered": tiered} if tiered else {}),
    }))
    _write_openmetrics(args)


def cmd_loadgen(args):
    """Open-loop SLO measurement against a ``ShardedTopKServer`` (see
    loadgen.py): deterministic seeded arrival schedule, per-label
    p50/p90/p99/p99.9 tables, printed as the final stdout line (the
    ``topk_slo`` record) and optionally written to ``--out``."""
    from randomprojection_tpu import loadgen
    from randomprojection_tpu.serving import (
        ShardedSimHashIndex,
        ShardedTopKServer,
    )

    def _csv(text, cast, flag):
        try:
            vals = [cast(v.strip()) for v in text.split(",") if v.strip()]
        except ValueError:
            vals = []
        if not vals:
            raise SystemExit(f"{flag} wants a comma list, got {text!r}")
        return vals

    request_rows = _csv(args.request_rows, int, "--request-rows")
    labels = _csv(args.labels, str, "--labels")
    try:
        schedule = loadgen.build_schedule(
            seed=args.seed, duration_s=args.duration, rate_qps=args.rate,
            arrival=args.arrival, request_rows=request_rows,
            labels=labels, burst_factor=args.burst_factor,
            burst_fraction=args.burst_fraction,
            burst_period_s=args.burst_period,
        )
    except ValueError as e:
        raise SystemExit(str(e))
    if not schedule:
        raise SystemExit(
            f"empty schedule: --rate {args.rate} over --duration "
            f"{args.duration}s produced no arrivals — raise one of them"
        )
    rng = np.random.default_rng(args.seed)
    codes = rng.integers(
        0, 256, size=(args.index_codes, args.code_bytes), dtype=np.uint8
    )
    # --probes: a bare int serves every label at that probe count; a
    # 'label=P,...' list sets a PER-LABEL probe policy (ISSUE 16 —
    # mixed quality classes against one serving tier; unlisted labels
    # take the tier default, P=0 pins a label onto the exact path)
    probes_txt = str(args.probes).strip()
    probes_default = 0
    probe_policy = None
    if "=" in probes_txt:
        probe_policy = {}
        for part in probes_txt.split(","):
            part = part.strip()
            if not part:
                continue
            k, eq, v = part.partition("=")
            try:
                if not eq:
                    raise ValueError(part)
                probe_policy[k.strip()] = int(v)
            except ValueError:
                raise SystemExit(
                    f"--probes wants an int or label=P pairs, got "
                    f"{part!r}"
                )
        if not probe_policy or any(
            p < 0 for p in probe_policy.values()
        ):
            raise SystemExit(
                f"--probes label=P pairs want non-negative probe "
                f"counts, got {probes_txt!r}"
            )
    else:
        try:
            probes_default = int(probes_txt or "0")
        except ValueError:
            raise SystemExit(
                f"--probes wants an int or label=P pairs, got "
                f"{probes_txt!r}"
            )
        if probes_default < 0:
            raise SystemExit(
                f"--probes must be >= 0, got {probes_default}"
            )
    if probes_default > 0 or probe_policy is not None:
        # the LSH candidate tier serves: probes is the recall/latency
        # knob the per-label SLO tables then express (ISSUE 15)
        from randomprojection_tpu.ann import LSHShardedSimHashIndex

        lsh_kw = {"probes": probes_default} if probes_default > 0 else {}
        groups = [
            LSHShardedSimHashIndex(
                codes, n_shards=args.shards, topk_impl=args.topk_impl,
                **lsh_kw,
            )
            for _ in range(args.replicas)
        ]
    else:
        groups = [
            ShardedSimHashIndex(
                codes, n_shards=args.shards, topk_impl=args.topk_impl
            )
            for _ in range(args.replicas)
        ]
    server = ShardedTopKServer(
        groups, args.m, max_batch=args.server_batch,
        max_delay_s=args.server_delay_ms / 1e3,
        max_pending=args.max_pending,
        probe_policy=probe_policy,
    )
    try:
        record = loadgen.run(
            server, schedule, code_bytes=args.code_bytes,
            seed=args.seed, warmup_rows=max(request_rows),
            probe_policy=probe_policy,
        )
    finally:
        server.close()
    record.update({
        "seed": args.seed,
        "arrival": args.arrival,
        "rate_qps": args.rate,
        "duration_s": args.duration,
        "request_rows": request_rows,
        "index_codes": args.index_codes,
        "code_bytes": args.code_bytes,
        "m": args.m,
        "shards": args.shards,
        "replicas": args.replicas,
        "probes": probes_default,
        "probe_policy": probe_policy,
    })
    if getattr(args, "health", None) is not None:
        # the SAME spec the live burn-rate detector grades against rides
        # in the record (r20): per-label targets + default, so post-hoc
        # analysis and the live verdicts share one contract
        from randomprojection_tpu.utils import health

        spec = health.parse_slo_spec(args.health)
        record["slo_targets"] = {
            "default_ms": spec["default_ms"],
            "labels": spec["labels"],
            "spec": args.health,
        }
    if args.settle and args.settle > 0:
        # hold the health/metrics planes open through the recovery
        # window before the final-line record is printed
        import time

        time.sleep(args.settle)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f)
    _write_openmetrics(args)
    # the record is the FINAL stdout line (tail-safe, like the bench)
    print(json.dumps(record))


def cmd_stream_bench(args):
    """Host-streamed rows/s: includes h2d (PCIe) — the honest streamed
    number, which SURVEY.md §7 R3 predicts is transfer-bound.  The
    estimator is built by the same ``_make_estimator`` as ``project``, so
    ``--kind``/``--precision``/``--materialization`` select the identical
    execution modes the bench's data-resident numbers use.

    The source is a seeded ``CallableSource`` synthesizing each batch on
    demand from one resident template (deterministic in ``(lo, hi)``, so
    runs are reproducible and resume-exact): host memory stays ~2 batches
    however large ``--rows`` is — ``--rows 10000000`` runs in well under a
    GiB instead of materializing a 156 GiB array (VERDICT r3 weak #6)."""
    import time

    from randomprojection_tpu.streaming import CallableSource
    from randomprojection_tpu.utils.observability import StreamStats, profile_trace

    out_dtype = np.float32
    if getattr(args, "dtype", "float32") == "bfloat16":
        from randomprojection_tpu.utils.validation import bfloat16_dtype

        out_dtype = bfloat16_dtype()
        if out_dtype is None:
            raise SystemExit("--dtype bfloat16 requires ml_dtypes")

    template_rows = min(args.batch_rows, args.rows) or 1
    template = np.random.default_rng(0).standard_normal(
        (template_rows, args.d), dtype=np.float32
    ).astype(out_dtype, copy=False)

    def read(lo, hi):
        # distinct values per batch (a repeated batch could be served from
        # this box's device-side call cache, faking the stream rate) at
        # memcpy cost — not a fresh RNG draw per batch, which would bill
        # ~seconds/GiB of host generation to the streaming number.  A row
        # ROLL (not a scalar add, which quantizes to nothing in bf16 once
        # the offset exceeds the ulp) keeps batches exactly distinct in any
        # dtype until the shift wraps after template_rows batches (~268M
        # rows at the defaults).
        shift = (lo // max(args.batch_rows, 1)) % template_rows
        return np.roll(template, -shift, axis=0)[: hi - lo]

    source = CallableSource(
        read, args.rows, args.d, dtype=out_dtype, batch_rows=args.batch_rows
    )
    args.n_components = args.k
    est = _make_estimator(args).fit_source(source)
    # warmup compile on one batch — NEGATED so its contents never equal any
    # streamed batch (batch 0 is read(0, ..) with shift 0; a warmup bit-equal
    # to it could prime this box's device call cache for the timed stream)
    est.transform(np.negative(template[: min(args.batch_rows, args.rows) or 1]))
    stats = StreamStats()
    _register_metrics_source(stats.registry.snapshot)
    timed_source = _wrap_prefetch(source, est, args, stats)
    t0 = time.perf_counter()
    with profile_trace(args.profile_dir):
        for _ in est.transform_stream(timed_source, stats=stats):
            pass
    elapsed = time.perf_counter() - t0
    out = {
        "metric": f"host-streamed rows/s {args.d}->{args.k} ({args.kind})",
        "value": round(args.rows / elapsed, 1),
        "unit": "rows/s",
        "kind": args.kind,
        "rows": args.rows,
        "batch_rows": args.batch_rows,
        "dtype": str(np.dtype(out_dtype)),
        "backend": args.backend,
        "backend_options": _backend_options(args),
        "bytes_in": stats.bytes_in,
        "elapsed_s": round(elapsed, 4),
        "prefetch_batches": args.prefetch_batches,
        "ingest_workers": args.ingest_workers,
    }
    if stats.stage_wall:
        out["stage_wall_s"] = {
            k_: round(v, 4) for k_, v in sorted(stats.stage_wall.items())
        }
        out["pipeline_overlap_ratio"] = round(stats.overlap_ratio(), 3)
        out["queue_depth_max"] = stats.queue_depth_max
    print(json.dumps(out))
    _write_openmetrics(args, stats.registry.snapshot())


def main(argv=None):
    args = build_parser().parse_args(argv)
    if hasattr(args, "log_level"):
        logging.basicConfig(level=getattr(logging, args.log_level.upper()))
    if getattr(args, "prefetch_batches", 0) < 0:
        raise SystemExit(
            f"--prefetch-batches must be >= 0, got {args.prefetch_batches}"
        )
    if getattr(args, "ingest_workers", 0) < 0:
        raise SystemExit(
            f"--ingest-workers must be >= 0, got {args.ingest_workers}"
        )
    if getattr(args, "hash_threads", None) is not None:
        if args.hash_threads < 1:
            raise SystemExit(
                f"--hash-threads must be >= 1, got {args.hash_threads}"
            )
        # process default for every batch-hash call (the C++ kernel reads
        # RP_HASH_THREADS per call); TokenSource(hash_threads=...) can
        # still override per stream
        import os

        os.environ["RP_HASH_THREADS"] = str(args.hash_threads)
    if getattr(args, "telemetry_jsonl", None):
        # process-wide sink: every instrumented call site (streaming
        # stages, backend dispatches, degraded retries, hash batches,
        # simhash serving) starts appending versioned JSONL events.
        # AFTER flag validation: an invalid invocation must abort without
        # touching (creating or tail-repairing) the event file
        from randomprojection_tpu.utils import telemetry

        telemetry.configure(args.telemetry_jsonl)
    # debug switches (SURVEY.md §6): applied before any jax computation
    if getattr(args, "debug_nans", False):
        import jax

        jax.config.update("jax_debug_nans", True)
    if getattr(args, "disable_jit", False):
        import jax

        jax.config.update("jax_disable_jit", True)
    # health plane (r20): parse the spec and build the (not-yet-
    # subscribed) engine BEFORE any server bind — a malformed spec must
    # abort without leaking a listener or a subscription
    engine = None
    if getattr(args, "health", None) is not None:
        from randomprojection_tpu.utils import health

        try:
            spec = health.parse_slo_spec(args.health)
            engine = health.HealthEngine(slo=spec)
        except ValueError as e:
            raise SystemExit(f"--health: {e}")
    recorder = None
    if getattr(args, "flight_dump", None):
        from randomprojection_tpu.utils import telemetry

        recorder = telemetry.FlightRecorder()
        if engine is not None:
            engine.recorder = recorder  # watchdog trip ⇒ dump
    live = None
    if getattr(args, "metrics_port", None) is not None:
        # live observability plane (r17): a LiveAggregator subscribed to
        # the in-process event stream + an HTTP /metrics endpoint, both
        # for the duration of the command.  The endpoint line goes to
        # STDERR — stdout keeps the bench/loadgen final-line contract.
        if args.metrics_port < 0 or args.metrics_port > 65535:
            raise SystemExit(
                f"--metrics-port must be 0..65535, got {args.metrics_port}"
            )
        from randomprojection_tpu.utils import metrics_server, telemetry

        agg = telemetry.LiveAggregator()
        # bind the port FIRST: MetricsServer is the failure-prone step
        # (address in use), and a subscribe before a failed bind would
        # leak a registered subscription no finally could clean up —
        # keeping telemetry active process-wide for in-process callers
        server = metrics_server.MetricsServer(
            port=args.metrics_port, aggregator=agg, health=engine
        )
        try:
            sub = telemetry.subscribe(agg, maxsize=4096,
                                      name="live-aggregator")
        except BaseException:
            server.close()
            raise
        live = (server, sub)
        global _METRICS_SERVER
        _METRICS_SERVER = server
        print(f"metrics: serving {server.url}", file=sys.stderr)
    rec_sub = None
    if recorder is not None or engine is not None:
        # subscriptions AFTER the bind (same leak argument as above);
        # the recorder installs its signal/excepthook handlers last so
        # a failed subscribe never leaves a handler pointing at a
        # recorder with no event feed
        from randomprojection_tpu.utils import telemetry

        try:
            if recorder is not None:
                rec_sub = telemetry.subscribe(
                    recorder, maxsize=4096, name="flight-recorder"
                )
                recorder.install(args.flight_dump)
            if engine is not None:
                engine.start()
                if recorder is not None:
                    recorder.attach_health(engine.active)
        except BaseException:
            if rec_sub is not None:
                recorder.uninstall()
                telemetry.unsubscribe(rec_sub)
            if live is not None:
                _METRICS_SERVER = None
                live[0].close()
                telemetry.unsubscribe(live[1])
            raise
    try:
        rv = {
            "jl-dim": cmd_jl_dim,
            "info": cmd_info,
            "project": cmd_project,
            "bench": cmd_bench,
            "stream-bench": cmd_stream_bench,
            "topk-bench": cmd_topk_bench,
            "loadgen": cmd_loadgen,
            "recover": cmd_recover,
            "doctor": cmd_doctor,
            "report": cmd_doctor,  # alias
            "lint": cmd_lint,
        }[args.cmd](args)
        # fallback for commands that didn't write their own (e.g. bench);
        # project/stream-bench merge their StreamStats registry in and
        # consume the flag first
        _write_openmetrics(args)
    finally:
        if engine is not None:
            engine.close()
        if recorder is not None:
            from randomprojection_tpu.utils import telemetry

            # an exception unwinding through here dies AFTER this
            # finally restores sys.excepthook — dump now, while the
            # ring is still subscribed, or the crash leaves nothing
            exc = sys.exc_info()[0]
            if exc is not None and not issubclass(
                exc, (SystemExit, KeyboardInterrupt)
            ):
                recorder.dump(reason=f"unhandled_exception:{exc.__name__}")
            recorder.uninstall()
            if rec_sub is not None:
                telemetry.unsubscribe(rec_sub)
        if live is not None:
            from randomprojection_tpu.utils import telemetry

            server, sub = live
            _METRICS_SERVER = None
            server.close()
            telemetry.unsubscribe(sub)
    return rv


if __name__ == "__main__":
    sys.exit(main())
