"""``make shard-smoke``: sharded-tier parity on the virtual CPU mesh.

Asserts, at toy shapes, the acceptance contract of the sharded serving
tier: ``ShardedSimHashIndex.query_topk`` — fused-per-shard AND
scan-pinned — is bit-identical to ``topk_bruteforce`` on the
concatenated corpus, including tombstones spanning shard boundaries
and a global id space offset past int32.  Runs before tier-1 in
``make verify`` so a broken shard/merge/route layer fails fast, on the
same ``--xla_force_host_platform_device_count=8`` topology tier-1
uses (degrades to however many devices the platform exposes — shard
placement round-robins, parity must hold regardless).
"""

from __future__ import annotations

import numpy as np

__all__ = ["main"]


def main() -> None:
    import jax

    from randomprojection_tpu.models import sketch as sk
    from randomprojection_tpu.serving import ShardedSimHashIndex

    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 256, size=(1100, 8), dtype=np.uint8)
    queries = rng.integers(0, 256, size=(24, 8), dtype=np.uint8)
    m = 7

    idx = ShardedSimHashIndex(codes, n_shards=8)
    for s, shard in enumerate(idx._shards):
        impl = shard._chunk_impl(
            queries.shape[0], shard._chunks[0].b.shape[0],
            min(m, shard.n_codes),
        )
        assert impl == "fused", f"shard {s} not on the fused kernel: {impl}"
    d, i = idx.query_topk(queries, m)
    rd, ri = sk.topk_bruteforce(queries, codes, m)
    assert np.array_equal(d, rd), "sharded fused dist != brute force"
    assert np.array_equal(i, ri.astype(np.int64)), (
        "sharded fused ids != brute force"
    )

    scan = ShardedSimHashIndex(codes, n_shards=8, topk_impl="scan")
    ds, js = scan.query_topk(queries, m)
    assert np.array_equal(ds, rd) and np.array_equal(js, i), (
        "sharded scan != fused/brute"
    )

    # tombstones spanning shard boundaries (8 shards of ~137 rows:
    # [200, 420) crosses two boundaries), checked against a masked
    # brute-force reference
    dead = np.arange(200, 420)
    scan.delete(dead)
    D = sk.pairwise_hamming(queries, codes).astype(np.int64)
    D[:, dead] = 8 * 8 + 1
    rdm, rim = sk._host_topk_select(D, m)
    dm, im = scan.query_topk(queries, m)
    assert np.array_equal(dm, rdm) and np.array_equal(im, rim), (
        "cross-shard tombstones break parity"
    )

    # global id space past int32: same distances, ids shifted exactly
    off = 2**31 + 13
    wide = ShardedSimHashIndex(codes, n_shards=8, id_offset=off,
                               topk_impl="scan")
    dw, iw = wide.query_topk(queries, m)
    assert np.array_equal(dw, rd), "id_offset changed distances"
    assert np.array_equal(iw, ri.astype(np.int64) + off), (
        "int64 global ids broke the merge order"
    )

    print(
        f"shard-smoke OK: fused == scan == brute force over 8 shards on "
        f"{n_dev} device(s); cross-shard tombstones + >int32 global ids "
        "bit-identical"
    )


if __name__ == "__main__":
    main()
