"""Replica-aware serving front-end for the sharded tier.

``TopKServer`` (models/sketch.py, r9/r10) already solves coalescing:
concurrent small requests batch into one row-bucketed ``query_topk``
dispatch with bounded delay, bounded queue and drain-on-close.  A
``ShardedSimHashIndex`` plugs straight into it — the micro-batcher only
needs ``query_topk``/``_check_queries`` — but one replica of a sharded
corpus still serializes coalesced batches behind each other.

``ShardedTopKServer`` adds the replica dimension: it holds N replica
groups (each one full copy of the corpus — typically a
``ShardedSimHashIndex`` spanning its own device set, or any index with
the ``query_topk`` surface) and routes each coalesced dispatch to the
next group **round-robin**, so consecutive batches land on disjoint
devices and overlap.  Routing is dispatcher-thread-only — no locks —
and results are replica-invariant by construction (replicas are
validated to agree on corpus shape at construction; serving identical
corpora is the operator's contract, exactly as "don't mutate a served
index" already is).

Telemetry: every routed dispatch emits ``serve.shard.batch`` (replica,
shard fanout, rows, wall) and bumps the ``serve.shard.*`` counters the
doctor's serving section reads, alongside the base server's
``serve.topk.*`` accounting.  Per-request tail latency (r17) rides the
base class: requests are stamped enqueue→dispatch→complete into the
``serve.latency.sharded`` histograms (``name=`` overrides the key) and
per client label — see ``TopKServer``.
"""

from __future__ import annotations

import threading

from randomprojection_tpu.models.sketch import TopKServer
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = ["ShardedTopKServer"]


class ShardedTopKServer(TopKServer):
    """Micro-batching top-k server with round-robin replica routing
    (see module docstring).  ``replicas`` is one index or a sequence of
    replica indexes; everything else matches ``TopKServer``."""

    def __init__(self, replicas, m: int, *, max_batch: int = 8192,
                 max_delay_s: float = 0.002, max_pending: int = 8192,
                 name: str = "sharded", probe_policy=None,
                 start: bool = True):
        if not isinstance(replicas, (list, tuple)):
            replicas = [replicas]
        replicas = list(replicas)
        if not replicas:
            raise ValueError("ShardedTopKServer needs at least one replica")
        first = replicas[0]
        for r, rep in enumerate(replicas[1:], start=1):
            if (
                rep.n_bytes != first.n_bytes
                or rep.n_bits != first.n_bits
                or rep.n_codes != first.n_codes
                or rep.n_live != first.n_live
            ):
                raise ValueError(
                    f"replica {r} disagrees with replica 0 on corpus "
                    f"shape (n_bytes {rep.n_bytes} vs {first.n_bytes}, "
                    f"n_bits {rep.n_bits} vs {first.n_bits}, "
                    f"n_codes {rep.n_codes} vs {first.n_codes}, n_live "
                    f"{rep.n_live} vs {first.n_live}): replicas must "
                    "serve identical corpora or results become "
                    "routing-dependent"
                )
        if probe_policy is not None:
            # the policy routes through whichever replica round-robin
            # picks, so EVERY replica must carry the probes kwarg —
            # the base constructor only sees replica 0
            for r, rep in enumerate(replicas):
                if not hasattr(rep, "probes"):
                    raise ValueError(
                        f"probe_policy requires LSH-tier replicas (its "
                        f"query_topk must accept probes=); replica {r} "
                        f"is {type(rep).__name__}"
                    )
        self.replicas = replicas
        self._rr = 0  # dispatcher-thread-private round-robin cursor
        # the per-replica tallies cross threads (dispatcher writes,
        # stats() reads) — the one piece of routing state that needs a
        # lock (RP10); _rr/_picked stay dispatcher-private, lock-free
        self._replica_batches = [0] * len(replicas)
        self._route_lock = threading.Lock()
        super().__init__(
            first, m, max_batch=max_batch, max_delay_s=max_delay_s,
            max_pending=max_pending, name=name,
            probe_policy=probe_policy, start=start,
        )

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    def _pick_index(self):
        r = self._rr % len(self.replicas)
        self._rr += 1
        self._picked = r
        return self.replicas[r]

    def _batch_served(self, index, rows: int, padded: int,
                      requests: int, wall: float) -> None:
        r = self._picked
        with self._route_lock:
            self._replica_batches[r] += 1
        reg = telemetry.registry()
        reg.counter_inc("serve.shard.batches")
        reg.counter_inc("serve.shard.requests", requests)
        reg.counter_inc("serve.shard.queries", rows)
        reg.counter_inc(f"serve.shard.replica.{r}.batches")
        reg.gauge_set("serve.shard.replicas", len(self.replicas))
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.SERVE_SHARD_BATCH, replica=r,
                shards=int(getattr(index, "n_shards", 1)),
                rows=int(rows), padded=int(padded),
                requests=int(requests), m=int(self.m),
                wall_s=round(wall, 6),
            )

    def stats(self) -> dict:
        """Base coalescing tallies plus the replica routing spread."""
        s = super().stats()
        s["replicas"] = len(self.replicas)
        with self._route_lock:
            s["replica_batches"] = list(self._replica_batches)
        return s
