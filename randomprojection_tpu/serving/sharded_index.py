"""Mesh-spanning SimHash index: per-shard top-k, one cross-shard merge.

``SimHashIndex`` (models/sketch.py) is one device's worth of serving:
its codes live on a single device (or behind one ``shard_map`` program
on the retained scan path) and its ids are int32 end to end, so it
refuses growth past ``2**31 - 1`` codes.  ``ShardedSimHashIndex`` is
the tier above it — the BL:10 shape (1B codes row-sharded over 8
chips) as an object:

- **Row sharding, per-device.**  The corpus row-shards over a set of
  shard devices (a ``jax.sharding.Mesh``'s ``data_axis``, an explicit
  device list, or ``n_shards`` over the local platform).  Each shard is
  a complete single-device ``SimHashIndex`` pinned to its device
  (``device=``), which is exactly what lets every shard serve through
  the r12 **fused Pallas kernel**: the fused path is single-device by
  construction, so the one-``shard_map``-program alternative would pin
  the whole mesh to the retained ``lax.scan`` leg.  Per-shard dispatch
  also keeps the whole degraded ladder intact per shard — fused →
  VMEM-OOM scan retry → minimal-VMEM tiling → dense host fallback —
  and runs on any jax version (no ``shard_map`` requirement; the
  virtual 8-device CPU mesh tier-1 uses exercises the real code).
- **Global-int64 / local-int32 id space.**  Global ids are assigned in
  insertion order across the corpus and surface as int64; each shard
  keeps int32 locals for its kernels, and the old ``2**31 - 1`` refusal
  becomes a per-shard invariant (the shard names itself in the error).
  ``id_offset`` starts the global id space anywhere in int64 — serving
  stacks that partition one corpus namespace across tiers, and the
  tier-1 proof that ids beyond int32 merge correctly without a
  2-billion-row fixture.
- **One cross-shard merge.**  A query tile fans out to every shard
  (dispatch is async — all shards compute concurrently), each returns
  its top-``min(m, shard live)`` candidates, and ONE host merge under
  the documented (distance, lower-global-id) total order finishes the
  tile — bit-identical to ``topk_bruteforce`` on the concatenated
  corpus, because a per-shard top-m under that order contains every
  global top-m element of its shard.  The merge is an exact
  ``np.lexsort`` (row, distance, global id), so it cannot overflow no
  matter how wide the id space gets.

Tombstones (``delete``) take global ids, translate through the segment
map, and land in each shard's bitmap — the per-shard kernels filter
them inside selection, so a tombstone spanning shard boundaries
behaves exactly like the single-device one.  Durable snapshots
(``save``/``load``) spill the corpus in global id order, which makes
the format **mesh-agnostic**: a snapshot saved under one mesh shape
restores under any other shard count — or as a plain single-device
``SimHashIndex`` — with bit-identical query results (see
``durable.save_sharded_index``).

Thread-safety matches ``SimHashIndex``: concurrent queries are fine,
mutation (``add``/``delete``/``compact``) requires quiescence.
"""

from __future__ import annotations

import numbers
import threading
import time
from typing import Optional

import numpy as np

from randomprojection_tpu.models.sketch import SimHashIndex
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = ["ShardedSimHashIndex", "shard_devices"]


def shard_devices(mesh=None, devices=None, n_shards: Optional[int] = None,
                  data_axis: str = "data") -> list:
    """Resolve the shard device list: one device per ``data_axis`` index
    of ``mesh``, an explicit ``devices`` sequence, or ``n_shards`` over
    the local platform (round-robin when shards outnumber devices —
    several shards per device is legal, it just serializes their
    compute).  With nothing given, one shard per local device.

    ``mesh`` fixes the layout by itself, so combining it with
    ``devices=`` or ``n_shards=`` is a conflict and raises (silently
    dropping an explicit count would hand back a layout the caller
    did not ask for); ``devices`` + ``n_shards`` together is the
    documented round-robin form."""
    if mesh is not None and (devices is not None or n_shards is not None):
        raise ValueError(
            "mesh= already fixes the shard layout (one shard per "
            f"{data_axis!r}-axis index); it cannot be combined with "
            "devices= or n_shards="
        )
    if devices is not None:
        devices = list(devices)
        if not devices:
            raise ValueError("devices= must name at least one device")
        if n_shards is None:
            return devices
        return [devices[i % len(devices)] for i in range(int(n_shards))]
    if mesh is not None:
        names = list(mesh.axis_names)
        if data_axis not in names:
            raise ValueError(
                f"mesh has axes {names}, no {data_axis!r} axis to shard "
                "rows over"
            )
        arr = np.asarray(mesh.devices)
        arr = np.moveaxis(arr, names.index(data_axis), 0)
        arr = arr.reshape(arr.shape[0], -1)
        # one shard per data-axis index; when the mesh also has other
        # axes (e.g. 'feature'), the shard lives on the first device of
        # its data-axis slice
        return [row[0] for row in arr]
    import jax

    local = list(jax.devices())
    if n_shards is None:
        return local
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return [local[i % len(local)] for i in range(int(n_shards))]


class _Segment:
    """One contiguous run of global ids living contiguously in one
    shard: global ids ``[g0, g0 + rows)`` are shard ``shard``'s local
    ids ``[l0, l0 + rows)``.  Segments tile ``[0, n_codes)`` in order
    and correspond 1:1 (per shard, in order) to the shard's resident
    chunks — every ``add`` appends at most one chunk AND one segment
    per shard, and nothing else ever touches a shard's chunk list."""

    __slots__ = ("g0", "rows", "shard", "l0")

    def __init__(self, g0: int, rows: int, shard: int, l0: int):
        self.g0 = g0
        self.rows = rows
        self.shard = shard
        self.l0 = l0


class ShardedSimHashIndex:
    """A SimHash code index row-sharded over many devices (see module
    docstring).  API mirrors ``SimHashIndex`` with ids widened to
    int64: ``query_topk`` returns ``(dist int32, idx int64)``,
    ``delete``/``compact`` speak global int64 ids, ``query`` returns
    the dense matrix with columns in global id order."""

    def __init__(self, codes, *, mesh=None, devices=None,
                 n_shards: Optional[int] = None, data_axis: str = "data",
                 n_bits: Optional[int] = None, topk_impl: str = "auto",
                 id_offset: int = 0,
                 hbm_budget_bytes: Optional[int] = None,
                 cold_tier: str = "host", cold_dir: Optional[str] = None):
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2:
            raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
        if not isinstance(id_offset, numbers.Integral) or id_offset < 0:
            raise ValueError(
                f"id_offset must be a non-negative int, got {id_offset!r}"
            )
        self.n_bytes = codes.shape[1]
        self.n_bits = self.n_bytes * 8 if n_bits is None else int(n_bits)
        if not 0 < self.n_bits <= self.n_bytes * 8:
            raise ValueError(
                f"n_bits={self.n_bits} outside (0, {self.n_bytes * 8}]"
            )
        self.id_offset = int(id_offset)
        self.topk_impl = topk_impl
        self.data_axis = data_axis
        # tiered residency (ISSUE 19 / r21): the budget is PER SHARD —
        # each shard tiers its own device's HBM independently, so the
        # aggregate hot capacity scales with the device count while the
        # knob stays one number per device, matching how HBM is owned
        self.hbm_budget_bytes = hbm_budget_bytes
        self.cold_tier = cold_tier
        self.cold_dir = cold_dir
        self._devices = shard_devices(mesh, devices, n_shards, data_axis)
        self._shards = [
            self._make_shard(s, dev)
            for s, dev in enumerate(self._devices)
        ]
        self._segments: list = []
        self._shard_seg_cache: dict = {}
        self.n_codes = 0
        self._merges = 0
        self._merge_wall_s = 0.0
        # merge tallies are the one piece of state concurrent queries
        # share; everything else in query_topk is per-call
        self._merge_stats_lock = threading.Lock()
        if codes.shape[0]:
            self.add(codes)

    def _make_shard(self, s: int, dev) -> SimHashIndex:
        """One empty per-device shard — the single construction point
        (``__init__`` and ``compact()``'s re-balance both come through
        here), and the serving hook the multi-probe LSH tier overrides:
        ``ann.LSHShardedSimHashIndex`` returns shards that carry their
        own banded bucket indexes, everything else identical."""
        return SimHashIndex(
            np.empty((0, self.n_bytes), np.uint8),
            n_bits=self.n_bits, topk_impl=self.topk_impl, device=dev,
            label=f"shard {s}/{len(self._devices)} on {dev}",
            **self._tier_kwargs(s),
        )

    def _tier_kwargs(self, s: int) -> dict:
        """Per-shard tiered-residency kwargs (empty dict when untiered):
        a disk cold tier gets a per-shard spill subdirectory so shards
        never collide on generation/sequence file names."""
        if self.hbm_budget_bytes is None:
            return {}
        cold_dir = self.cold_dir
        if cold_dir is not None:
            import os

            cold_dir = os.path.join(cold_dir, f"shard-{s:02d}")
        return {
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "cold_tier": self.cold_tier, "cold_dir": cold_dir,
        }

    def close(self) -> None:
        """Close every shard's tiered-residency worker (no-op when
        untiered, idempotent)."""
        for s in self._shards:
            s.close()

    # -- shape/accounting ----------------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    @property
    def devices(self) -> list:
        return list(self._devices)

    @property
    def n_deleted(self) -> int:
        return sum(s.n_deleted for s in self._shards)

    @property
    def n_live(self) -> int:
        return self.n_codes - self.n_deleted

    def stats(self) -> dict:
        """Sharded-tier tallies: per-shard row/live counts, cross-shard
        merge count and accumulated merge wall (the host-side cost the
        tier adds on top of the per-shard kernels)."""
        with self._merge_stats_lock:
            merges, merge_wall = self._merges, self._merge_wall_s
        return {
            "shards": self.n_shards,
            "n_codes": int(self.n_codes),
            "n_live": int(self.n_live),
            "shard_rows": [int(s.n_codes) for s in self._shards],
            "shard_live": [int(s.n_live) for s in self._shards],
            "merges": merges,
            "merge_wall_s": round(merge_wall, 6),
        }

    def _check_queries(self, A):
        A = np.asarray(A, dtype=np.uint8)
        if A.ndim != 2 or A.shape[1] != self.n_bytes:
            raise ValueError(
                f"queries must be (n, {self.n_bytes}), got {A.shape}"
            )
        return A

    # -- growth --------------------------------------------------------------

    def _split_for_add(self, n_new: int) -> list:
        """Row counts each shard receives from an ``n_new``-row append,
        filling the emptiest shards first so shard sizes stay balanced
        (to ±1 once every shard has caught up) without ever moving
        resident rows."""
        p = self.n_shards
        sizes = [s.n_codes for s in self._shards]
        total = self.n_codes + n_new
        base, rem = divmod(total, p)
        targets = [base + (1 if s < rem else 0) for s in range(p)]
        counts = [0] * p
        remaining = n_new
        for s in range(p):
            take = min(max(targets[s] - sizes[s], 0), remaining)
            counts[s] = take
            remaining -= take
        # shards already past their target absorb nothing; any residue
        # (only possible when every deficit is filled) round-robins
        s = 0
        while remaining > 0:  # pragma: no cover — deficits always cover
            counts[s % p] += 1
            remaining -= 1
            s += 1
        return counts

    def add(self, codes) -> "ShardedSimHashIndex":
        """Append codes: global ids continue in insertion order, rows
        split contiguously across shards balancing shard sizes.  Ships
        only the new rows (one new chunk per receiving shard)."""
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2 or codes.shape[1] != self.n_bytes:
            raise ValueError(
                f"codes must be (n, {self.n_bytes}), got {codes.shape}"
            )
        n = codes.shape[0]
        if n == 0:
            return self
        counts = self._split_for_add(n)
        lo = 0
        g = self.n_codes
        for s, c in enumerate(counts):
            if c == 0:
                continue
            shard = self._shards[s]
            l0 = shard.n_codes
            shard.add(codes[lo : lo + c])
            self._segments.append(_Segment(g, c, s, l0))
            lo += c
            g += c
        self.n_codes += n
        self._shard_seg_cache.clear()
        return self

    # -- id translation ------------------------------------------------------

    def _seg_arrays(self):
        """``(g0s, rows, shards, l0s)`` int64 arrays over the segments
        in global id order — the searchsorted tables for global→local
        translation."""
        cached = self._shard_seg_cache.get("global")
        if cached is None:
            cached = (
                np.array([s.g0 for s in self._segments], dtype=np.int64),
                np.array([s.rows for s in self._segments], dtype=np.int64),
                np.array([s.shard for s in self._segments], dtype=np.int64),
                np.array([s.l0 for s in self._segments], dtype=np.int64),
            )
            self._shard_seg_cache["global"] = cached
        return cached

    def _shard_tables(self, si: int):
        """``(l0s, g0s)`` for shard ``si``'s segments sorted by local
        start — the local→global translation table."""
        cached = self._shard_seg_cache.get(si)
        if cached is None:
            segs = sorted(
                (s for s in self._segments if s.shard == si),
                key=lambda s: s.l0,
            )
            cached = (
                np.array([s.l0 for s in segs], dtype=np.int64),
                np.array([s.g0 for s in segs], dtype=np.int64),
            )
            self._shard_seg_cache[si] = cached
        return cached

    def _local_to_global(self, si: int, local_ids: np.ndarray) -> np.ndarray:
        """Shard-local int32 ids → 0-based global int64 ids (the
        ``id_offset`` shift happens at the API boundary)."""
        l0s, g0s = self._shard_tables(si)
        li = local_ids.astype(np.int64)
        k = np.searchsorted(l0s, li, side="right") - 1
        return g0s[k] + (li - l0s[k])

    def _shard_gids(self, si: int) -> np.ndarray:
        """0-based global ids of shard ``si``'s locals ``0..n_s-1``."""
        return self._local_to_global(
            si, np.arange(self._shards[si].n_codes, dtype=np.int64)
        )

    # -- mutation ------------------------------------------------------------

    def delete(self, ids) -> int:
        """Tombstone codes by GLOBAL id (int64, ``id_offset`` included);
        returns how many were newly deleted.  Ids translate through the
        segment map into each owning shard's bitmap, so a deleted range
        spanning shard boundaries filters exactly like the
        single-device case — inside every shard's top-k selection."""
        ids = np.atleast_1d(np.asarray(ids))
        if ids.size == 0:
            return 0
        if not np.issubdtype(ids.dtype, np.integer):
            raise ValueError(
                f"delete ids must be integers, got dtype {ids.dtype}"
            )
        ids0 = np.unique(ids.astype(np.int64)) - self.id_offset
        lo, hi = int(ids0.min()), int(ids0.max())
        if lo < 0 or hi >= self.n_codes:
            raise ValueError(
                f"delete ids must be in [{self.id_offset}, "
                f"{self.id_offset + self.n_codes}), got "
                f"[{lo + self.id_offset}, {hi + self.id_offset}]"
            )
        g0s, _rows, shards, l0s = self._seg_arrays()
        k = np.searchsorted(g0s, ids0, side="right") - 1
        local = l0s[k] + (ids0 - g0s[k])
        owner = shards[k]
        newly = 0
        for si in np.unique(owner):
            newly += self._shards[int(si)].delete(local[owner == si])
        return newly

    def _iter_segment_host(self):
        """Yield ``(global_row0, host_rows)`` per segment in global id
        order — cold maintenance paths only (compact, snapshot).  One
        segment is one shard chunk, so the snapshot writer streams the
        corpus without ever holding it whole."""
        seen: dict = {}
        for seg in self._segments:
            shard = self._shards[seg.shard]
            j = seen.get(seg.shard, 0)
            seen[seg.shard] = j + 1
            chunk = shard._chunks[j]
            if chunk.n != seg.rows or chunk.row0 != seg.l0:
                raise RuntimeError(
                    "segment/chunk map out of step (internal invariant: "
                    "every add appends one chunk and one segment per "
                    f"shard) at shard {seg.shard} chunk {j}"
                )
            yield seg.g0, shard._fetch_chunk_host(chunk)

    def _codes_host(self) -> np.ndarray:
        """The whole corpus on host in global id order."""
        parts = [rows for _, rows in self._iter_segment_host()]
        if not parts:
            return np.empty((0, self.n_bytes), np.uint8)
        return np.concatenate(parts, axis=0)

    def _dead_global(self) -> Optional[np.ndarray]:
        """The global tombstone bitmap in id order (None when nothing
        is deleted)."""
        if self.n_deleted == 0:
            return None
        dead = np.zeros(self.n_codes, dtype=bool)
        for seg in self._segments:
            sl = self._shards[seg.shard]._dead
            if sl is not None:
                dead[seg.g0 : seg.g0 + seg.rows] = sl[
                    seg.l0 : seg.l0 + seg.rows
                ]
        return dead

    def compact(self) -> np.ndarray:
        """Fold tombstones and re-balance: the live corpus re-shards
        into one chunk per shard; returns the old GLOBAL ids (int64,
        ``id_offset`` included) of the survivors in their new id order.
        Host rebuild — a maintenance operation, requires quiescence."""
        codes = self._codes_host()
        dead = self._dead_global()
        if dead is not None:
            mapping = np.flatnonzero(~dead).astype(np.int64)
            codes = codes[~dead]
        else:
            mapping = np.arange(self.n_codes, dtype=np.int64)
        old_n = self.n_codes
        chunks_before = sum(len(s._chunks) for s in self._shards)
        self._shards = [
            self._make_shard(s, dev)
            for s, dev in enumerate(self._devices)
        ]
        self._segments = []
        self._shard_seg_cache.clear()
        self.n_codes = 0
        if codes.shape[0]:
            self.add(codes)
        telemetry.registry().counter_inc("simhash.compactions")
        telemetry.emit(
            EVENTS.INDEX_COMPACT, chunks_before=chunks_before,
            chunks_after=sum(len(s._chunks) for s in self._shards),
            n_codes=int(self.n_codes),
            dropped=int(old_n - self.n_codes),
        )
        return mapping + self.id_offset

    # -- durable snapshot/restore (see durable.py) ---------------------------

    def save(self, path: str) -> dict:
        """Durable, MESH-AGNOSTIC snapshot: per-segment spills in global
        id order + one atomic checksummed manifest — loadable under any
        shard count (``ShardedSimHashIndex.load``) or, when
        ``id_offset`` is 0, as a plain single-device ``SimHashIndex``
        (``durable.load_index``)."""
        from randomprojection_tpu import durable

        return durable.save_sharded_index(self, path)

    @classmethod
    def load(cls, path: str, *, mesh=None, devices=None,
             n_shards: Optional[int] = None, data_axis: str = "data",
             topk_impl: str = "auto"):
        """Restore a snapshot (sharded or plain) onto ANY shard layout:
        checksums verify before upload, codes re-shard balanced over the
        new devices, tombstones re-arm — query results are bit-identical
        across layouts because global ids and the merge order are layout
        -independent."""
        from randomprojection_tpu import durable

        return durable.load_sharded_index(
            path, mesh=mesh, devices=devices, n_shards=n_shards,
            data_axis=data_axis, topk_impl=topk_impl,
        )

    # -- dense analysis surface ----------------------------------------------

    def query(self, A, *, tile: int = 2048):
        """Dense Hamming distances ``(n_queries, n_codes)`` with columns
        in GLOBAL id order (column ``j`` is global id
        ``id_offset + j``).  Analysis-scale only, like the single-device
        ``query``; shards serve serially here — the serving path is
        ``query_topk``."""
        A = self._check_queries(A)
        out = np.empty((A.shape[0], self.n_codes), dtype=np.int32)
        for si, shard in enumerate(self._shards):
            if shard.n_codes == 0:
                continue
            out[:, self._shard_gids(si)] = shard.query(A, tile=tile)
        return out

    def query_cosine(self, A, *, tile: int = 2048):
        """SimHash cosine estimates against the sharded corpus."""
        from randomprojection_tpu.models.sketch import cosine_from_hamming

        return cosine_from_hamming(self.query(A, tile=tile), self.n_bits)

    # -- the serving path ----------------------------------------------------

    def _merge_tile(self, d_parts: list, g_parts: list, m_eff: int):
        """THE cross-shard candidate merge: concatenate per-shard
        ``(dist, 0-based global id)`` candidate columns and select the
        top ``m_eff`` per row under the exact (row, distance,
        lower-global-id) order via one stable ``np.lexsort`` — immune
        to key-packing overflow however wide the int64 id space is.
        Returns ``(dist, idx)`` with ``idx`` already ``id_offset``
        -shifted.  Shared by the exact fan-out path and the multi-probe
        LSH tier (``ann.LSHShardedSimHashIndex``), so the documented
        merge order cannot drift between them; also owns the merge
        tallies and the ``shard.merge`` telemetry."""
        t0 = time.perf_counter()
        D = np.concatenate(d_parts, axis=1)
        G = np.concatenate(g_parts, axis=1)
        t, k = D.shape
        order = np.lexsort(
            (G.ravel(), D.ravel(), np.repeat(np.arange(t), k))
        )
        sel = order.reshape(t, k)[:, :m_eff]
        out_d = D.ravel()[sel]
        out_i = G.ravel()[sel] + self.id_offset
        wall = time.perf_counter() - t0
        with self._merge_stats_lock:
            self._merges += 1
            self._merge_wall_s += wall
        # live plane (r17): the per-merge wall as a registry gauge
        # (last/mean/max) so a scrape sees cross-shard merge cost
        # without replaying the event log
        telemetry.registry().gauge_set(
            "serve.shard.merge_wall_s", wall
        )
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.SHARD_MERGE, queries=int(t), candidates=int(k),
                shards=len(d_parts), m=int(m_eff),
                wall_s=round(wall, 6), **telemetry.trace_fields(),
            )
        return out_d, out_i

    def query_topk(self, A, m: int, *, tile: int = 2048):
        """Top-``m`` nearest codes per query across every shard.

        Returns ``(dist, idx)``: ``dist`` ``(n_queries, m_eff)`` int32,
        ``idx`` ``(n_queries, m_eff)`` **int64 global ids**
        (``id_offset`` included), ``m_eff = min(m, n_live)``, sorted by
        (distance, lower global id) — bit-identical to
        ``topk_bruteforce`` on the concatenated corpus (ids shifted by
        ``id_offset``), for any shard count, chunk layout or tiling.

        Per tile: the query rows fan out to all live shards FIRST (one
        async dispatch chain per shard — every device computes
        concurrently; each shard runs its own fused/scan/dense ladder),
        then one host merge of the ``Σ min(m_eff, live_s)`` candidates
        finishes the tile.  d2h per query is ``O(p·m)``, never
        ``O(n_codes)``.  Tiles overlap one behind, so tile ``i``'s d2h
        + merge ride under tile ``i+1``'s device compute."""
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        m_eff = int(min(m, self.n_live))
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int64)
        pending: list = []  # [(lo, hi, [(shard_idx, kind, payload, m_s)])]

        def finish(entry):
            lo, hi, per_shard = entry
            d_parts, g_parts = [], []
            for si, kind, payload, m_s in per_shard:
                if kind == "handles":
                    d_s, li_s = self._shards[si]._topk_finish_tile(
                        payload, m_s
                    )
                else:  # 'done': the shard's host-scale dense leg
                    d_s, li_s = payload
                d_parts.append(d_s)
                g_parts.append(self._local_to_global(si, li_s))
            out_d[lo:hi], out_i[lo:hi] = self._merge_tile(
                d_parts, g_parts, m_eff
            )

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            tile_a = A[lo:hi]
            per_shard = []
            for si, shard in enumerate(self._shards):
                if shard.n_live == 0:
                    continue  # empty or fully-tombstoned shard
                m_s = int(min(m_eff, shard.n_live))
                if shard._topk_route(tile_a.shape[0], m_s) == "dense":
                    # a shard whose request shape only the host can
                    # represent serves its dense leg synchronously —
                    # rare (host-scale m / >2^24-bit codes), and the
                    # merge below treats it like any other shard
                    per_shard.append(
                        (si, "done",
                         shard.query_topk(tile_a, m_s, tile=tile), m_s)
                    )
                else:
                    per_shard.append(
                        (si, "handles",
                         shard._topk_dispatch_tile(tile_a, m_s), m_s)
                    )
            telemetry.registry().counter_inc(
                "shard.dispatches", len(per_shard)
            )
            if telemetry.enabled():
                telemetry.emit(
                    EVENTS.SHARD_TOPK_TILE, queries=int(hi - lo),
                    m=int(m_eff), shards=len(per_shard),
                    n_codes=int(self.n_codes),
                    **telemetry.trace_fields(),
                )
            pending.append((lo, hi, per_shard))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i
