"""Sharded serving tier (ISSUE 8; ROADMAP open item 2).

The layer between the fused top-k kernel (``ops/topk_kernels.py``) and
the micro-batching server: ``ShardedSimHashIndex`` row-shards a SimHash
corpus over many devices with a global-int64 / local-int32 id space and
one cross-shard merge per query tile; ``ShardedTopKServer`` routes
coalesced request batches round-robin across replica groups.  See
``sharded_index.py`` for the id-space and merge-order arguments, and
docs/ARCHITECTURE.md "Sharded serving tier".
"""

from randomprojection_tpu.serving.server import ShardedTopKServer
from randomprojection_tpu.serving.sharded_index import (
    ShardedSimHashIndex,
    shard_devices,
)

__all__ = ["ShardedSimHashIndex", "ShardedTopKServer", "shard_devices"]
