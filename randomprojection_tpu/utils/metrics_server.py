"""Scrapeable live-metrics HTTP endpoint (ISSUE r17, tentpole layer 3).

``to_openmetrics`` (utils/telemetry.py) has rendered registry snapshots
as OpenMetrics text since r8 — but only to a file, after the run.  This
module puts the same exposition behind a real ``GET /metrics`` endpoint
served WHILE the process runs, so the live plane closes end to end:

- **MetricsServer** — a stdlib ``http.server`` bound to
  ``host:port`` (``port=0`` = ephemeral, read ``.port`` back) serving
  the merged exposition of: the process-wide default registry, every
  snapshot source registered via ``add_source`` (per-stream
  ``StreamStats`` registries), and — when an ``aggregator``
  (``telemetry.LiveAggregator``) is attached — the rolling-window
  span/queue gauges.  Runs on one background daemon thread
  (``ThreadingHTTPServer``, so a slow scraper cannot wedge the next
  one); ``close()`` shuts the listener down cleanly and joins the
  thread.  Serving is read-only and best-effort by design: a scrape
  failure never propagates into the serving process.
- **fetch_metrics / parse_openmetrics** — the scrape client half
  (``cli doctor --live`` uses it): fetch the text over HTTP and parse
  it back into ``{metric_name: value}`` /
  ``{metric_name: {label_sig: value}}`` dicts.
- **render_live** — the refreshing terminal view ``doctor --live``
  prints: queue depths, live per-stage walls, serve-latency quantiles,
  and degraded-event RATES (counter deltas between polls).

The CLI flag ``--metrics-port PORT`` (project / stream-bench /
topk-bench / loadgen) starts a ``MetricsServer`` with a subscribed
``LiveAggregator`` for the duration of the command.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional, Tuple

from randomprojection_tpu.utils import telemetry

__all__ = [
    "MetricsServer",
    "fetch_metrics",
    "parse_openmetrics",
    "render_live",
]


class MetricsServer:
    """Background HTTP server exposing ``GET /metrics`` (and ``/``) as
    an OpenMetrics text exposition of the process registry + registered
    sources + the live aggregator window (see module docstring).

    ``sources`` / ``add_source`` take zero-arg callables returning
    ``MetricsRegistry.snapshot()``-shaped dicts, evaluated at scrape
    time — a source that raises is skipped for that scrape (the
    endpoint must keep answering while a stream is tearing down).

    With a ``health`` engine attached (anything exposing ``ok()`` and
    ``active()`` — ``health.HealthEngine``), the server also answers
    ``GET /health`` (r20): 200 while ``ok()``, 503 while any critical
    detector fires, JSON body listing the active verdicts either way —
    the ops-probe surface a load balancer or systemd watchdog polls.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 aggregator=None, sources=None, health=None,
                 start: bool = True):
        self.host = host
        self._requested_port = int(port)
        self.aggregator = aggregator
        self.health = health
        self._lock = threading.Lock()
        self._sources: List[Callable[[], dict]] = list(sources or ())
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- exposition ----------------------------------------------------------

    def add_source(self, fn: Callable[[], dict]) -> None:
        """Register an extra snapshot source (e.g. a ``StreamStats``
        registry's ``.snapshot`` bound method) for every future
        scrape."""
        with self._lock:
            self._sources.append(fn)

    def remove_source(self, fn: Callable[[], dict]) -> None:
        with self._lock:
            try:
                self._sources.remove(fn)
            except ValueError:
                pass

    def exposition(self) -> str:
        """The OpenMetrics text a scrape returns right now."""
        with self._lock:
            sources = list(self._sources)
        snaps = [telemetry.registry().snapshot()]
        for fn in sources:
            try:
                snaps.append(fn())
            except Exception:
                # a torn-down stream's source must not kill the scrape;
                # count it so a permanently-broken source is visible
                telemetry.registry().counter_inc(
                    "metrics.server.source_errors"
                )
        agg = self.aggregator
        if agg is not None:
            try:
                snaps.append(agg.registry_snapshot())
            except Exception:
                telemetry.registry().counter_inc(
                    "metrics.server.source_errors"
                )
        return telemetry.to_openmetrics(*snaps)

    def health_response(self) -> Tuple[int, str]:
        """``(status, json_body)`` for ``GET /health``: 503 while any
        critical detector fires, 200 otherwise.  With no engine
        attached the endpoint stays honest — 200, ``attached: false``
        (the probe learns the plane is up but ungraded)."""
        eng = self.health
        if eng is None:
            return 200, json.dumps(
                {"ok": True, "attached": False, "active": []}
            )
        ok = bool(eng.ok())
        return (200 if ok else 503), json.dumps(
            {"ok": ok, "attached": True, "active": eng.active()},
            sort_keys=True, default=str,
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MetricsServer":
        if self._thread is not None:
            raise RuntimeError("MetricsServer already started")
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path == "/health":
                    try:
                        status, body_text = server.health_response()
                    except Exception:
                        # an engine mid-teardown must not kill the
                        # probe; 500 = plane up, grading broken
                        telemetry.registry().counter_inc(
                            "metrics.server.render_errors"
                        )
                        self.send_response(500)
                        self.end_headers()
                        return
                    body = body_text.encode("utf-8")
                    self.send_response(status)
                    self.send_header(
                        "Content-Type", "application/json; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = server.exposition().encode("utf-8")
                except Exception:
                    # the scrape must answer SOMETHING; a 500 tells the
                    # poller the plane is up but the render broke
                    telemetry.registry().counter_inc(
                        "metrics.server.render_errors"
                    )
                    self.send_response(500)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        # connection handler threads must not pin a dying process
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="rp-metrics-server", daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        """The BOUND port (meaningful after ``start`` — with
        ``port=0`` this is the ephemeral port the OS picked)."""
        if self._httpd is None:
            return self._requested_port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        """Stop the listener and join the serving thread.  Idempotent."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- scrape client (doctor --live) -------------------------------------------


def fetch_metrics(host: str, port: int, timeout: float = 5.0) -> str:
    """One HTTP scrape of ``http://host:port/metrics``; returns the raw
    exposition text (raises ``OSError``/``urllib.error.URLError`` on an
    unreachable endpoint — the caller renders the failure)."""
    from urllib.request import urlopen

    with urlopen(f"http://{host}:{port}/metrics", timeout=timeout) as r:
        return r.read().decode("utf-8")


def parse_openmetrics(text: str) -> Tuple[Dict[str, float], Dict[str, dict]]:
    """Parse an OpenMetrics text exposition (the dialect
    ``to_openmetrics`` writes) into ``(plain, labeled)``:

    - ``plain``: ``{name: value}`` for unlabeled samples;
    - ``labeled``: ``{name: {label_sig: value}}`` for labeled samples
      (``label_sig`` is the raw ``key="value",...`` text between the
      braces — enough for the live doctor's quantile/bucket views
      without a full PromQL parser).
    """
    plain: Dict[str, float] = {}
    labeled: Dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            continue
        try:
            value = float(value_part)
        except ValueError:
            continue
        if "{" in name_part and name_part.endswith("}"):
            name, _, rest = name_part.partition("{")
            labeled.setdefault(name, {})[rest[:-1]] = value
        else:
            plain[name_part] = value
    return plain, labeled


# -- live terminal view ------------------------------------------------------


def _rate_lines(plain: Dict[str, float], prev: Optional[Dict[str, float]],
                interval_s: float) -> List[str]:
    """Counter deltas/s between two polls for the degraded/reject
    counters the doctor audits post-hoc."""
    watch = (
        "rp_backend_vmem_oom_retries_total",
        "rp_kernel_dma_fallbacks_total",
        "rp_simhash_topk_dense_fallbacks_total",
        "rp_simhash_topk_scan_fallbacks_total",
        "rp_serve_topk_rejects_total",
        "rp_serve_topk_errors_total",
        "rp_telemetry_subscriber_dropped_total",
        "rp_telemetry_subscriber_errors_total",
    )
    # per-subscriber drop counters (r20 satellite): the aggregate above
    # cannot say WHICH observer is chronically overrun, so surface every
    # rp_telemetry_subscriber_<name>_dropped_total as its own rate line
    per_sub = tuple(sorted(
        name for name in plain
        if name.startswith("rp_telemetry_subscriber_")
        and name.endswith("_dropped_total")
        and name != "rp_telemetry_subscriber_dropped_total"
    ))
    out = []
    for name in watch + per_sub:
        cur = plain.get(name)
        if cur is None:
            continue
        if prev is None or interval_s <= 0:
            out.append(f"  {name:<44} {cur:.0f} total")
        else:
            delta = cur - prev.get(name, 0.0)
            out.append(
                f"  {name:<44} {cur:.0f} total  "
                f"(+{delta / interval_s:.2f}/s)"
            )
    return out


def _health_lines(plain: Dict[str, float]) -> List[str]:
    """Active health-verdict gauges (``rp_health_*_firing``, mirrored
    by ``health.HealthEngine`` each tick) for the live view."""
    out = []
    for name in sorted(plain):
        if not (name.startswith("rp_health_") and name.endswith("_firing")):
            continue
        n = plain[name]
        detector = name[len("rp_health_"):-len("_firing")]
        state = f"FIRING x{n:.0f}" if n else "ok"
        out.append(f"  {detector:<24} {state}")
    return out


def render_live(plain: Dict[str, float], labeled: Dict[str, dict],
                prev: Optional[Dict[str, float]] = None, *,
                interval_s: float = 0.0, endpoint: str = "",
                poll: int = 0) -> str:
    """Render one poll of a live scrape as the refreshing terminal view
    ``cli doctor --live`` prints: queue depth, live span window, serve-
    latency quantiles, degraded-counter rates."""
    lines = [
        f"live doctor: {endpoint} — poll #{poll}"
        + (f" (every {interval_s:g}s)" if interval_s else "")
    ]
    depth = plain.get("rp_live_queue_depth",
                      plain.get("rp_stream_queue_depth"))
    if depth is not None:
        cap = plain.get("rp_live_queue_capacity")
        age = plain.get("rp_live_queue_depth_age_s")
        mean = plain.get("rp_live_queue_depth_mean")
        lines.append(
            "queue depth: "
            f"{depth:.0f}"
            + (f"/{cap:.0f}" if cap is not None else "")
            + (f", window mean {mean:.2f}" if mean is not None else "")
            + (f", last sample {age:.1f}s ago" if age is not None else "")
        )
    stages = sorted(
        (name[len("rp_live_span_"):-len("_wall_s")], v)
        for name, v in plain.items()
        if name.startswith("rp_live_span_") and name.endswith("_wall_s")
    )
    if stages:
        lines.append("live span window (summed wall):")
        for sname, wall in stages:
            cnt = plain.get(f"rp_live_span_{sname}_count")
            lines.append(
                f"  {sname:<18} {wall:8.4f}s"
                + (f"  x{cnt:.0f}" if cnt is not None else "")
            )
    lat = sorted(
        (name, qs) for name, qs in labeled.items()
        if "latency" in name and name.endswith("_quantile")
    )
    if lat:
        lines.append("serve latency quantiles:")
        for name, qs in lat:
            short = name[len("rp_"):-len("_seconds_quantile")]
            by_q = {}
            for sig, v in qs.items():
                q = sig.split("=", 1)[-1].strip('"')
                by_q[q] = v
            lines.append(
                f"  {short:<34} "
                + "  ".join(
                    f"p{float(q) * 100:g}={by_q[q] * 1e3:.2f}ms"
                    for q in sorted(by_q, key=float)
                )
            )
    health = _health_lines(plain)
    if health:
        lines.append("health verdicts:")
        lines.extend(health)
    rates = _rate_lines(plain, prev, interval_s)
    if rates:
        lines.append("degraded counters:")
        lines.extend(rates)
    if len(lines) == 1:
        lines.append("(no live metrics yet — is anything running?)")
    return "\n".join(lines) + "\n"


def live_snapshot_json(plain: Dict[str, float],
                       labeled: Dict[str, dict]) -> str:
    """One poll as a JSON line (``doctor --live --json``)."""
    return json.dumps({"metrics": plain, "labeled": labeled},
                      sort_keys=True)
