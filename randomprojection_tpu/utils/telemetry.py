"""Telemetry spine: metrics registry + structured JSONL event log.

Round 5's verdict showed the repo losing its own evidence: the flagship
bench headline never survived the driver's tail capture, a 13% config
regression went unflagged, and prose quoted numbers the committed record
contradicted.  This module is the durable half of the fix (the bench's
tail-safe compact line and regression tripwire are the other half —
``benchmark.py``):

- **MetricsRegistry** — process-wide counters, gauges and wall-clock
  histograms (fixed log2 buckets), thread-safe, snapshot-able to plain
  JSON.  ``StreamStats`` (``utils/observability.py``) is re-based on a
  registry, so every streamed run's counters are one ``snapshot()`` away
  from a machine-readable record.
- **TelemetryLog** — a JSONL event sink with a versioned schema: one
  event per pipeline stage / dispatch / commit / degraded retry,
  appended as a single line so a crash can lose at most the final event.
  ``parse_event``/``read_events`` are the shipped round-trip parsers —
  anything the sink writes, they load back.

Instrumented call sites go through the module-level ``emit()`` which is
a no-op (one attribute read) until ``configure()`` installs a sink —
the hot paths pay nothing when telemetry is off.  The CLI flag
``--telemetry-jsonl PATH`` (``project``/``stream-bench``/``bench``)
installs the process-wide sink.

Event schema — every line is a JSON object with:

- ``v``     int, schema version (this writer emits 2; readers accept 1-2)
- ``ts``    float, unix seconds (``time.time()``)
- ``event`` str, dotted event name — a member of the central ``EVENTS``
  registry below (rplint rule RP02 keeps emit sites, the registry,
  ``trace_report`` and the docs in agreement) (``stream.commit``,
  ``backend.dispatch``, ``backend.vmem_oom_retry``, ``stage.wall``,
  ``hash.batch``, ``simhash.query_tile``, ``simhash.topk_block_clamp``,
  ``simhash.topk_dense_fallback``, ``stream.prefetch.deliver``, ...)
- any further keys are event-specific payload (JSON scalars /
  lists / dicts only).

The schema is append-only: new payload keys may appear, ``v`` bumps
only when a new EVENT KIND (not just a payload key) is introduced or
the meaning of an existing key changes.  Version history:

- **v1** — flat events only (counters/stage walls/commits/retries).
- **v2** — adds the paired tracing events ``span_start``/``span_end``
  (the ``span()`` API below): ``span_start`` carries ``name``,
  ``trace_id``, ``span_id`` and ``parent_id`` (null for a trace root);
  ``span_end`` carries ``name``, ``trace_id``, ``span_id``, ``dur_s``
  and any end-time attributes.  Ids are run-unique strings.  All other
  events are unchanged — a v1 reader that ignores unknown event names
  parses a v2 file minus the spans; this module's ``read_events``
  accepts both versions, so committed v1 files keep loading.

Tracing spans (v2)
------------------

``span(name, **attrs)`` is a context manager emitting a
``span_start``/``span_end`` pair.  Nesting is tracked per thread: a
span opened inside another becomes its child (``parent_id``).  The
streaming pipeline gives every batch ONE trace — a root span named
``batch`` — whose child spans cover hash, enqueue-wait, H2D, dispatch
and d2h *whichever thread runs them*: cross-thread propagation is
explicit — the producer (``streaming.PrefetchSource`` worker) creates
the root and passes it through the queue; the consumer re-activates it
(``activate_span``) around its dispatch/d2h stages.
``utils/trace_report.py`` rebuilds per-batch timelines and critical-
path attribution from the resulting span stream.

Live subscribers (r17)
----------------------

Everything above is post-hoc: the JSONL file is read back after the
run.  The live observability plane adds an IN-PROCESS path:
``subscribe(fn)`` registers a subscriber that receives every emitted
event/span as a plain dict — delivered through a bounded per-subscriber
queue drained by that subscriber's own daemon dispatch thread, so a
slow (or wedged) subscriber can NEVER block or slow the emitting hot
path: when its queue is full the event is dropped for that subscriber
only, counted in the ``telemetry.subscriber.dropped`` registry counter
(and surfaced as a rate-limited ``telemetry.subscriber.dropped`` event
from the dispatch thread).  Subscribers make telemetry "active" on
their own: spans and events flow to them even when no JSONL sink is
configured — a serving process can be observed live without writing a
file.  ``LiveAggregator`` is the shipped subscriber: it folds the span
stream into rolling windowed per-stage stats (the doctor's critical-
path inputs, incremental) plus a TIME-WEIGHTED queue-depth view — the
last delivered depth persists between deliver events, so a stalled
stage shows its queue pinned instead of going blind (the post-hoc
report only sees depth AT deliveries).  ``utils/metrics_server.py``
exposes the whole picture on a scrapeable HTTP endpoint.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import queue as _queue_mod
import re
import sys
import threading
import time
from collections import deque
from typing import Iterator, Optional

__all__ = [
    "SCHEMA_VERSION",
    "SUPPORTED_SCHEMA_VERSIONS",
    "EVENTS",
    "registered_event",
    "MetricsRegistry",
    "TelemetryLog",
    "configure",
    "shutdown",
    "enabled",
    "active_path",
    "emit",
    "parse_event",
    "read_events",
    "Span",
    "span",
    "start_span",
    "end_span",
    "activate_span",
    "current_span",
    "trace_fields",
    "to_openmetrics",
    "quantiles_from_buckets",
    "Subscription",
    "subscribe",
    "unsubscribe",
    "LiveAggregator",
    "FlightRecorder",
]

SCHEMA_VERSION = 2
# readers accept every version whose events they can represent; v1 files
# (committed telemetry fixtures, old runs) parse forever
SUPPORTED_SCHEMA_VERSIONS = frozenset({1, 2})


class EVENTS:
    """Central registry of every telemetry event name (ISSUE r10).

    Before this class existed the event namespace lived in string
    literals scattered across seven modules, kept in agreement with
    ``trace_report.py`` and the docs by code review alone — exactly the
    emitter/consumer drift nothing guarded.  The contract, enforced by
    ``analysis/rplint.py`` rule RP02 (``cli lint``, run by
    ``make verify``):

    - every statically-resolvable name passed to ``emit()`` anywhere in
      the package MUST be a member here (emit sites reference the
      constants, never fresh literals);
    - every member MUST be either consumed by ``utils/trace_report.py``
      or documented in docs/ARCHITECTURE.md's event table — an event
      nobody reads and nobody documents is drift, and fails the lint.

    ``FAMILIES`` registers dotted-name *prefixes* for names completed at
    runtime (f-string emits and the per-path metric families such as the
    ``hash.batches.<path>`` counters); ``registered_event()`` accepts a
    name when it is a member or extends a family.  ``trace_report``'s
    degraded-event audit warns on any event in a telemetry file that the
    registry it was built against does not know.
    """

    # tracing span pair (schema v2) — emitted ONLY by this module
    SPAN_START = "span_start"
    SPAN_END = "span_end"
    # streaming pipeline
    STAGE_WALL = "stage.wall"
    STREAM_COMMIT = "stream.commit"
    STREAM_DISPATCH = "stream.dispatch"
    STREAM_PREFETCH_DELIVER = "stream.prefetch.deliver"
    STREAM_PREFETCH_ERROR = "stream.prefetch.error"
    STREAM_PREFETCH_SHUTDOWN_TIMEOUT = "stream.prefetch.shutdown_timeout"
    STREAM_STAGED_DELIVER = "stream.staged.deliver"
    STREAM_STAGED_ERROR = "stream.staged.error"
    STREAM_STAGED_SHUTDOWN_TIMEOUT = "stream.staged.shutdown_timeout"
    # backend dispatch + degraded retries
    BACKEND_DISPATCH = "backend.dispatch"
    BACKEND_VMEM_OOM_RETRY = "backend.vmem_oom_retry"
    # fused transform kernel (ISSUE 9): per-host-dispatch route record
    # (DMA vs single-buffered, dispatch-fusion chain length), the
    # DMA→single-buffered scoped-VMEM fallback, and the backend's
    # multi-step dispatch-fusion record.  Deliberately NOT a family —
    # rogue ``kernel.dma.*`` names stay lintable (rp02_dma_bad.py).
    KERNEL_DMA_DISPATCH = "kernel.dma.dispatch"
    KERNEL_DMA_FALLBACK = "kernel.dma.fallback"
    BACKEND_DISPATCH_FUSED = "backend.dispatch_fused"
    # ingest hashing
    HASH_BATCH = "hash.batch"
    # simhash query/serving
    SIMHASH_QUERY_TILE = "simhash.query_tile"
    SIMHASH_TOPK_TILE = "simhash.topk_tile"
    SIMHASH_TOPK_BLOCK_CLAMP = "simhash.topk_block_clamp"
    SIMHASH_TOPK_DENSE_FALLBACK = "simhash.topk_dense_fallback"
    # fused serving kernel (ISSUE 7): per-tile kernel dispatches, the
    # VMEM-OOM degraded retry, and fused->scan routing fallbacks
    TOPK_KERNEL_DISPATCH = "topk.kernel.dispatch"
    TOPK_KERNEL_VMEM_RETRY = "topk.kernel.vmem_retry"
    TOPK_KERNEL_SCAN_FALLBACK = "topk.kernel.scan_fallback"
    SERVE_TOPK_BATCH = "serve.topk_batch"
    SERVE_TOPK_ERROR = "serve.topk.error"
    # sharded serving tier (ISSUE 8): per-tile shard fanout, the
    # cross-shard candidate merge, and the replica-routed coalesced
    # dispatch (deliberately NOT a family — rogue ``shard.*`` /
    # ``serve.shard.*`` names stay lintable)
    SHARD_TOPK_TILE = "shard.topk_tile"
    SHARD_MERGE = "shard.merge"
    SERVE_SHARD_BATCH = "serve.shard.batch"
    # durable index lifecycle (snapshot/restore + crash recovery)
    INDEX_SNAPSHOT_SAVE = "index.snapshot.save"
    INDEX_SNAPSHOT_LOAD = "index.snapshot.load"
    INDEX_COMPACT = "index.compact"
    RECOVER_RESUME = "recover.resume"
    RECOVER_CHECKSUM_MISMATCH = "recover.checksum_mismatch"
    RECOVER_ORPHAN_CHUNK = "recover.orphan_chunk"
    # live observability plane (r17): subscriber overflow (emitted by the
    # dispatch thread, rate-limited — the emitting hot path only counts),
    # per-request serving latency (enqueue→dispatch→complete stamps from
    # TopKServer/ShardedTopKServer), and the open-loop load generator's
    # run summary.  Deliberately NOT families — rogue
    # ``telemetry.subscriber.*`` / ``serve.latency.*`` / ``loadgen.*``
    # names stay lintable (rp02_live_bad.py).
    TELEMETRY_SUBSCRIBER_DROPPED = "telemetry.subscriber.dropped"
    SERVE_LATENCY_REQUEST = "serve.latency.request"
    LOADGEN_RUN = "loadgen.run"
    # multi-probe LSH candidate tier (ISSUE 15): per-tile candidate
    # generation record (probes, candidate fraction), the density/
    # starvation fallback to the exact-scan ladder rung (degraded-to-
    # exact — on the doctor's audit), and banded-bucket build folds.
    # Deliberately NOT a family — rogue ``index.lsh.*`` names stay
    # lintable (rp02_lsh_bad.py).
    INDEX_LSH_DISPATCH = "index.lsh.dispatch"
    INDEX_LSH_FALLBACK = "index.lsh.fallback"
    INDEX_LSH_BUILD = "index.lsh.build"
    # device-fused candidate generation (ISSUE 16): per-tile fused
    # probe → gather → re-rank dispatch record, device-CSR mirror
    # (re-)uploads, and the adaptive per-query probing round summary
    # (probes-used, early exits, budget stops).
    INDEX_LSH_DEVICE_DISPATCH = "index.lsh.device_dispatch"
    INDEX_LSH_DEVICE_UPLOAD = "index.lsh.device_upload"
    INDEX_LSH_ADAPTIVE = "index.lsh.adaptive"
    # health plane (ISSUE 18 / r20): typed detector verdicts with a
    # firing/cleared lifecycle (utils/health.py emits, deduplicated and
    # rate-limited), plus the flight recorder's dump record.
    # Deliberately NOT a family — rogue ``health.*`` names stay
    # lintable (rp02_health_bad.py).
    HEALTH_SLO_BURN = "health.slo_burn"
    HEALTH_STALL = "health.stall"
    HEALTH_QUEUE_PINNED = "health.queue_pinned"
    HEALTH_DEGRADED_SPIKE = "health.degraded_spike"
    HEALTH_FLIGHT_DUMP = "health.flight_dump"
    # tiered hot/cold residency (ISSUE 19 / r21): per-gather hot-tier
    # hit record, cold-tier row fetch (rows/bytes/wall, with the
    # overlapped-under-the-hot-kernel window), demotion/promotion churn,
    # and the synchronous-fetch fallback rung (degraded — on the
    # doctor's audit).  Deliberately NOT a family — rogue
    # ``index.tier.*`` names stay lintable (rp02_tier_bad.py).
    INDEX_TIER_HIT = "index.tier.hit"
    INDEX_TIER_FETCH = "index.tier.fetch"
    INDEX_TIER_EVICT = "index.tier.evict"
    INDEX_TIER_FALLBACK = "index.tier.fallback"

    # runtime-completed name families.  ``*_FAMILY`` constants are the
    # prefixes callers build on (today: the per-kernel-path hash counter
    # family, ``hash.batches.strided`` / ``.list`` / ``.python``);
    # FAMILIES is the tuple ``registered_event`` prefix-matches against.
    HASH_BATCHES_FAMILY = "hash.batches."
    FAMILIES = (HASH_BATCHES_FAMILY,)


def _event_names() -> frozenset:
    return frozenset(
        v
        for k, v in vars(EVENTS).items()
        if k.isupper()
        and not k.endswith("_FAMILY")
        and k != "FAMILIES"
        and isinstance(v, str)
    )


_EVENT_NAMES = _event_names()


def registered_event(name: str) -> bool:
    """True when ``name`` is a registered event: an ``EVENTS`` member or
    an extension of a registered family prefix."""
    return name in _EVENT_NAMES or any(
        name.startswith(f) for f in EVENTS.FAMILIES
    )


class MetricsRegistry:
    """Thread-safe counters, gauges and log2 wall-clock histograms.

    - ``counter_inc(name, value)`` — monotone accumulators (batches,
      rows, bytes, dispatches, retries).
    - ``gauge_set(name, value)`` — point-in-time samples; the registry
      keeps ``last``/``max``/``sum``/``n`` so both extremes and means
      are recoverable (the prefetch queue-occupancy gauge needs max AND
      mean).
    - ``observe(name, seconds)`` / ``timer(name)`` — wall-clock
      histograms over fixed log2 buckets: bucket ``i`` holds samples in
      ``[2^i, 2^(i+1))`` microseconds, so buckets are comparable across
      processes and rounds (no adaptive boundaries to drift).  ``sum``
      and ``count`` ride along, so totals (the ``StreamStats``
      stage-wall contract) are exact, not bucket-approximated.

    One registry per concern: ``StreamStats`` owns one per stream; the
    process-wide default (``registry()``) collects cross-cutting counts
    (backend dispatches, hash fallbacks, top-k clamps).
    """

    def __init__(self):
        # REENTRANT: the flight recorder's fatal-signal dump snapshots
        # this registry FROM the main thread, which may have been
        # interrupted while holding this very lock inside counter_inc —
        # a plain Lock would self-deadlock the signal handler (r20)
        self._lock = threading.RLock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._hists: dict = {}

    # -- counters -----------------------------------------------------------

    def counter_inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name: str):
        """Current value (0 when never incremented)."""
        with self._lock:
            return self._counters.get(name, 0)

    # -- gauges -------------------------------------------------------------

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = {"last": value, "max": value, "sum": 0.0, "n": 0}
                self._gauges[name] = g
            g["last"] = value
            if value > g["max"]:
                g["max"] = value
            g["sum"] += value
            g["n"] += 1

    def gauge(self, name: str) -> dict:
        """``{last, max, sum, n}`` (zeros when never set)."""
        with self._lock:
            g = self._gauges.get(name)
            return dict(g) if g else {"last": 0, "max": 0, "sum": 0.0, "n": 0}

    def gauge_max(self, name: str):
        return self.gauge(name)["max"]

    def gauge_mean(self, name: str) -> float:
        g = self.gauge(name)
        return g["sum"] / g["n"] if g["n"] else 0.0

    # -- histograms ---------------------------------------------------------

    @staticmethod
    def _bucket(seconds: float) -> int:
        """Fixed log2 bucket index: ``floor(log2(max(seconds, 1e-6) / 1e-6))``
        — bucket 0 is [1µs, 2µs), bucket 20 is [~1s, ~2s)."""
        us = max(seconds, 1e-6) / 1e-6
        return max(int(math.floor(math.log2(us))), 0)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = {"sum": 0.0, "count": 0, "buckets": {}}
                self._hists[name] = h
            h["sum"] += seconds
            h["count"] += 1
            b = self._bucket(seconds)
            h["buckets"][b] = h["buckets"].get(b, 0) + 1

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def hist_sum(self, name: str) -> float:
        with self._lock:
            h = self._hists.get(name)
            return h["sum"] if h else 0.0

    def hist_sums(self, prefix: str = "") -> dict:
        """``{name_without_prefix: total_seconds}`` for every histogram
        whose name starts with ``prefix`` (the ``StreamStats.stage_wall``
        view is ``hist_sums('stage.')``)."""
        with self._lock:
            return {
                k[len(prefix):]: h["sum"]
                for k, h in self._hists.items()
                if k.startswith(prefix)
            }

    def hist_quantiles(self, name: str,
                       qs=(0.5, 0.9, 0.99, 0.999)) -> Optional[dict]:
        """HDR-style quantile extraction from a log2-bucket histogram:
        ``{"p50": seconds, "p90": ..., "count": exact, "sum": exact}``
        (see ``quantiles_from_buckets`` for the estimation contract), or
        None when the histogram was never observed."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            buckets = dict(h["buckets"])
            count, total = h["count"], h["sum"]
        return quantiles_from_buckets(buckets, count, total, qs)

    # -- snapshot -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain-JSON view of every metric (bucket keys stringified so the
        result survives ``json.dumps`` → ``json.loads`` unchanged)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": {k: dict(v) for k, v in self._gauges.items()},
                "histograms": {
                    k: {
                        "sum": h["sum"],
                        "count": h["count"],
                        "buckets": {
                            str(b): c for b, c in sorted(h["buckets"].items())
                        },
                    }
                    for k, h in self._hists.items()
                },
            }


def quantiles_from_buckets(buckets: dict, count: int, total: float,
                           qs=(0.5, 0.9, 0.99, 0.999)) -> dict:
    """Quantile extraction from a fixed-log2-bucket histogram snapshot
    (bucket ``i`` holds samples in ``[2^i, 2^(i+1))`` µs; ``count`` and
    ``total`` are the registry's EXACT tallies, never approximated).

    Returns ``{"p50": seconds, ..., "count": count, "sum": total,
    "mean": total/count}`` with one ``p<q*100>`` key per requested
    quantile.  Estimation contract:

    - ``count == 0`` → every quantile is None (an empty histogram has no
      quantiles; callers render "-", never 0.0 — a fake zero would read
      as a sub-microsecond latency).
    - ``count == 1`` → every quantile is EXACTLY ``total`` (the single
      sample's value is recoverable from the exact sum).
    - otherwise quantile rank ``q*(count-1)`` lands in a bucket by
      cumulative count and interpolates linearly inside it, clamped to
      the bucket edges — the estimate is within one bucket of the true
      value, i.e. a factor-of-2 relative error bound (bucket 0's lower
      edge is taken as 0 s: it also holds every sub-microsecond sample).

    Quantiles are monotone in ``q`` by construction (the cumulative walk
    never moves backwards), including under concurrent recording — the
    snapshot is taken under the registry lock.
    """
    out = {"count": int(count), "sum": total,
           "mean": (total / count) if count else None}
    if count <= 0:
        for q in qs:
            out[_q_key(q)] = None
        return out
    if count == 1:
        for q in qs:
            out[_q_key(q)] = total
        return out
    items = sorted((int(b), c) for b, c in buckets.items())
    for q in qs:
        rank = q * (count - 1)  # 0-based fractional rank
        cum = 0
        val = None
        for b, c in items:
            if cum + c > rank:
                lo = 0.0 if b == 0 else (1 << b) * 1e-6
                hi = (1 << (b + 1)) * 1e-6
                # linear interpolation by the rank's position within
                # this bucket's occupants
                frac = (rank - cum) / c if c > 1 else 0.5
                val = lo + frac * (hi - lo)
                break
            cum += c
        if val is None:  # rank beyond the last bucket (shouldn't happen)
            b = items[-1][0]  # pragma: no cover — defensive
            val = (1 << (b + 1)) * 1e-6  # pragma: no cover
        out[_q_key(q)] = val
    return out


def _q_key(q: float) -> str:
    """0.5 → "p50", 0.999 → "p99.9" (trailing zeros dropped)."""
    s = f"{q * 100:.4f}".rstrip("0").rstrip(".")
    return f"p{s}"


_DEFAULT_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry (cross-cutting counters: backend
    dispatches, VMEM-OOM retries, hash fallbacks, top-k clamps)."""
    return _DEFAULT_REGISTRY


def _repair_torn_tail(path: str) -> None:
    """Make an existing event file append-safe before reopening it.

    A previous run that crashed mid-write leaves a torn final line with
    no trailing newline; appending onto it would merge it with the new
    run's first event into one corrupt MID-file line, which the strict
    reader rightly refuses — turning a lost-final-event file into an
    unreadable one.  A fragment that parses as a complete event (only
    the newline was lost) is terminated; a genuinely torn fragment is
    truncated away — that event was already lost at crash time — but
    ONLY when the preceding complete line proves the file is already a
    telemetry log: a user pointing ``--telemetry-jsonl`` at some other
    newline-less file must never have its content destroyed (the repair
    then just terminates the line and appends after it).
    """
    try:
        f = open(path, "r+b")
    except FileNotFoundError:
        return
    with f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return
        f.seek(size - 1)
        if f.read(1) == b"\n":
            return
        window = min(size, 1 << 20)  # events are far smaller than 1 MB
        f.seek(size - window)
        tail = f.read(window)
        nl = tail.rfind(b"\n")
        if nl < 0 and size > window:  # pragma: no cover — >1 MB one-line
            f.write(b"\n")  # can't see the line start; don't destroy data
            return
        frag = tail[nl + 1:]

        def _parses(raw: bytes) -> bool:
            try:
                parse_event(raw.decode("utf-8"))
                return True
            except (ValueError, UnicodeDecodeError):
                return False

        if _parses(frag):
            f.write(b"\n")  # complete event, only the newline was lost
            return
        prev_is_event = nl >= 0 and _parses(
            tail[tail.rfind(b"\n", 0, nl) + 1 : nl]
        )
        # a run that crashed writing its very FIRST event leaves no
        # preceding line to prove ownership; the sink's own serialization
        # prefix is the next-best evidence (either direction of
        # startswith: the fragment may be shorter than the prefix)
        own_prefix = b'{"v":'
        frag_is_ours = frag.startswith(own_prefix) or own_prefix.startswith(
            frag
        )
        if prev_is_event or (nl < 0 and frag_is_ours):
            f.truncate(size - len(frag))  # our log's torn final event
        else:
            f.write(b"\n")  # not provably our log: preserve the content


class TelemetryLog:
    """Append-only JSONL event sink (versioned schema, thread-safe).

    Each ``emit`` writes exactly one ``\\n``-terminated line and flushes,
    so concurrent producer/consumer threads interleave whole events and
    a crash loses at most the event being written.  Reopening a file a
    crashed run left torn repairs the tail first (``_repair_torn_tail``),
    so multi-run files stay readable end to end.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        _repair_torn_tail(path)
        self._f = open(path, "a")

    def emit(self, event: str, **fields) -> None:
        rec = {"v": SCHEMA_VERSION, "ts": time.time(), "event": event}
        rec.update(fields)
        self.emit_record(rec)

    def emit_record(self, rec: dict) -> None:
        """Write one already-assembled event dict (the module ``emit()``
        builds the record once and hands it to the sink AND the live
        subscribers)."""
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            if self._f is None:  # pragma: no cover - emit after close
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_ACTIVE_LOG: Optional[TelemetryLog] = None


def configure(path: str) -> TelemetryLog:
    """Install the process-wide JSONL sink (replacing any previous one).
    Instrumented call sites all over the package start emitting into it
    immediately; ``shutdown()`` uninstalls and closes.  Each configure
    draws a fresh run token for span ids, so the runs appended to one
    file can never collide trace ids."""
    global _ACTIVE_LOG, _RUN_TOKEN
    if _ACTIVE_LOG is not None:
        _ACTIVE_LOG.close()
    with _SPAN_LOCK:
        # the token pairs with the span sequence under the same lock: a
        # span id drawn concurrently with configure() must carry either
        # the old token or the new one, never a torn read (RP10)
        _RUN_TOKEN = os.urandom(4).hex()
    _ACTIVE_LOG = TelemetryLog(path)
    return _ACTIVE_LOG


def shutdown() -> None:
    global _ACTIVE_LOG
    if _ACTIVE_LOG is not None:
        _ACTIVE_LOG.close()
        _ACTIVE_LOG = None


def enabled() -> bool:
    """True when a process-wide sink is installed OR at least one live
    subscriber is registered (the live plane makes telemetry active
    without any JSONL file).  Hot paths with non-trivial payload
    construction should guard on this."""
    return _ACTIVE_LOG is not None or bool(_SUBSCRIPTIONS)


def active_path() -> Optional[str]:
    """Path of the installed sink (None when telemetry is off) — lets a
    caller that needs a scoped sink (the bench's staged-ingest trace
    capture) restore the user's sink afterwards."""
    log = _ACTIVE_LOG
    return log.path if log is not None else None


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync after an ``os.replace`` — without it
    the rename itself can be lost on crash, which for the flight
    recorder means losing exactly the postmortem the crash produced.
    Tolerant: some filesystems refuse O_RDONLY directory opens, and a
    dump must never turn into a new crash."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _finalizing() -> bool:
    """True when the interpreter is tearing down (or so far gone that we
    cannot even tell).  Emitting from a daemon thread or a ``__del__``
    at that point must drop the event, never traceback."""
    try:
        return sys is None or sys.is_finalizing()
    # rplint: allow[RP06] — teardown probe: the failure IS the answer
    except Exception:  # pragma: no cover — modules already demolished
        return True


def emit(event: str, **fields) -> None:
    """Emit one event to the process-wide sink AND every live
    subscriber; no-op when neither is installed (two global reads —
    safe in hot paths).  Subscriber delivery is a non-blocking bounded
    enqueue: a full subscriber queue drops the event for that
    subscriber (counted), never stalls the emitter.  Safe during
    interpreter teardown: a late emit from a daemon thread or a
    ``__del__`` is dropped instead of raising into the finalizer."""
    log = _ACTIVE_LOG
    subs = _SUBSCRIPTIONS
    if log is None and not subs:
        return
    try:
        rec = {"v": SCHEMA_VERSION, "ts": time.time(), "event": event}
        rec.update(fields)
        if log is not None:
            log.emit_record(rec)
        for s in subs:
            s._offer(rec)
    except Exception:
        if _finalizing():
            return
        raise


# -- live subscribers (r17) ---------------------------------------------------


class Subscription:
    """One live event subscriber: a bounded queue fed by ``emit()`` and
    drained by this subscription's own daemon dispatch thread, which
    calls ``fn(event_dict)`` for every delivered event.

    Delivery contract:

    - the emitting thread only ever does a non-blocking enqueue; when
      the queue is full the event is DROPPED for this subscriber
      (``telemetry.subscriber.dropped`` counter on the default
      registry + the per-subscription ``stats()`` tally) — overload
      degrades the observer, never the observed;
    - events arrive on the dispatch thread in emit order (per-queue
      FIFO); a raising ``fn`` is counted (``errors``) and delivery
      continues — one bad callback must not kill the plane;
    - the dispatch thread reports accumulated drops as a rate-limited
      ``telemetry.subscriber.dropped`` EVENT (at most one per
      ``_DROP_REPORT_S``) so overload is visible on the spine, not just
      in a counter nobody scrapes.

    Create with ``subscribe()``; detach with ``unsubscribe()`` /
    ``close()`` (drains nothing further, joins the dispatch thread).
    """

    _POLL_S = 0.05
    _DROP_REPORT_S = 1.0

    def __init__(self, fn, *, maxsize: int = 1024, name: str = ""):
        if not callable(fn):
            raise TypeError(f"subscriber fn must be callable, got {fn!r}")
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "subscriber")
        self._q: "_queue_mod.Queue" = _queue_mod.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._dropped = 0
        self._delivered = 0
        self._errors = 0
        self._last_drop_report = 0.0
        self._reported_drops = 0
        self._thread = threading.Thread(
            target=self._run, name=f"rp-telemetry-sub-{self.name}",
            daemon=True,
        )
        self._thread.start()

    # emitter side — called from emit() on ANY thread; must never block
    def _offer(self, rec: dict) -> None:
        try:
            self._q.put_nowait(rec)
        except _queue_mod.Full:
            with self._lock:
                self._dropped += 1
            _DEFAULT_REGISTRY.counter_inc("telemetry.subscriber.dropped")
            # per-subscriber tally (ISSUE 18 satellite): one aggregate
            # counter cannot say WHICH observer is chronically overrun —
            # doctor --live renders a drop rate per subscriber from these
            _DEFAULT_REGISTRY.counter_inc(
                f"telemetry.subscriber.{self.name}.dropped"
            )

    # dispatch side — this subscription's own daemon thread
    def _run(self) -> None:
        while True:
            # stop is checked every iteration, not only on an empty
            # queue: close() discards pending events (as documented)
            # instead of delivering a full queue's worth to a possibly
            # slow fn — close() on a wedged subscriber must not block
            # for queue-length × callback-wall
            if self._stop.is_set():
                return
            try:
                rec = self._q.get(timeout=self._POLL_S)
            except _queue_mod.Empty:
                continue
            try:
                self.fn(rec)
            except Exception:
                # a raising subscriber must not kill delivery; count it
                # so a silently-broken observer is still diagnosable
                with self._lock:
                    self._errors += 1
                _DEFAULT_REGISTRY.counter_inc("telemetry.subscriber.errors")
            with self._lock:
                self._delivered += 1
                drops = self._dropped - self._reported_drops
                now = time.monotonic()
                report = (
                    drops > 0
                    and now - self._last_drop_report >= self._DROP_REPORT_S
                )
                if report:
                    self._reported_drops = self._dropped
                    self._last_drop_report = now
                    total = self._dropped
            if report:
                # re-enters emit() from the dispatch thread (rate-
                # limited above); recursion is bounded: this event fans
                # out like any other and may itself be dropped
                emit(
                    EVENTS.TELEMETRY_SUBSCRIBER_DROPPED,
                    subscriber=self.name, dropped=int(drops),
                    dropped_total=int(total),
                )

    def stats(self) -> dict:
        """``{delivered, dropped, errors, queued}`` (thread-safe)."""
        with self._lock:
            return {
                "delivered": self._delivered,
                "dropped": self._dropped,
                "errors": self._errors,
                "queued": self._q.qsize(),
            }

    def close(self) -> None:
        """Detach from the live stream (equivalent to ``unsubscribe``),
        stop the dispatch thread (pending queued events are discarded)
        and join it.  Idempotent.  Detaching matters: a closed-but-
        registered subscription would keep ``enabled()`` True and its
        full queue would count a drop on every future emit forever."""
        with _SUB_LOCK:
            try:
                _SUBSCRIPTIONS.remove(self)
            except ValueError:
                pass
        self._stop.set()
        self._thread.join()

    def __enter__(self) -> "Subscription":
        return self

    def __exit__(self, *exc) -> None:
        unsubscribe(self)


# registered subscriptions: a plain list MUTATED under _SUB_LOCK (never
# rebound — the hot-path readers in emit()/enabled() iterate/test it
# lock-free, which is safe under the GIL for append/remove)
_SUBSCRIPTIONS: list = []
_SUB_LOCK = threading.Lock()


def subscribe(fn, *, maxsize: int = 1024, name: str = "") -> Subscription:
    """Register a live subscriber: ``fn(event_dict)`` will be called on
    a dedicated daemon dispatch thread for every event/span emitted
    from now on (bounded queue — see ``Subscription``).  Makes
    telemetry active even without a JSONL sink.  Returns the
    ``Subscription``; pass it to ``unsubscribe`` to detach."""
    sub = Subscription(fn, maxsize=maxsize, name=name)
    with _SUB_LOCK:
        _SUBSCRIPTIONS.append(sub)
    return sub


def unsubscribe(sub: Subscription) -> None:
    """Detach a subscription registered by ``subscribe`` and stop its
    dispatch thread (alias of ``Subscription.close``).  Unknown or
    already-removed subscriptions are a no-op (idempotent)."""
    sub.close()


class LiveAggregator:
    """The shipped live subscriber: folds the event/span stream into
    rolling-window aggregates — the doctor's per-stage critical-path
    inputs, computed incrementally while the run is still going.

    Usage: ``agg = LiveAggregator(); sub = subscribe(agg)`` (the
    instance is itself the subscriber callable).  All state is guarded
    by one lock; ``snapshot()`` / ``registry_snapshot()`` may be called
    from any thread (the metrics endpoint scrapes them).

    Windows (default 10 s, sliding):

    - **per-stage span wall** — every ``span_end`` lands in its name's
      window: count, summed wall, mean.
    - **event rates** — per-event-name occurrence count in the window.
    - **queue depth, TIME-WEIGHTED** — the satellite fix: the post-hoc
      report samples queue depth only AT ``stream.*.deliver`` events,
      so a stalled stage (no deliveries) is a blind spot exactly when
      depth matters most.  Here the last delivered depth PERSISTS: the
      window mean integrates the piecewise-constant depth signal up to
      ``now``, and ``age_s`` says how stale the last sample is — a
      consumer that stopped draining shows a pinned-full queue getting
      older, not silence.
    """

    def __init__(self, window_s: float = 10.0):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._spans: dict = {}   # stage name -> deque[(ts, dur_s)]
        self._events: dict = {}  # event name -> deque[ts]
        self._queue: deque = deque()  # (ts, depth) samples, window+1 kept
        self._queue_capacity: Optional[int] = None
        self._n_seen = 0

    # the subscriber callable face
    def __call__(self, rec: dict) -> None:
        name = rec.get("event")
        ts = rec.get("ts")
        if not isinstance(name, str) or not isinstance(ts, (int, float)):
            return
        with self._lock:
            self._n_seen += 1
            dq = self._events.setdefault(name, deque())
            dq.append(ts)
            if name == EVENTS.SPAN_END and isinstance(
                rec.get("dur_s"), (int, float)
            ):
                sdq = self._spans.setdefault(
                    str(rec.get("name")), deque()
                )
                sdq.append((ts, rec["dur_s"]))
            elif name in (
                EVENTS.STREAM_PREFETCH_DELIVER,
                EVENTS.STREAM_STAGED_DELIVER,
            ):
                self._queue.append((ts, rec.get("queue_depth", 0) or 0))
                if rec.get("capacity") is not None:
                    self._queue_capacity = rec["capacity"]
            self._prune(ts)

    def _prune(self, now: float) -> None:
        # under self._lock.  The queue deque keeps ONE sample older than
        # the window: it carries the depth the window opened at (the
        # piecewise-constant signal needs a left endpoint).
        horizon = now - self.window_s
        for dq in self._spans.values():
            while dq and dq[0][0] < horizon:
                dq.popleft()
        for dq in self._events.values():
            while dq and dq[0] < horizon:
                dq.popleft()
        while len(self._queue) > 1 and self._queue[1][0] <= horizon:
            self._queue.popleft()

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Rolling-window view as plain JSON: per-stage span stats,
        per-event rates, and the time-weighted queue-depth signal
        evaluated at ``now`` (default: wall clock — pass an explicit
        ``now`` for deterministic tests)."""
        if now is None:
            now = time.time()
        with self._lock:
            self._prune(now)
            horizon = now - self.window_s
            stages = {}
            for sname, dq in sorted(self._spans.items()):
                if not dq:
                    continue
                walls = [d for _, d in dq]
                stages[sname] = {
                    "count": len(walls),
                    "wall_s": round(sum(walls), 6),
                    "mean_s": round(sum(walls) / len(walls), 6),
                }
            rates = {
                ename: round(len(dq) / self.window_s, 3)
                for ename, dq in sorted(self._events.items())
                if dq
            }
            qinfo = None
            if self._queue:
                samples = list(self._queue)
                last_ts, last_depth = samples[-1]
                # integrate the piecewise-constant depth over
                # [horizon, now]: each sample holds until the next one,
                # the last holds until NOW — the stalled-consumer fix
                area = 0.0
                for (t0, d0), (t1, _) in zip(samples, samples[1:]):
                    lo, hi = max(t0, horizon), min(t1, now)
                    if hi > lo:
                        area += d0 * (hi - lo)
                lo = max(last_ts, horizon)
                if now > lo:
                    area += last_depth * (now - lo)
                span_len = min(self.window_s, max(now - samples[0][0], 0.0))
                qinfo = {
                    "last": last_depth,
                    "age_s": round(max(now - last_ts, 0.0), 3),
                    "time_weighted_mean": round(
                        area / span_len if span_len > 0 else float(last_depth),
                        3,
                    ),
                    "capacity": self._queue_capacity,
                }
            return {
                "window_s": self.window_s,
                "events_seen": self._n_seen,
                "stages": stages,
                "event_rates": rates,
                "queue": qinfo,
            }

    def registry_snapshot(self, now: Optional[float] = None) -> dict:
        """The rolling window rendered as a ``MetricsRegistry.snapshot``
        -shaped dict (gauges only) so ``to_openmetrics`` can merge it
        into a scrape: ``live.span.<stage>.wall_s`` /
        ``live.span.<stage>.mean_s`` / ``live.span.<stage>.count``,
        ``live.event.<name>.rate``, and the ``live.queue.*`` depth
        signal."""
        snap = self.snapshot(now)
        gauges: dict = {}

        def g(gname, value):
            gauges[gname] = {"last": value, "max": value,
                             "sum": value, "n": 1}

        for sname, st in snap["stages"].items():
            g(f"live.span.{sname}.wall_s", st["wall_s"])
            g(f"live.span.{sname}.mean_s", st["mean_s"])
            g(f"live.span.{sname}.count", st["count"])
        for ename, rate in snap["event_rates"].items():
            g(f"live.event.{ename}.rate", rate)
        q = snap["queue"]
        if q is not None:
            g("live.queue.depth", q["last"])
            g("live.queue.depth_age_s", q["age_s"])
            g("live.queue.depth_mean", q["time_weighted_mean"])
            if q.get("capacity") is not None:
                g("live.queue.capacity", q["capacity"])
        return {"counters": {}, "gauges": gauges, "histograms": {}}


class FlightRecorder:
    """Always-on crash evidence (ISSUE 18): a fixed-size in-memory ring
    of the last ``capacity`` events/spans — the cheapest possible
    subscriber (one deque append per event, no JSONL sink required) —
    dumped atomically to a self-describing postmortem file when the
    process dies.

    Usage: ``rec = FlightRecorder(); sub = subscribe(rec, ...)`` (the
    instance is itself the subscriber callable), then
    ``rec.install(path)`` to arm the fatal-signal (SIGTERM/SIGABRT)
    handlers and the unhandled-exception hook.  ``dump()`` can also be
    called on demand (the health watchdog trips it; see
    ``utils/health.py``).  ``cli doctor --postmortem <dump>``
    reconstructs the final seconds from the result.

    Signal-safety argument (docs/ARCHITECTURE.md "Health plane"): CPython
    runs signal handlers in the MAIN thread at bytecode boundaries — not
    in async-signal context — so file IO inside the handler is safe.
    Locks are the real hazard: the interrupted main-thread frame may
    HOLD any lock the hot path takes (the JSONL sink lock inside
    ``emit``, the subscriber-list lock, the registry lock inside
    ``counter_inc``), and a handler that blocks on one of those
    self-deadlocks — same thread, never released.  Three measures close
    every such path: (1) the signal-context dump never re-enters the
    event spine (``emit_event=False`` — no sink lock, no subscriber
    lock); (2) the two locks the dump DOES take (ring, registry) are
    reentrant, so an interrupted holder on the main thread is re-entry,
    not deadlock; (3) a signal arriving during a dump cannot re-enter
    the dump itself (the non-blocking ``_dump_guard`` makes the nested
    dump a no-op).  After dumping, the previous signal disposition is
    restored and the signal re-raised, so the process still dies with
    the correct exit status (``kill -TERM`` still exits 143).

    Dump format (one JSON object, written tmp→fsync→``os.replace`` — the
    r11 durable-write discipline, so a crash mid-dump leaves the
    previous dump or nothing, never a torn file)::

        {"format": "rp-flight-recorder", "v": 1, "pid": ..., "ts": ...,
         "reason": "signal:SIGTERM" | "unhandled_exception:..." |
                   "watchdog:<detector>" | "on_demand",
         "capacity": N, "events": [<the ring, oldest first>],
         "counters": <registry().snapshot()>,
         "health": <active verdicts, when a health engine is attached>}
    """

    FORMAT = "rp-flight-recorder"
    VERSION = 1

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        # reentrant for the same reason as the registry lock: the
        # signal handler's dump copies the ring on the main thread,
        # which may have been interrupted inside install/attach_health
        self._lock = threading.RLock()
        # non-blocking reentrancy guard: a signal landing mid-dump must
        # skip the nested dump, not deadlock on it
        self._dump_guard = threading.Lock()
        self._path: Optional[str] = None
        self._health = None  # zero-arg callable -> active verdict list
        self._prev_handlers: dict = {}
        self._prev_excepthook = None
        self._installed_signals: tuple = ()

    # the subscriber callable face — one bounded append, never blocks
    def __call__(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def attach_health(self, fn) -> None:
        """Attach a zero-arg callable returning the active health
        verdicts (``HealthEngine.active``); its result rides in every
        dump so the postmortem names the detectors firing at death."""
        with self._lock:
            self._health = fn

    def snapshot(self) -> list:
        """The ring's current contents, oldest first (thread-safe)."""
        with self._lock:
            return list(self._ring)

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand", *,
             emit_event: bool = True) -> Optional[str]:
        """Write the postmortem file atomically and return its path.
        Returns None when no path is known or a dump is already in
        progress (a signal arriving mid-dump).  Never raises during
        interpreter teardown — the dump is best-effort evidence, not a
        new crash.

        ``emit_event=False`` is the SIGNAL-CONTEXT mode: the handler
        may have interrupted the main thread while it held the JSONL
        sink lock or the subscriber-list lock inside ``emit()``, so the
        dump must never re-enter the event spine from there — the file
        itself is the evidence."""
        with self._lock:
            path = path or self._path
        if path is None:
            return None
        if not self._dump_guard.acquire(blocking=False):
            return None  # nested dump (signal during dump): skip
        try:
            with self._lock:
                events = list(self._ring)
                health_fn = self._health
            health = None
            if health_fn is not None:
                try:
                    health = health_fn()
                except Exception:
                    # the postmortem must still land when the engine is
                    # mid-teardown; record that the section is missing
                    _DEFAULT_REGISTRY.counter_inc(
                        "telemetry.flight.health_snapshot_errors"
                    )
            rec = {
                "format": self.FORMAT,
                "v": self.VERSION,
                "pid": os.getpid(),
                "ts": time.time(),
                "reason": reason,
                "capacity": self.capacity,
                "events": events,
                "counters": _DEFAULT_REGISTRY.snapshot(),
                "health": health,
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(rec, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(os.path.dirname(os.path.abspath(path)))
            _DEFAULT_REGISTRY.counter_inc("telemetry.flight.dumps")
            if emit_event:
                emit(
                    EVENTS.HEALTH_FLIGHT_DUMP, reason=reason, path=path,
                    events=len(events),
                )
            return path
        except Exception:
            if _finalizing():
                return None
            raise
        finally:
            self._dump_guard.release()

    # -- fatal-path arming ---------------------------------------------------

    def install(self, path: str, *, signals: Optional[tuple] = None,
                on_exception: bool = True) -> None:
        """Arm the recorder: dump to ``path`` on SIGTERM/SIGABRT (or the
        given ``signals``) and — with ``on_exception`` — on any unhandled
        exception.  Must run on the MAIN thread (CPython delivers
        signals there; ``signal.signal`` enforces it).  The previous
        dispositions are saved and re-raised after the dump, so exit
        codes are preserved.  ``uninstall()`` restores everything."""
        import signal as _signal

        if signals is None:
            signals = (_signal.SIGTERM, _signal.SIGABRT)
        with self._lock:
            self._path = path

        def _on_signal(signum, frame):
            try:
                name = _signal.Signals(signum).name
            except ValueError:  # pragma: no cover — unnamed signal
                name = str(signum)
            # emit_event=False: the spine's locks may be held by the
            # very frame this handler interrupted (see dump docstring)
            self.dump(reason=f"signal:{name}", emit_event=False)
            # restore the pre-install disposition and re-raise so the
            # process still dies with the right exit status (TERM→143).
            # A None previous handler means it was installed at the C
            # level (e.g. faulthandler) — SIG_DFL is the only honest
            # restore signal.signal accepts for it
            prev = self._prev_handlers.get(signum)
            _signal.signal(
                signum, prev if prev is not None else _signal.SIG_DFL
            )
            os.kill(os.getpid(), signum)

        for signum in signals:
            self._prev_handlers[signum] = _signal.signal(
                signum, _on_signal
            )
        self._installed_signals = tuple(signals)
        if on_exception:
            prev_hook = sys.excepthook
            self._prev_excepthook = prev_hook

            def _on_exception(exc_type, exc, tb):
                self.dump(
                    reason=f"unhandled_exception:{exc_type.__name__}"
                )
                prev_hook(exc_type, exc, tb)

            sys.excepthook = _on_exception

    def uninstall(self) -> None:
        """Restore the previous signal dispositions and excepthook.
        Idempotent."""
        import signal as _signal

        for signum in self._installed_signals:
            prev = self._prev_handlers.pop(signum, None)
            if prev is not None:
                _signal.signal(signum, prev)
        self._installed_signals = ()
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None


# -- tracing spans (schema v2) ------------------------------------------------


class Span:
    """One in-flight span: identity + start time.  Create with
    ``start_span``/``span``; ids are run-unique strings (run token from
    ``configure()`` + a process-wide sequence)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0")

    def __init__(self, name, trace_id, span_id, parent_id, t0):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0

    def __repr__(self):  # pragma: no cover — debugging aid
        return (
            f"Span({self.name!r}, trace={self.trace_id}, "
            f"span={self.span_id}, parent={self.parent_id})"
        )


_SPAN_TLS = threading.local()  # .current: the active Span of this thread
_SPAN_LOCK = threading.Lock()
_SPAN_SEQ = 0
# run token: regenerated by configure() so ids stay unique across the
# multiple runs that may append to one telemetry file
_RUN_TOKEN = "0"


def _new_span_id() -> str:
    global _SPAN_SEQ
    with _SPAN_LOCK:
        _SPAN_SEQ += 1
        return f"{_RUN_TOKEN}-{_SPAN_SEQ:x}"


def current_span() -> Optional[Span]:
    """This thread's innermost active span (set by ``span()`` /
    ``activate_span``), or None."""
    return getattr(_SPAN_TLS, "current", None)


def trace_fields() -> dict:
    """``{"trace_id", "span_id"}`` of the thread's active span — splice
    into flat events (``emit(..., **trace_fields())``) so dispatches,
    hash batches and degraded retries correlate with their batch trace.
    Empty when no span is active (the event stays v1-shaped)."""
    cur = current_span()
    if cur is None:
        return {}
    return {"trace_id": cur.trace_id, "span_id": cur.span_id}


def start_span(name: str, *, parent: Optional[Span] = None,
               new_trace: bool = False, require_parent: bool = False,
               **attrs) -> Optional[Span]:
    """Open a span and emit its ``span_start``; returns None (a no-op
    handle) when neither a sink nor a live subscriber is installed.

    Parenting: explicit ``parent=`` wins; otherwise the thread's active
    span; ``new_trace=True`` forces a fresh trace root (``parent_id``
    null).  ``require_parent=True`` skips the span entirely when there
    is no parent in scope — used by instrumented stages that only make
    sense inside a batch trace.  Close with ``end_span`` (any thread).
    """
    if not enabled():
        return None
    try:
        if parent is None and not new_trace:
            parent = current_span()
        if parent is None and require_parent and not new_trace:
            return None
        span_id = _new_span_id()
        if new_trace or parent is None:
            trace_id, parent_id = span_id, None
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        s = Span(name, trace_id, span_id, parent_id, time.perf_counter())
        emit(
            EVENTS.SPAN_START, name=name, trace_id=trace_id,
            span_id=span_id, parent_id=parent_id, **attrs,
        )
        return s
    except Exception:
        if _finalizing():
            return None
        raise


def end_span(span_: Optional[Span], **attrs) -> None:
    """Emit the ``span_end`` for a span returned by ``start_span`` (from
    any thread).  None (disabled-telemetry handle) is a no-op; safe at
    interpreter teardown."""
    if span_ is None:
        return
    try:
        emit(
            EVENTS.SPAN_END, name=span_.name, trace_id=span_.trace_id,
            span_id=span_.span_id,
            dur_s=round(time.perf_counter() - span_.t0, 9), **attrs,
        )
    except Exception:
        if _finalizing():
            return
        raise


@contextlib.contextmanager
def activate_span(span_: Optional[Span]):
    """Make ``span_`` this thread's active span for the block — the
    explicit cross-thread propagation primitive: a consumer adopting a
    trace root the producer created re-activates it around its own
    stages so their spans parent correctly.  Does NOT end the span.
    None (telemetry disabled) is a cheap no-op."""
    if span_ is None:
        yield None
        return
    prev = getattr(_SPAN_TLS, "current", None)
    _SPAN_TLS.current = span_
    try:
        yield span_
    finally:
        _SPAN_TLS.current = prev


@contextlib.contextmanager
def span(name: str, *, parent: Optional[Span] = None,
         new_trace: bool = False, require_parent: bool = False, **attrs):
    """Context manager: ``start_span`` + thread-local activation +
    ``end_span``.  Yields the ``Span`` (None when telemetry is off)."""
    s = start_span(
        name, parent=parent, new_trace=new_trace,
        require_parent=require_parent, **attrs,
    )
    if s is None:
        yield None
        return
    try:
        with activate_span(s):
            yield s
    finally:
        end_span(s)


def parse_event(line: str) -> dict:
    """Parse + validate one JSONL event line (the shipped round-trip
    parser: anything ``TelemetryLog.emit`` writes, this loads back).
    Raises ``ValueError`` on malformed lines or unsupported versions."""
    try:
        rec = json.loads(line)
    except json.JSONDecodeError as e:
        raise ValueError(f"not a JSON event line: {line!r}") from e
    if not isinstance(rec, dict):
        raise ValueError(f"event line is not an object: {line!r}")
    if rec.get("v") not in SUPPORTED_SCHEMA_VERSIONS:
        raise ValueError(
            f"unsupported telemetry schema version {rec.get('v')!r} "
            f"(supported: {sorted(SUPPORTED_SCHEMA_VERSIONS)})"
        )
    if not isinstance(rec.get("event"), str) or not isinstance(
        rec.get("ts"), (int, float)
    ):
        raise ValueError(f"event line missing 'event'/'ts': {line!r}")
    return rec


def read_events(path: str) -> Iterator[dict]:
    """Iterate the validated events of a JSONL telemetry file.  A torn
    FINAL line (crash mid-write) is tolerated and skipped; a torn line
    anywhere else raises — that file is corrupt, not merely truncated.
    Streams with one line of lookahead (O(1) memory): a long run's
    multi-GB event log never has to fit in host memory to be read."""
    with open(path) as f:
        pending: Optional[str] = None
        for line in f:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            if pending is not None:
                yield parse_event(pending)  # non-final: torn ⇒ raise
            pending = line
        if pending is not None:
            try:
                yield parse_event(pending)
            except ValueError:  # torn final line: tolerated
                return


# -- OpenMetrics / Prometheus text exposition --------------------------------


def _om_name(name: str) -> str:
    """Metric name → OpenMetrics-legal name (``rp_`` namespace, dots and
    other separators to underscores)."""
    return "rp_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _om_num(v) -> str:
    """Render a sample value; OpenMetrics wants plain decimal/scientific
    (repr of a Python float qualifies; ints stay ints)."""
    if isinstance(v, bool):  # pragma: no cover — no bool metrics today
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _merge_snapshots(snapshots) -> dict:
    """Merge ``MetricsRegistry.snapshot()`` dicts (the default registry
    plus per-stream registries) into one: counters and histogram
    sums/counts/buckets add; gauges combine max-of-max, sum/n add, and
    the later snapshot's ``last`` wins."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snapshots:
        for k, v in (snap.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, g in (snap.get("gauges") or {}).items():
            m = out["gauges"].setdefault(
                k, {"last": 0, "max": 0, "sum": 0.0, "n": 0}
            )
            m["last"] = g["last"]
            m["max"] = max(m["max"], g["max"]) if m["n"] else g["max"]
            m["sum"] += g["sum"]
            m["n"] += g["n"]
        for k, h in (snap.get("histograms") or {}).items():
            m = out["histograms"].setdefault(
                k, {"sum": 0.0, "count": 0, "buckets": {}}
            )
            m["sum"] += h["sum"]
            m["count"] += h["count"]
            for b, c in (h.get("buckets") or {}).items():
                m["buckets"][str(b)] = m["buckets"].get(str(b), 0) + c
    return out


def to_openmetrics(*snapshots: dict) -> str:
    """Render one or more ``MetricsRegistry.snapshot()`` dicts as an
    OpenMetrics/Prometheus text exposition (pure text — scrape it from a
    file or paste it into a pushgateway; no HTTP server involved).

    Mapping: counters → ``<name>_total``; gauges → three gauges
    (``<name>`` = last sample, ``<name>_max``, ``<name>_mean``);
    wall-clock histograms → a ``<name>_seconds`` histogram whose
    ``le`` boundaries are the registry's fixed log2 bucket upper edges
    (bucket *i* = ``[2^i, 2^(i+1))`` µs ⇒ ``le = 2^(i+1)·1e-6`` s),
    cumulative per the spec, with exact ``_sum``/``_count`` — PLUS a
    sibling ``<name>_seconds_quantile`` summary carrying
    p50/p90/p99/p99.9 extracted from the buckets
    (``quantiles_from_buckets``: exact for 0/1 samples, within one log2
    bucket otherwise), the serve-latency tail numbers a scrape needs
    without re-deriving them from bucket math.  Output is
    deterministically ordered and ends with ``# EOF``.
    """
    m = _merge_snapshots(snapshots)
    lines = []
    for name in sorted(m["counters"]):
        om = _om_name(name)
        lines.append(f"# TYPE {om} counter")
        lines.append(f"{om}_total {_om_num(m['counters'][name])}")
    for name in sorted(m["gauges"]):
        g = m["gauges"][name]
        om = _om_name(name)
        mean = g["sum"] / g["n"] if g["n"] else 0.0
        for suffix, v in (("", g["last"]), ("_max", g["max"]),
                          ("_mean", mean)):
            lines.append(f"# TYPE {om}{suffix} gauge")
            lines.append(f"{om}{suffix} {_om_num(v)}")
    for name in sorted(m["histograms"]):
        h = m["histograms"][name]
        om = _om_name(name) + "_seconds"
        lines.append(f"# TYPE {om} histogram")
        cum = 0
        for b in sorted(int(k) for k in h["buckets"]):
            cum += h["buckets"][str(b)]
            le = _om_num((1 << (b + 1)) * 1e-6)
            lines.append(f'{om}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{om}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{om}_sum {_om_num(h['sum'])}")
        lines.append(f"{om}_count {h['count']}")
        qs = quantiles_from_buckets(h["buckets"], h["count"], h["sum"])
        if qs["count"]:
            qom = om + "_quantile"
            lines.append(f"# TYPE {qom} summary")
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"),
                           (0.999, "p99.9")):
                lines.append(
                    f'{qom}{{quantile="{q}"}} {_om_num(qs[key])}'
                )
            lines.append(f"{qom}_sum {_om_num(h['sum'])}")
            lines.append(f"{qom}_count {h['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
