"""``make live-smoke``: prove the live observability plane end to end.

Runs a real ``stream-bench --metrics-port 0`` (the actual CLI path: the
flag subscribes a ``LiveAggregator`` and starts the HTTP endpoint) on a
worker thread, and scrapes ``/metrics`` over REAL HTTP while the bench
is still streaming.  The smoke passes only when one scrape taken
mid-run is a valid OpenMetrics exposition that contains:

- histogram ``_bucket{le=...}`` lines AND the new
  ``_quantile{quantile=...}`` summary lines (the r17 extension), and
- a NONZERO span-derived live gauge (``rp_live_span_*_wall_s``) — the
  proof that spans flowed emitter → subscriber queue → dispatch thread
  → rolling window → exposition while the run was live, with no JSONL
  file anywhere.

Exit 0 on success (prints ``live-smoke OK``), 1 with diagnostics
otherwise.  Run by ``make verify`` before tier-1 (ISSUE r17 satellite).
"""

from __future__ import annotations

import sys
import threading
import time

__all__ = ["main"]

_BENCH_ARGS = [
    "stream-bench", "--rows", "600000", "--d", "256", "--k", "32",
    "--batch-rows", "8192", "--backend", "numpy",
    "--prefetch-batches", "2", "--metrics-port", "0",
]


def _validate(text: str) -> dict:
    """Predicate bundle over one scrape; returns the check dict (all
    True = the smoke's mid-run scrape is good)."""
    from randomprojection_tpu.utils.metrics_server import parse_openmetrics

    plain, labeled = parse_openmetrics(text)
    live_span_nonzero = any(
        name.startswith("rp_live_span_") and name.endswith("_wall_s")
        and value > 0
        for name, value in plain.items()
    )
    return {
        "eof_terminated": text.endswith("# EOF\n"),
        "parses": bool(plain) or bool(labeled),
        "histogram_buckets": any(
            name.endswith("_bucket") for name in labeled
        ),
        "quantile_lines": any(
            name.endswith("_quantile") for name in labeled
        ),
        "live_span_gauge_nonzero": live_span_nonzero,
    }


def main(argv=None) -> int:
    from randomprojection_tpu import cli
    from randomprojection_tpu.utils.metrics_server import fetch_metrics

    bench_err: list = []

    def bench():
        try:
            cli.main(list(_BENCH_ARGS))
        except BaseException as e:  # surfaced after join, below
            bench_err.append(e)

    good: dict = {}
    last_checks: dict = {}
    scrapes = 0
    t = threading.Thread(target=bench, name="rp-live-smoke-bench",
                         daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            server = cli._METRICS_SERVER
            if server is None:
                if not t.is_alive() and scrapes == 0:
                    break  # bench died before the endpoint came up
                time.sleep(0.02)
                continue
            try:
                port = server.port
                text = fetch_metrics("127.0.0.1", port, timeout=5.0)
            except OSError:
                # the run (and its endpoint) just ended — stop scraping
                if not t.is_alive():
                    break
                time.sleep(0.02)
                continue
            scrapes += 1
            checks = _validate(text)
            last_checks = checks
            if all(checks.values()):
                good = checks
                break
            time.sleep(0.05)
    finally:
        # bounded: a wedged stream-bench (the daemon thread never
        # exiting) must fail the smoke loudly, not hang `make verify`
        t.join(timeout=60.0)
    if t.is_alive():
        print(
            "live-smoke FAIL: stream-bench wedged — its thread is "
            "still alive 60s after the scrape deadline",
            file=sys.stderr,
        )
        return 1
    if bench_err:
        print(f"live-smoke FAIL: stream-bench raised: {bench_err[0]!r}",
              file=sys.stderr)
        return 1
    if not good:
        detail = (
            f"last scrape's checks: {last_checks}"
            if scrapes
            else "endpoint never answered — did --metrics-port start?"
        )
        print(
            f"live-smoke FAIL: no mid-run scrape satisfied every check "
            f"({scrapes} scrape(s) taken; {detail})",
            file=sys.stderr,
        )
        return 1
    print(
        f"live-smoke OK: mid-run HTTP scrape is valid OpenMetrics with "
        f"histogram buckets + quantile summaries and a nonzero "
        f"span-derived live gauge ({scrapes} scrape(s))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
