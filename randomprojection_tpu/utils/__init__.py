from randomprojection_tpu.utils.validation import (
    DataDimensionalityWarning,
    NotFittedError,
    check_array,
    check_density,
    check_input_size,
    resolve_transform_dtype,
)

__all__ = [
    "DataDimensionalityWarning",
    "NotFittedError",
    "check_array",
    "check_density",
    "check_input_size",
    "resolve_transform_dtype",
]
