"""Parameter and input validation helpers (layer L0).

Contract: sklearn ``random_projection.py:149-166`` (``_check_density``,
``_check_input_size``) and the input-validation behavior of
``BaseRandomProjection.fit`` (``random_projection.py:367-433``); see
``SURVEY.md`` §3.1.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

__all__ = [
    "DataDimensionalityWarning",
    "bfloat16_dtype",
    "restore_void_dtype",
    "check_density",
    "check_input_size",
    "check_array",
    "resolve_transform_dtype",
    "NotFittedError",
]


def bfloat16_dtype():
    """np.dtype of bfloat16 (via ml_dtypes), or None when unavailable."""
    try:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        return None


def restore_void_dtype(arr, want=None):
    """Undo ``.npy``'s label degradation for ml_dtypes arrays.

    ``np.save`` of a bfloat16 array writes a raw-void header (``|V2``) —
    the format cannot express the name — so ``np.load`` returns unusable
    void data.  When the array is unstructured 2-byte void and bfloat16 is
    either the expected dtype (``want``) or the only plausible producer
    (this stack writes no other 2-byte void), restore the typed view;
    anything else passes through for the caller's validation to reject
    loudly.
    """
    dtype = getattr(arr, "dtype", None)
    if dtype is None or dtype.kind != "V" or dtype.names is not None:
        return arr
    bf16 = bfloat16_dtype()
    if bf16 is None or dtype.itemsize != 2:
        return arr
    if want is not None and np.dtype(want) != bf16:
        return arr
    return arr.view(bf16)


class DataDimensionalityWarning(UserWarning):
    """The number of components exceeds the data dimensionality.

    Raised-as-warning when a user-fixed ``n_components > n_features``: the
    projection then *increases* dimensionality, which is allowed but almost
    certainly a mistake (contract: ``random_projection.py:410-418``).
    """


class NotFittedError(ValueError, AttributeError):
    """Estimator used before ``fit`` (contract: sklearn ``NotFittedError``)."""


def check_density(density, n_features: int) -> float:
    """Resolve and validate the sparse-kernel density parameter.

    ``'auto'`` resolves to ``1/sqrt(n_features)`` (Li, Hastie & Church 2006);
    otherwise density must lie in ``(0, 1]`` (``random_projection.py:149-156``).
    """
    if density == "auto":
        if n_features <= 0:
            raise ValueError(
                f"n_features must be strictly positive to resolve density='auto', "
                f"got {n_features}"
            )
        return 1.0 / np.sqrt(n_features)
    density = float(density)
    if density <= 0.0 or density > 1.0:
        raise ValueError(f"Expected density in range (0, 1], got: {density!r}")
    return density


def check_input_size(n_components: int, n_features: int) -> None:
    """Reject non-positive matrix dimensions (``random_projection.py:159-166``)."""
    if n_components <= 0:
        raise ValueError(f"n_components must be strictly positive, got {n_components}")
    if n_features <= 0:
        raise ValueError(f"n_features must be strictly positive, got {n_features}")


def check_array(X, *, accept_sparse: bool = True, allow_1d: bool = False):
    """Validate an input batch: 2-D, numeric, dense ndarray or CSR/CSC.

    Returns the array unchanged when already acceptable (no copy): dense
    inputs as ``np.ndarray`` (or any ``__array__``-convertible, converted),
    sparse inputs converted to CSR.  Dense 1-D inputs raise unless
    ``allow_1d``; sparse inputs must always be 2-D.
    """
    if sp.issparse(X):
        if not accept_sparse:
            raise TypeError(
                "Sparse input is not supported here; densify with .toarray() first"
            )
        X = X.tocsr()
        if X.ndim != 2:
            raise ValueError(f"Expected 2D sparse input, got ndim={X.ndim}")
        return X
    X = np.asarray(X)
    if X.ndim == 1 and not allow_1d:
        raise ValueError(
            f"Expected 2D array, got 1D array of shape {X.shape}. "
            "Reshape with X.reshape(1, -1) for a single sample."
        )
    if X.ndim not in (1, 2):
        raise ValueError(f"Expected 2D array, got ndim={X.ndim}")
    if (
        not np.issubdtype(X.dtype, np.number)
        and X.dtype != bool
        and X.dtype != bfloat16_dtype()
    ):
        raise ValueError(f"Expected numeric input, got dtype {X.dtype}")
    return X


def resolve_transform_dtype(dtype) -> np.dtype:
    """Dtype policy: f32 in → f32 out; f64 in → f64 out; bf16 in → bf16 out
    (TPU-native extension — halves the host↔device bytes, SURVEY.md §7 R3);
    everything else (ints, bool, f16) promotes to f64
    (``random_projection.py:386-387``, ``test_random_projection.py:547-567``;
    IEEE f16 keeps the sklearn promotion contract — only the TPU-native
    bfloat16 gets the pass-through)."""
    dtype = np.dtype(dtype)
    if dtype in (np.dtype(np.float32), np.dtype(np.float64)):
        return dtype
    bf16 = bfloat16_dtype()
    if bf16 is not None and dtype == bf16:
        return bf16
    return np.dtype(np.float64)
