"""``make health-smoke``: prove the health plane end to end (r20).

Three scenarios, all through real surfaces (the CLI flags, real HTTP,
a real subprocess kill) — no detector is driven by hand:

1. **SLO burn-rate fires and clears** — a real ``loadgen --health``
   run with an absurdly tight latency target (every request violates)
   and a ``--settle`` window: mid-run ``GET /health`` must answer 503
   with a ``health.slo_burn`` verdict active; during the settle window
   (offered load gone, windows drain) it must flip back to 200; and
   the ``--telemetry-jsonl`` file must carry both the ``firing`` and
   the ``cleared`` lifecycle events.
2. **Induced stall trips the watchdog** — an in-process
   ``HealthEngine`` with a 1 s stall timeout watches a stage that
   heartbeats span events while the queue-depth signal sits pinned,
   then goes silent: ``health.stall`` must fire within the configured
   timeout (plus tick slack), and the watchdog trip must dump the
   attached ``FlightRecorder``.
3. **SIGTERM leaves a postmortem** — a real ``stream-bench
   --flight-dump`` subprocess is killed with SIGTERM mid-run: the
   process must die by that signal, the dump must exist and parse, and
   ``cli doctor --postmortem`` must render it naming a real pipeline
   stage as last-active at death.

Exit 0 on success (prints ``health-smoke OK``), 1 with per-scenario
diagnostics otherwise.  Run by ``make verify`` before tier-1.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

__all__ = ["main"]


def _get_health(port: int) -> tuple:
    """``(status, body_dict)`` for one ``GET /health`` probe."""
    url = f"http://127.0.0.1:{port}/health"
    try:
        with urllib.request.urlopen(url, timeout=5.0) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _scenario_burn(tmp: str) -> list:
    """Scenario 1: loadgen overload ⇒ 503 + firing, settle ⇒ 200 +
    cleared.  Returns a list of failure strings (empty = pass)."""
    from randomprojection_tpu import cli
    from randomprojection_tpu.utils.telemetry import EVENTS, read_events

    jsonl = os.path.join(tmp, "burn_telemetry.jsonl")
    args = [
        "loadgen", "--rate", "150", "--duration", "2",
        "--index-codes", "2048", "--code-bytes", "16", "--m", "4",
        "--request-rows", "8,16", "--metrics-port", "0",
        # 0.001 ms p99 target: every request violates ⇒ burn = 1/budget;
        # short windows so the settle window is long enough to clear
        "--health", "0.001,fast=1,slow=2.5,tick=0.1,stall=30",
        "--settle", "6", "--telemetry-jsonl", jsonl,
    ]
    err: list = []

    def run():
        try:
            cli.main(list(args))
        except BaseException as e:  # surfaced after join, below
            err.append(f"loadgen raised: {e!r}")

    saw_503 = False
    saw_200_after = False
    t = threading.Thread(target=run, name="rp-health-smoke-loadgen",
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline and t.is_alive():
        server = cli._METRICS_SERVER
        if server is None:
            time.sleep(0.02)
            continue
        try:
            code, _ = _get_health(server.port)
        except OSError:
            time.sleep(0.05)
            continue
        if code == 503:
            saw_503 = True
        elif code == 200 and saw_503:
            saw_200_after = True
            break
        time.sleep(0.1)
    t.join(timeout=60.0)
    fails = list(err)
    if t.is_alive():
        return fails + ["loadgen wedged: thread alive after 60s join"]
    if not saw_503:
        fails.append(
            "GET /health never answered 503 while every request "
            "violated the 0.001ms target"
        )
    if saw_503 and not saw_200_after:
        fails.append(
            "GET /health never recovered to 200 during the --settle "
            "window"
        )
    statuses = set()
    if os.path.exists(jsonl):
        for e in read_events(jsonl):
            if e.get("event") == EVENTS.HEALTH_SLO_BURN:
                statuses.add(e.get("status"))
    if "firing" not in statuses or "cleared" not in statuses:
        fails.append(
            f"telemetry JSONL carries health.slo_burn statuses "
            f"{sorted(statuses)}, want both 'firing' and 'cleared'"
        )
    return fails


def _scenario_stall(tmp: str) -> list:
    """Scenario 2: heartbeat then silence with a pinned queue ⇒
    ``health.stall`` within the configured timeout, and a watchdog-trip
    flight dump."""
    from randomprojection_tpu.utils import health, telemetry
    from randomprojection_tpu.utils.telemetry import EVENTS

    dump_path = os.path.join(tmp, "stall_dump.json")
    timeout_s = 1.0
    recorder = telemetry.FlightRecorder()
    rec_sub = telemetry.subscribe(recorder, name="flight-recorder")
    try:
        recorder.install(dump_path, signals=(), on_exception=False)
        engine = health.HealthEngine(
            slo=health.parse_slo_spec(f"stall={timeout_s},tick=0.1"),
            recorder=recorder,
        ).start()
    except BaseException:
        # the r17 bug shape: a failed downstream acquire must not leak
        # the already-live subscription (its dispatch thread would pin
        # the process)
        telemetry.unsubscribe(rec_sub)
        raise
    recorder.attach_health(engine.active)
    fails: list = []
    try:
        # the stage heartbeats while the queue-depth signal pins at
        # capacity... then everything goes silent (the wedge)
        for _ in range(5):
            with telemetry.span("hash"):
                pass
            telemetry.emit(
                EVENTS.STREAM_PREFETCH_DELIVER, queue_depth=2, capacity=2
            )
            time.sleep(0.02)
        silent_t0 = time.monotonic()
        fired_at = None
        while time.monotonic() - silent_t0 < timeout_s * 4 + 2.0:
            if any(
                v["detector"] == EVENTS.HEALTH_STALL
                for v in engine.active()
            ):
                fired_at = time.monotonic() - silent_t0
                break
            time.sleep(0.05)
        if fired_at is None:
            fails.append(
                f"health.stall never fired within "
                f"{timeout_s * 4 + 2.0:.1f}s of silence"
            )
        elif fired_at < timeout_s:
            fails.append(
                f"health.stall fired after only {fired_at:.2f}s of "
                f"silence — before the {timeout_s}s timeout"
            )
        # the watchdog trip must have dumped the flight recorder
        t0 = time.monotonic()
        while not os.path.exists(dump_path) and time.monotonic() - t0 < 5:
            time.sleep(0.05)
        if not os.path.exists(dump_path):
            fails.append("watchdog trip left no flight-recorder dump")
        else:
            with open(dump_path) as f:
                dump = json.load(f)
            if not str(dump.get("reason", "")).startswith("watchdog:"):
                fails.append(
                    f"dump reason {dump.get('reason')!r} is not a "
                    "watchdog trip"
                )
    finally:
        engine.close()
        recorder.uninstall()
        telemetry.unsubscribe(rec_sub)
    return fails


def _scenario_sigterm(tmp: str) -> list:
    """Scenario 3: SIGTERM a real ``stream-bench --flight-dump`` run,
    then render the dump with ``doctor --postmortem``."""
    from randomprojection_tpu import cli

    dump_path = os.path.join(tmp, "sigterm_dump.json")
    jsonl = os.path.join(tmp, "sigterm_telemetry.jsonl")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "randomprojection_tpu", "stream-bench",
            "--rows", "80000000", "--d", "256", "--k", "32",
            "--batch-rows", "8192", "--backend", "numpy",
            "--prefetch-batches", "2", "--flight-dump", dump_path,
            "--telemetry-jsonl", jsonl,
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    fails: list = []
    try:
        # wait until the pipeline is demonstrably mid-flight (span
        # events on the JSONL), then kill
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if proc.poll() is not None:
                return [
                    f"stream-bench exited rc={proc.returncode} before "
                    "the kill — rows too low to stay busy?"
                ]
            if os.path.exists(jsonl) and os.path.getsize(jsonl) > 4096:
                break
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return ["stream-bench did not die within 30s of SIGTERM"]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    if rc != -signal.SIGTERM:
        # the handler must re-raise so the exit code stays the
        # signal's, not a clean 0 that would fool a supervisor
        fails.append(f"exit code {rc}, want SIGTERM death (-15)")
    if not os.path.exists(dump_path):
        return fails + ["SIGTERM left no flight-recorder dump"]
    with open(dump_path) as f:
        dump = json.load(f)
    if not str(dump.get("reason", "")).startswith("signal:"):
        fails.append(
            f"dump reason {dump.get('reason')!r}, want 'signal:SIGTERM'"
        )
    # the doctor face: render through the real CLI and check it names
    # a real pipeline stage as last-active
    from io import StringIO

    buf = StringIO()
    stdout, sys.stdout = sys.stdout, buf
    try:
        cli.main(["doctor", "--postmortem", dump_path])
    except BaseException as e:
        fails.append(f"doctor --postmortem raised: {e!r}")
    finally:
        sys.stdout = stdout
    text = buf.getvalue()
    known_stages = ("hash", "enqueue_wait", "h2d", "dispatch", "d2h",
                    "batch")
    named = None
    for line in text.splitlines():
        if line.startswith("  last active stage:"):
            named = line.split(":", 1)[1].strip()
    if named not in known_stages:
        fails.append(
            f"doctor --postmortem named last-active stage {named!r}, "
            f"want one of {known_stages}"
        )
    return fails


def main(argv=None) -> int:
    failures: dict = {}
    with tempfile.TemporaryDirectory(prefix="rp_health_smoke_") as tmp:
        for name, fn in (
            ("slo-burn-rate", _scenario_burn),
            ("stall-watchdog", _scenario_stall),
            ("sigterm-postmortem", _scenario_sigterm),
        ):
            fails = fn(tmp)
            if fails:
                failures[name] = fails
    if failures:
        for name, fails in failures.items():
            for f in fails:
                print(f"health-smoke FAIL [{name}]: {f}",
                      file=sys.stderr)
        return 1
    print(
        "health-smoke OK: SLO burn-rate fired and cleared over real "
        "HTTP (503→200) with both lifecycle events on the JSONL, an "
        "induced stall tripped the watchdog inside its timeout and "
        "dumped the flight recorder, and a SIGTERM'd stream-bench left "
        "a postmortem the doctor renders with the last-active stage"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
