"""Observability: per-batch stream counters + profiler hooks (SURVEY.md §6).

The reference's only instrumentation is Python warnings (and Spark's web UI
on the spark backend); here streams carry structured counters and any
transform region can be wrapped in a ``jax.profiler`` trace for
TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Optional

logger = logging.getLogger("randomprojection_tpu")

__all__ = ["StreamStats", "batch_nbytes", "profile_trace", "annotate", "logger"]


def batch_nbytes(batch) -> int:
    """Payload bytes of one (dense or scipy-sparse) batch.

    scipy sparse carries its payload in per-format component arrays and
    exposes no ``.nbytes`` itself — a bare ``getattr(batch, 'nbytes', 0)``
    silently records 0 for every sparse stream.  CSR/CSC/BSR count
    data+indices+indptr, COO data+coords (or row/col on pre-array scipy),
    DIA data+offsets."""
    import numpy as np
    import scipy.sparse as sp

    if not sp.issparse(batch):
        return int(getattr(batch, "nbytes", 0))
    data = getattr(batch, "data", None)
    total = int(data.nbytes) if isinstance(data, np.ndarray) else 0
    coords = getattr(batch, "coords", None)
    if isinstance(coords, tuple):  # COO; .row/.col are views of .coords
        return total + sum(int(c.nbytes) for c in coords)
    for a in ("indices", "indptr", "row", "col", "offsets"):
        v = getattr(batch, a, None)
        if isinstance(v, np.ndarray):
            total += int(v.nbytes)
    return total


class StreamStats:
    """Running counters for a streamed transform.

    Pass to ``stream_transform(..., stats=...)``; updated at every commit
    (host materialization), so throughput includes the full h2d → einsum →
    d2h pipeline, not just dispatch.
    """

    def __init__(self, log_every: int = 0):
        self.log_every = log_every
        self.batches = 0
        self.rows = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None

    def start(self) -> None:
        """Start the clock — called by ``stream_transform`` before the first
        batch is dispatched, so throughput includes the first batch's full
        h2d → einsum → d2h time (not just inter-commit gaps)."""
        self._t0 = time.perf_counter()

    def on_commit(self, start_row: int, bytes_in: int, batch_out) -> None:
        now = time.perf_counter()
        if self._t0 is None:  # standalone use without start(): degrade
            self._t0 = now
        self._t_last = now
        self.batches += 1
        n = getattr(batch_out, "shape", (0,))[0]
        self.rows += n
        self.bytes_in += bytes_in
        self.bytes_out += batch_nbytes(batch_out)
        if self.log_every and self.batches % self.log_every == 0:
            logger.info(
                "stream: %d batches, %d rows, %.0f rows/s",
                self.batches, self.rows, self.rows_per_s(),
            )

    def elapsed_s(self) -> float:
        if self._t0 is None or self._t_last is None:
            return 0.0
        return max(self._t_last - self._t0, 1e-9)

    def rows_per_s(self) -> float:
        return self.rows / self.elapsed_s() if self.rows else 0.0

    def summary(self) -> dict:
        return {
            "batches": self.batches,
            "rows": self.rows,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "elapsed_s": round(self.elapsed_s(), 4),
            "rows_per_s": round(self.rows_per_s(), 1),
        }


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Wrap a region in ``jax.profiler.trace`` (no-op when ``log_dir`` is
    falsy, so callers can thread a ``--profile-dir`` flag unconditionally)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region visible in profiler timelines.

    No-op unless jax is already imported: profiler stages only exist on the
    jax path, and the numpy-only path must never pull jax in (the
    ``backend='numpy'`` no-jax invariant, ``backends/jax_backend.py``).
    """
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    return jax.profiler.TraceAnnotation(name)
