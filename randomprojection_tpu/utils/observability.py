"""Observability: per-batch stream counters + profiler hooks (SURVEY.md §6).

The reference's only instrumentation is Python warnings (and Spark's web UI
on the spark backend); here streams carry structured counters and any
transform region can be wrapped in a ``jax.profiler`` trace for
TensorBoard/Perfetto.

Since r7 the counters are backed by ``utils.telemetry.MetricsRegistry``
(counters / gauges / log2 wall-clock histograms) and every instrumented
region double-writes to the process-wide JSONL event log when one is
configured (``telemetry.configure`` / CLI ``--telemetry-jsonl``) — the
``StreamStats`` surface and ``summary()`` output are unchanged.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Optional

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS, MetricsRegistry

logger = logging.getLogger("randomprojection_tpu")

__all__ = [
    "StreamStats", "batch_nbytes", "profile_trace", "annotate", "logger",
    "stage",
]


def batch_nbytes(batch) -> int:
    """Payload bytes of one (dense or scipy-sparse) batch.

    scipy sparse carries its payload in per-format component arrays and
    exposes no ``.nbytes`` itself — a bare ``getattr(batch, 'nbytes', 0)``
    silently records 0 for every sparse stream.  CSR/CSC/BSR count
    data+indices+indptr, COO data+coords (or row/col on pre-array scipy),
    DIA data+offsets.  Formats without flat numeric component arrays
    (LIL's object-dtype row lists, DOK's dict) are *estimated* as
    ``nnz · (itemsize + index bytes)`` — counting LIL's ``.data`` directly
    would record 8 pointer bytes per ROW and DOK would record 0, the very
    silent-undercount failure this helper exists to prevent (ADVICE r5)."""
    import numpy as np
    import scipy.sparse as sp

    if not sp.issparse(batch):
        return int(getattr(batch, "nbytes", 0))
    data = getattr(batch, "data", None)
    if not isinstance(data, np.ndarray) or data.dtype == object:
        # LIL/DOK: no flat payload arrays to count — estimate the
        # COO-equivalent payload, one value + a (row, col) intp pair per
        # stored element
        return int(batch.nnz) * (
            np.dtype(batch.dtype).itemsize + 2 * np.dtype(np.intp).itemsize
        )
    total = int(data.nbytes)
    coords = getattr(batch, "coords", None)
    if isinstance(coords, tuple):  # COO; .row/.col are views of .coords
        return total + sum(int(c.nbytes) for c in coords)
    for a in ("indices", "indptr", "row", "col", "offsets"):
        v = getattr(batch, a, None)
        if isinstance(v, np.ndarray):
            total += int(v.nbytes)
    return total


def stage(stats: Optional["StreamStats"], name: str):
    """``stats.stage(name)`` when stats is given, else a span-only
    context — so pipeline stages can be instrumented unconditionally.
    Either way the region emits a tracing span when a batch trace is
    active on this thread (``require_parent``: stray stage timings
    outside any trace never start orphan traces)."""
    if stats is None:
        return telemetry.span(name, require_parent=True)
    return stats.stage(name)


class StreamStats:
    """Running counters for a streamed transform.

    Pass to ``stream_transform(..., stats=...)``; updated at every commit
    (host materialization), so throughput includes the full h2d → einsum →
    d2h pipeline, not just dispatch.

    Storage is a ``telemetry.MetricsRegistry`` (one per StreamStats, or a
    shared one passed as ``registry=``): commit counters are registry
    counters, stage walls are log2 wall-clock histograms (their exact
    ``sum`` is the ``stage_wall`` value — histograms carry the totals,
    buckets are for distribution shape), the queue-occupancy samples are a
    gauge.  The legacy attribute surface (``batches``/``rows``/
    ``bytes_in``/``bytes_out``/``stage_wall``/``queue_depth_max``) is
    preserved as read-only views of the registry, and ``summary()`` emits
    the same keys as before the re-base.

    Per-stage wall attribution: pipeline stages (``hash`` in ``TokenSource``,
    ``h2d`` in ``PrefetchSource``'s prepare step, ``dispatch``/``d2h`` in
    ``stream_transform``) wrap themselves in ``stage(name)``, accumulating
    wall-clock — the producer stages run on the prefetch worker thread, the
    consumer stages on the caller's, so with an overlapped pipeline the
    stage walls can legitimately sum to MORE than the end-to-end elapsed
    time.  That excess is the measured overlap:
    ``overlap_ratio() = 1 - elapsed / Σ stage_wall`` (clamped at 0) — 0 for
    a fully serial pipeline, → 0.5 when two equal stages fully overlap.
    ``on_queue_depth`` is the prefetch queue-occupancy gauge, sampled by
    the producer at each delivery: a max that sits at 0 means the
    consumer always had the queue drained (producer-bound stream); the
    queue capacity means the producer had to wait for space
    (consumer-bound).
    """

    def __init__(self, log_every: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        self.log_every = log_every
        self.registry = registry if registry is not None else MetricsRegistry()
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None

    # -- registry-backed views (the pre-r7 attribute surface) ---------------

    @property
    def batches(self) -> int:
        return int(self.registry.counter("stream.batches"))

    @property
    def rows(self) -> int:
        return int(self.registry.counter("stream.rows"))

    @property
    def bytes_in(self) -> int:
        return int(self.registry.counter("stream.bytes_in"))

    @property
    def bytes_out(self) -> int:
        return int(self.registry.counter("stream.bytes_out"))

    @property
    def stage_wall(self) -> dict:
        return self.registry.hist_sums("stage.")

    @property
    def queue_depth_max(self) -> int:
        return int(self.registry.gauge_max("stream.queue_depth"))

    def queue_depth_mean(self) -> float:
        return self.registry.gauge_mean("stream.queue_depth")

    # -- recording ----------------------------------------------------------

    def start(self) -> None:
        """Start the clock — called by ``stream_transform`` before the first
        batch is dispatched, so throughput includes the first batch's full
        h2d → einsum → d2h time (not just inter-commit gaps)."""
        self._t0 = time.perf_counter()

    def on_commit(self, start_row: int, bytes_in: int, batch_out) -> None:
        now = time.perf_counter()
        if self._t0 is None:  # standalone use without start(): degrade
            self._t0 = now
        self._t_last = now
        n = getattr(batch_out, "shape", (0,))[0]
        out_bytes = batch_nbytes(batch_out)
        r = self.registry
        r.counter_inc("stream.batches")
        r.counter_inc("stream.rows", n)
        r.counter_inc("stream.bytes_in", bytes_in)
        r.counter_inc("stream.bytes_out", out_bytes)
        telemetry.emit(
            EVENTS.STREAM_COMMIT, row=int(start_row), rows=int(n),
            bytes_in=int(bytes_in), bytes_out=int(out_bytes),
            **telemetry.trace_fields(),
        )
        if self.log_every and self.batches % self.log_every == 0:
            logger.info(
                "stream: %d batches, %d rows, %.0f rows/s",
                self.batches, self.rows, self.rows_per_s(),
            )

    @contextlib.contextmanager
    def stage(self, name: str):
        """Attribute the wrapped region's wall-clock to pipeline stage
        ``name``.  Thread-safe: producer stages record from the prefetch
        worker concurrently with the consumer's dispatch/d2h stages.
        When a batch trace is active on this thread the region also
        emits a child span (v2 schema), so the per-batch critical path
        is reconstructable — ``require_parent`` keeps standalone stage
        timings from opening orphan traces."""
        t0 = time.perf_counter()
        with telemetry.span(name, require_parent=True):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self.registry.observe("stage." + name, dt)
                telemetry.emit(
                    EVENTS.STAGE_WALL, stage=name, wall_s=round(dt, 6)
                )

    def on_queue_depth(self, depth: int) -> None:
        """Record one prefetch-queue occupancy sample (taken by the
        producer at each delivery)."""
        self.registry.gauge_set("stream.queue_depth", depth)

    def overlap_ratio(self) -> float:
        """Fraction of attributed stage wall hidden by overlap:
        ``1 - elapsed / Σ stage_wall``, clamped at 0.  Exactly 0 when the
        stages ran back-to-back on one thread; approaches ``1 - 1/n`` when
        ``n`` equal stages run fully concurrently.  Only attributed stages
        count, so unattributed host work outside any ``stage()`` region
        biases the ratio DOWN (never fakes overlap)."""
        total = sum(self.stage_wall.values())
        elapsed = self.elapsed_s()
        if total <= 0.0 or elapsed <= 0.0:
            return 0.0
        return max(0.0, 1.0 - elapsed / total)

    def elapsed_s(self) -> float:
        if self._t0 is None or self._t_last is None:
            return 0.0
        return max(self._t_last - self._t0, 1e-9)

    def rows_per_s(self) -> float:
        return self.rows / self.elapsed_s() if self.rows else 0.0

    def summary(self) -> dict:
        out = {
            "batches": self.batches,
            "rows": self.rows,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "elapsed_s": round(self.elapsed_s(), 4),
            "rows_per_s": round(self.rows_per_s(), 1),
        }
        stage_wall = self.stage_wall
        if stage_wall:
            out["stage_wall_s"] = {
                k: round(v, 4) for k, v in sorted(stage_wall.items())
            }
            out["pipeline_overlap_ratio"] = round(self.overlap_ratio(), 3)
        if self.registry.gauge("stream.queue_depth")["n"]:
            out["queue_depth_max"] = self.queue_depth_max
            out["queue_depth_mean"] = round(self.queue_depth_mean(), 2)
        return out


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Wrap a region in ``jax.profiler.trace`` (no-op when ``log_dir`` is
    falsy, so callers can thread a ``--profile-dir`` flag unconditionally)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region visible in profiler timelines.

    No-op unless jax is already imported: profiler stages only exist on the
    jax path, and the numpy-only path must never pull jax in (the
    ``backend='numpy'`` no-jax invariant, ``backends/jax_backend.py``).
    """
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    return jax.profiler.TraceAnnotation(name)
