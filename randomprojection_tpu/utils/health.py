"""Health plane (ISSUE 18 / r20): typed verdicts over the live stream.

r17's live observability plane streams raw spans/events and latency
histograms, but nothing in the process *interprets* them — ROADMAP #3's
adaptive controller and #6's graded degradation need "p99 is burning
the SLO budget" / "ingest stalled" / "queue pinned" as typed, liveness-
checked verdicts, not an event firehose.  This module is the detection
half of that control loop:

- **HealthEngine** — subscribes to the live stream
  (``telemetry.subscribe``, the r17 bounded-queue/drop-never-block
  discipline: the emitting hot path can NEVER be slowed by a detector)
  and folds events into a registry of detectors over
  ``LiveAggregator``-style rolling windows.  A separate tick thread
  evaluates the detectors on a fixed cadence — required because the
  most important verdict (a stall) is precisely the case where no
  events arrive to trigger evaluation.
- **Detectors** — every verdict is a typed, EVENTS-registered
  ``health.*`` event with a firing/cleared lifecycle: emitted once on
  each transition (deduplicated), re-emitted at most every
  ``refire_s`` while still firing (rate-limited), and mirrored onto the
  process registry as ``health.<detector>.firing`` gauges so a
  ``/metrics`` scrape carries the verdict without parsing events.

  - ``BurnRateDetector`` (``health.slo_burn``) — multi-window SLO
    burn-rate over the per-server/per-label request latencies: the
    **burn rate** is the observed violation fraction divided by the
    SLO's error budget (``budget``, default 1%), so burn 1.0 = exactly
    consuming the budget, burn 100 = everything violating a 1% budget.
    A **fast** window catches a transient spike within seconds, a
    **slow** window catches a leak a spiky window would amortize away
    — each window is an independent firing condition with its own
    hysteresis (fire at ``fire_burn``, clear at ``clear_burn`` <
    ``fire_burn``), per (server, label) key.
  - ``StallWatchdog`` (``health.stall``) — per-stage span-heartbeat
    timeout: a stage that WAS emitting spans (>= ``min_events``) goes
    silent for ``timeout_s`` while the queue-depth signal stays pinned
    (last delivered depth >= 1 and itself stale) ⇒ the pipeline is
    wedged, not finished.  The queue guard is what separates a stall
    from a normal end-of-run, where depth drains to 0.  A firing
    transition trips the attached ``FlightRecorder`` (one dump per
    firing, rate-limited) so the wedge leaves evidence even if the
    operator later kills -9.
  - ``QueuePinnedDetector`` (``health.queue_pinned``) — the queue-depth
    signal has sat at capacity for a full window: classic backpressure
    collapse, distinct from a stall (stages may still be emitting,
    just slower than arrivals).
  - ``DegradedSpikeDetector`` (``health.degraded_spike``) — polls the
    degraded counters (fallback-ladder rungs,
    ``telemetry.subscriber.dropped``, ``serve.topk.rejects``) each tick
    and fires when the fast-window rate exceeds ``min_rate`` AND
    ``spike_ratio`` × the slow-window baseline — "suddenly degrading"
    rather than "has degraded events at all".

Concurrency contract (RP10/RP11): all detector state is guarded by ONE
engine lock; the subscriber callback and the tick thread both take it
for bounded folds only; events are emitted and the flight recorder
tripped strictly OUTSIDE the lock (emit fans out to subscriber queues
— never under a lock), and the engine ignores its own ``health.*``
events so verdicts cannot feed back into detectors.

``parse_slo_spec`` is the shared ``--health`` spec grammar (CLI +
loadgen record): a bare number is the default p99 target in ms,
``label=ms`` pairs set per-label targets, and the reserved keys
``budget``/``fast``/``slow``/``fire``/``clear``/``stall``/``tick``
tune the engine — the same spec text loadgen records in ``topk_slo``
(``slo_targets``), so the detector and the record grade against the
identical contract.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

# Closed set of verdict names the engine may emit, one statically
# lintable call site per event (RP02 checks emit names against the
# registry; a dynamic name is unauditable).  A detector whose ``event``
# is missing here fails loudly at emit time instead of minting a rogue
# ``health.*`` name that no consumer folds.
_VERDICT_EMIT = {
    EVENTS.HEALTH_SLO_BURN:
        lambda **f: telemetry.emit(EVENTS.HEALTH_SLO_BURN, **f),
    EVENTS.HEALTH_STALL:
        lambda **f: telemetry.emit(EVENTS.HEALTH_STALL, **f),
    EVENTS.HEALTH_QUEUE_PINNED:
        lambda **f: telemetry.emit(EVENTS.HEALTH_QUEUE_PINNED, **f),
    EVENTS.HEALTH_DEGRADED_SPIKE:
        lambda **f: telemetry.emit(EVENTS.HEALTH_DEGRADED_SPIKE, **f),
}

__all__ = [
    "parse_slo_spec",
    "BurnRateDetector",
    "StallWatchdog",
    "QueuePinnedDetector",
    "DegradedSpikeDetector",
    "HealthEngine",
    "DEFAULT_DEGRADED_COUNTERS",
]

# reserved config keys in a --health spec; anything else on the left of
# '=' is a client label with a per-label target in ms
_SPEC_KEYS = ("budget", "fast", "slow", "fire", "clear", "stall", "tick")

# counters the spike detector polls by default — the same degraded
# ladder the doctor audits post-hoc, plus the serving-tier shed counter
DEFAULT_DEGRADED_COUNTERS = (
    "telemetry.subscriber.dropped",
    "serve.topk.rejects",
    "serve.topk.errors",
    "backend.vmem_oom_retries",
    "simhash.topk_dense_fallbacks",
    "simhash.topk_scan_fallbacks",
    "index.lsh.fallbacks",
)


def parse_slo_spec(text: Optional[str]) -> dict:
    """Parse a ``--health`` spec into
    ``{"default_ms", "labels": {label: ms}, "config": {key: float}}``.

    Grammar (comma-separated): a bare number = the default p99 target
    in milliseconds for every label; ``label=ms`` = a per-label target;
    reserved keys (``budget``, ``fast``, ``slow``, ``fire``,
    ``clear``, ``stall``, ``tick``) tune the engine instead of naming
    a label.  Empty/None = no latency targets (the burn-rate detector
    stays dormant; stall/queue/spike detectors still run).  Raises
    ``ValueError`` on malformed entries.
    """
    out: dict = {"default_ms": None, "labels": {}, "config": {}}
    if not text:
        return out
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key = key.strip()
        if not eq:
            try:
                default_ms = float(key)
            except ValueError:
                raise ValueError(
                    f"--health spec entry {part!r}: want a bare "
                    "default-target number, label=ms, or a reserved "
                    f"key={_SPEC_KEYS}"
                )
            if default_ms <= 0:
                raise ValueError(
                    f"--health spec entry {part!r}: values must be > 0"
                )
            out["default_ms"] = default_ms
            continue
        if not key:
            raise ValueError(
                f"--health spec entry {part!r}: empty label"
            )
        try:
            num = float(val)
        except ValueError:
            raise ValueError(
                f"--health spec entry {part!r}: {val!r} is not a number"
            )
        if num <= 0:
            raise ValueError(
                f"--health spec entry {part!r}: values must be > 0"
            )
        if key in _SPEC_KEYS:
            out["config"][key] = num
        else:
            out["labels"][key] = num
    return out


class _Hysteresis:
    """Per-key firing/cleared state machine shared by every detector:
    transitions are recorded once (dedup), still-firing keys re-emit at
    most every ``refire_s`` (rate limit).  Mutated only under the
    engine lock; the engine drains ``transitions`` outside it."""

    __slots__ = ("firing", "since", "last_emit", "fields")

    def __init__(self):
        self.firing = False
        self.since = 0.0
        self.last_emit = 0.0
        self.fields: dict = {}


class _Detector:
    """Base detector: owns per-key hysteresis state and the transition
    queue the engine drains.  Subclasses implement ``on_event`` (fold
    one event, under the engine lock) and ``evaluate`` (recompute each
    key's condition at ``now``, under the engine lock)."""

    #: the EVENTS-registered ``health.*`` name this detector emits
    event = ""
    #: a firing critical detector turns ``GET /health`` to 503
    critical = True

    def __init__(self, *, refire_s: float = 30.0):
        self.refire_s = float(refire_s)
        self._keys: Dict[str, _Hysteresis] = {}
        self._pending: List[dict] = []

    # -- under the engine lock ----------------------------------------------

    def on_event(self, rec: dict, now: float) -> None:
        pass

    def evaluate(self, now: float) -> None:
        raise NotImplementedError

    def _set(self, key: str, firing: bool, now: float, **fields) -> None:
        st = self._keys.get(key)
        if st is None:
            if not firing:
                return
            st = self._keys[key] = _Hysteresis()
        if firing and not st.firing:
            st.firing, st.since, st.last_emit = True, now, now
            st.fields = dict(fields)
            self._pending.append({
                "key": key, "status": "firing", "since": now, **fields,
            })
        elif firing and st.firing:
            st.fields = dict(fields)
            if now - st.last_emit >= self.refire_s:
                st.last_emit = now
                self._pending.append({
                    "key": key, "status": "firing", "since": st.since,
                    **fields,
                })
        elif not firing and st.firing:
            st.firing = False
            self._pending.append({
                "key": key, "status": "cleared", "since": st.since,
                "held_s": round(now - st.since, 3), **fields,
            })

    def drain(self) -> List[dict]:
        out, self._pending = self._pending, []
        return out

    def firing_keys(self) -> List[Tuple[str, dict]]:
        return [
            (k, {"since": st.since, **st.fields})
            for k, st in sorted(self._keys.items())
            if st.firing
        ]


class BurnRateDetector(_Detector):
    """Multi-window SLO burn-rate over ``serve.latency.request`` events
    (see module docstring for the burn-rate definition).  One sample
    deque per (server, label) key holds ``slow_window_s`` of
    ``(ts, violated)`` pairs; the fast window is a suffix of the same
    deque, so memory is one entry per request in the slow window."""

    event = EVENTS.HEALTH_SLO_BURN
    critical = True

    def __init__(self, spec: dict, *, budget: float = 0.01,
                 fast_window_s: float = 5.0, slow_window_s: float = 60.0,
                 fire_burn: float = 10.0, clear_burn: Optional[float] = None,
                 min_count: int = 10, refire_s: float = 30.0):
        super().__init__(refire_s=refire_s)
        cfg = spec.get("config") or {}
        self.default_ms = spec.get("default_ms")
        self.labels = dict(spec.get("labels") or {})
        self.budget = float(cfg.get("budget", budget))
        self.fast_window_s = float(cfg.get("fast", fast_window_s))
        self.slow_window_s = float(cfg.get("slow", slow_window_s))
        self.fire_burn = float(cfg.get("fire", fire_burn))
        self.clear_burn = float(
            cfg.get("clear", clear_burn if clear_burn is not None
                    else self.fire_burn / 2.0)
        )
        if not 0 < self.budget <= 1:
            raise ValueError(f"budget must be in (0, 1], got {self.budget}")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"fast window ({self.fast_window_s}s) must be shorter "
                f"than slow ({self.slow_window_s}s)"
            )
        if self.clear_burn >= self.fire_burn:
            raise ValueError(
                f"clear_burn ({self.clear_burn}) must be below "
                f"fire_burn ({self.fire_burn}) — that gap IS the "
                "hysteresis"
            )
        self.min_count = int(min_count)
        self._samples: Dict[Tuple[str, str], deque] = {}

    def target_ms(self, label: Optional[str]) -> Optional[float]:
        if label is not None and label in self.labels:
            return self.labels[label]
        return self.default_ms

    def on_event(self, rec: dict, now: float) -> None:
        if rec.get("event") != EVENTS.SERVE_LATENCY_REQUEST:
            return
        total = rec.get("total_s")
        if not isinstance(total, (int, float)):
            return
        label = rec.get("label")
        target = self.target_ms(label)
        if target is None:
            return
        key = (str(rec.get("server") or "topk"), str(label or "*"))
        dq = self._samples.setdefault(key, deque())
        dq.append((now, total > target / 1e3))

    def _burn(self, dq: deque, now: float, window_s: float) -> Tuple[
        float, int
    ]:
        horizon = now - window_s
        count = violated = 0
        for ts, bad in reversed(dq):
            if ts < horizon:
                break
            count += 1
            violated += bad
        if count == 0:
            return 0.0, 0
        return (violated / count) / self.budget, count

    def evaluate(self, now: float) -> None:
        for (server, label), dq in self._samples.items():
            horizon = now - self.slow_window_s
            while dq and dq[0][0] < horizon:
                dq.popleft()
            for window, window_s in (("fast", self.fast_window_s),
                                     ("slow", self.slow_window_s)):
                burn, count = self._burn(dq, now, window_s)
                key = f"{server}[{label}]/{window}"
                st = self._keys.get(key)
                already = st.firing if st else False
                if already:
                    firing = burn > self.clear_burn
                else:
                    firing = burn >= self.fire_burn and (
                        count >= self.min_count
                    )
                self._set(
                    key, firing, now, server=server, label=label,
                    window=window, window_s=window_s,
                    burn=round(burn, 3), samples=count,
                    target_ms=self.target_ms(
                        None if label == "*" else label
                    ),
                    budget=self.budget,
                )


class StallWatchdog(_Detector):
    """Per-stage span-heartbeat timeout gated on a pinned queue (see
    module docstring).  ``min_events`` keeps a stage that never really
    started from counting as stalled; the queue guard keeps a finished
    run (queue drained) from counting as stalled."""

    event = EVENTS.HEALTH_STALL
    critical = True

    def __init__(self, *, timeout_s: float = 5.0, min_events: int = 3,
                 require_queue: bool = True, refire_s: float = 30.0):
        super().__init__(refire_s=refire_s)
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.min_events = int(min_events)
        self.require_queue = bool(require_queue)
        self._stages: Dict[str, Tuple[float, int]] = {}  # last ts, count
        self._queue_depth = 0
        self._queue_ts: Optional[float] = None

    def on_event(self, rec: dict, now: float) -> None:
        name = rec.get("event")
        if name in (EVENTS.SPAN_START, EVENTS.SPAN_END):
            stage = str(rec.get("name"))
            _, n = self._stages.get(stage, (0.0, 0))
            self._stages[stage] = (now, n + 1)
        elif name in (EVENTS.STREAM_PREFETCH_DELIVER,
                      EVENTS.STREAM_STAGED_DELIVER):
            self._queue_depth = rec.get("queue_depth", 0) or 0
            self._queue_ts = now

    def _queue_pinned(self, now: float) -> bool:
        # the last delivered depth persists (the r17 time-weighted
        # queue idea): a wedged consumer means no new deliver events,
        # so a PINNED queue is exactly a stale nonzero last sample
        if self._queue_ts is None:
            return False
        return self._queue_depth >= 1 and (
            now - self._queue_ts >= self.timeout_s
        )

    def evaluate(self, now: float) -> None:
        queue_ok = (not self.require_queue) or self._queue_pinned(now)
        for stage, (last_ts, n) in self._stages.items():
            silent_s = now - last_ts
            firing = (
                n >= self.min_events
                and silent_s >= self.timeout_s
                and queue_ok
            )
            self._set(
                stage, firing, now, stage=stage,
                silent_s=round(silent_s, 3), events=n,
                timeout_s=self.timeout_s,
                queue_depth=self._queue_depth,
            )


class QueuePinnedDetector(_Detector):
    """The queue-depth signal has sat at capacity for a full window:
    backpressure collapse.  Pinned-ness is tracked as "time since the
    last sample BELOW capacity" over the persisted piecewise-constant
    depth signal; any sample below capacity clears immediately."""

    event = EVENTS.HEALTH_QUEUE_PINNED
    critical = False

    def __init__(self, *, window_s: float = 5.0, refire_s: float = 30.0):
        super().__init__(refire_s=refire_s)
        self.window_s = float(window_s)
        self._capacity: Optional[int] = None
        self._depth = 0
        self._pinned_since: Optional[float] = None

    def on_event(self, rec: dict, now: float) -> None:
        if rec.get("event") not in (EVENTS.STREAM_PREFETCH_DELIVER,
                                    EVENTS.STREAM_STAGED_DELIVER):
            return
        if rec.get("capacity") is not None:
            self._capacity = rec["capacity"]
        self._depth = rec.get("queue_depth", 0) or 0
        if self._capacity is None or self._depth < self._capacity:
            self._pinned_since = None
        elif self._pinned_since is None:
            self._pinned_since = now

    def evaluate(self, now: float) -> None:
        firing = (
            self._pinned_since is not None
            and now - self._pinned_since >= self.window_s
        )
        self._set(
            "queue", firing, now, depth=self._depth,
            capacity=self._capacity,
            pinned_s=(
                round(now - self._pinned_since, 3)
                if self._pinned_since is not None else 0.0
            ),
        )


class DegradedSpikeDetector(_Detector):
    """Degraded-counter spike vs its own baseline: the engine's tick
    samples each watched counter on the process registry; fire when the
    fast-window rate is both absolutely material (``min_rate``/s) and
    ``spike_ratio`` × the slow-window baseline rate (a counter that has
    ALWAYS ticked at 5/s is a known condition, not a spike)."""

    event = EVENTS.HEALTH_DEGRADED_SPIKE
    critical = False

    def __init__(self, counters=DEFAULT_DEGRADED_COUNTERS, *,
                 fast_window_s: float = 5.0, slow_window_s: float = 60.0,
                 min_rate: float = 1.0, spike_ratio: float = 10.0,
                 refire_s: float = 30.0):
        super().__init__(refire_s=refire_s)
        self.counters = tuple(counters)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.min_rate = float(min_rate)
        self.spike_ratio = float(spike_ratio)
        self._series: Dict[str, deque] = {}  # name -> (ts, value)

    def observe(self, name: str, value: float, now: float) -> None:
        """Record one counter sample (the engine's tick feeds these —
        polling the registry is NOT an event fold, so this detector has
        no ``on_event``)."""
        dq = self._series.setdefault(name, deque())
        dq.append((now, float(value)))
        horizon = now - self.slow_window_s
        # keep one pre-horizon sample as the slow window's left endpoint
        while len(dq) > 1 and dq[1][0] <= horizon:
            dq.popleft()

    def _rate(self, dq: deque, now: float, window_s: float) -> float:
        # per-second rate over the WINDOW (increments / window_s, not
        # / observed span): a series younger than the window reads as
        # if the missing history were zero increments, so a steady
        # counter's fast and slow rates converge to the same number
        # while a burst concentrated in the fast window reads
        # (slow_window/fast_window)× hotter there — the ratio the
        # spike threshold grades
        horizon = now - window_s
        base = None
        for ts, v in dq:
            if ts <= horizon:
                base = (ts, v)
            else:
                if base is None:
                    base = (ts, v)
                break
        if base is None:
            base = dq[0]
        last_v = dq[-1][1]
        return max(last_v - base[1], 0.0) / window_s

    def evaluate(self, now: float) -> None:
        for name, dq in self._series.items():
            if not dq:
                continue
            fast = self._rate(dq, now, self.fast_window_s)
            slow = self._rate(dq, now, self.slow_window_s)
            st = self._keys.get(name)
            already = st.firing if st else False
            threshold = self.min_rate if already else max(
                self.min_rate, self.spike_ratio * slow
            )
            # hysteresis: once firing, only a fast rate back under half
            # the absolute floor clears — a spike that plateaus at the
            # firing threshold must not flap
            firing = fast >= threshold if not already else (
                fast > self.min_rate / 2.0
            )
            self._set(
                name, firing, now, counter=name,
                fast_rate=round(fast, 3), baseline_rate=round(slow, 3),
            )


class HealthEngine:
    """The health plane's runtime (see module docstring): one live-
    stream subscription folding events into detectors + one tick thread
    evaluating them, emitting ``health.*`` verdicts and mirroring
    ``health.<detector>.firing`` gauges.

    Lifecycle: ``start()`` subscribes and spawns the tick thread;
    ``close()`` reverses both (idempotent).  ``evaluate(now)`` may also
    be driven manually with an explicit clock — the detector unit tests
    pin window math that way, no threads involved.

    ``ok()`` is False while any CRITICAL detector fires — the
    ``GET /health`` 503 condition; ``active()`` lists every firing
    verdict (critical or not) for ``/health``'s body, ``doctor
    --live``, and the flight recorder's health section."""

    def __init__(self, *, slo: Optional[dict] = None,
                 detectors: Optional[list] = None,
                 tick_s: float = 0.25, maxsize: int = 2048,
                 recorder=None):
        spec = slo or {"default_ms": None, "labels": {}, "config": {}}
        cfg = spec.get("config") or {}
        self.tick_s = float(cfg.get("tick", tick_s))
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")
        if detectors is None:
            stall_s = float(cfg.get("stall", 5.0))
            detectors = [
                BurnRateDetector(spec),
                StallWatchdog(timeout_s=stall_s),
                QueuePinnedDetector(window_s=stall_s),
                DegradedSpikeDetector(),
            ]
        self.detectors = list(detectors)
        self.spec = spec
        self.recorder = recorder
        self._maxsize = int(maxsize)
        self._lock = threading.Lock()
        self._sub: Optional[telemetry.Subscription] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dumped_stalls: set = set()

    # -- live-stream face ----------------------------------------------------

    def start(self) -> "HealthEngine":
        if self._sub is not None:
            raise RuntimeError("HealthEngine already started")
        self._sub = telemetry.subscribe(
            self._on_event, maxsize=self._maxsize, name="health-engine"
        )
        self._thread = threading.Thread(
            target=self._run, name="rp-health-tick", daemon=True
        )
        self._thread.start()
        return self

    def _on_event(self, rec: dict) -> None:
        # runs on the subscription's dispatch thread; the emitting hot
        # path already paid only a put_nowait
        name = rec.get("event")
        if not isinstance(name, str) or name.startswith("health."):
            return  # verdicts must not feed back into detectors
        ts = rec.get("ts")
        now = ts if isinstance(ts, (int, float)) else time.time()
        with self._lock:
            for d in self.detectors:
                d.on_event(rec, now)

    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            self.evaluate()

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One detector pass at ``now`` (default: wall clock).  Returns
        the transitions emitted this pass (each also emitted as its
        detector's ``health.*`` event).  Callable directly with an
        explicit ``now`` for deterministic tests."""
        if now is None:
            now = time.time()
        reg = telemetry.registry()
        transitions: List[Tuple[str, dict]] = []
        gauges: List[Tuple[str, int]] = []
        with self._lock:
            for d in self.detectors:
                if isinstance(d, DegradedSpikeDetector):
                    for cname in d.counters:
                        d.observe(cname, reg.counter(cname), now)
                d.evaluate(now)
                for t in d.drain():
                    transitions.append((d.event, t))
                gauges.append((d.event, len(d.firing_keys())))
        # everything below runs OUTSIDE the lock: emit fans out to
        # subscriber queues and the dump writes a file (RP11: no
        # blocking call under a held lock)
        for gname, n in gauges:
            reg.gauge_set(f"{gname}.firing", n)
        out = []
        for event, t in transitions:
            _VERDICT_EMIT[event](**t)
            out.append({"event": event, **t})
            if (
                event == EVENTS.HEALTH_STALL
                and t["status"] == "firing"
                and self.recorder is not None
                and t["key"] not in self._dumped_stalls
            ):
                # one dump per distinct stalled stage: the wedge leaves
                # evidence even if the operator later kills -9
                self._dumped_stalls.add(t["key"])
                self.recorder.dump(reason=f"watchdog:{t['key']}")
        return out

    # -- verdict surface -----------------------------------------------------

    def active(self) -> List[dict]:
        """Every firing verdict, as plain dicts (``/health`` body,
        ``doctor --live``, flight-recorder health section)."""
        with self._lock:
            out = []
            for d in self.detectors:
                for key, fields in d.firing_keys():
                    out.append({
                        "detector": d.event, "key": key,
                        "critical": d.critical, **fields,
                    })
            return out

    def ok(self) -> bool:
        """False while any CRITICAL detector fires (``GET /health`` →
        503)."""
        with self._lock:
            return not any(
                d.critical and d.firing_keys() for d in self.detectors
            )

    def close(self) -> None:
        """Stop the tick thread and detach the subscription.
        Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._sub is not None:
            telemetry.unsubscribe(self._sub)
            self._sub = None

    def __enter__(self) -> "HealthEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
