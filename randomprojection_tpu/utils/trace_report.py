"""Per-batch critical-path attribution over the telemetry span stream.

The r7 spine records flat events; the r8 tracing layer
(``utils/telemetry.py`` schema v2) records one TRACE per streamed batch
— a root span named ``batch`` with child spans for every pipeline stage
(hash, enqueue-wait, h2d, dispatch, d2h), whichever thread ran them.
This module turns a telemetry JSONL file back into the question the
overlapped-ingest work actually asks: **which stage bounded each batch,
and where are the pipeline bubbles?**

``build_report(path)`` reconstructs per-batch timelines and computes:

- **critical-path attribution** — within each batch trace, every
  instant of the root interval is attributed to exactly one covering
  child stage (ties to the earliest-started span) or, uncovered, to the
  **bubble**; stage fractions + bubble therefore sum to exactly 100% of
  batch wall, by construction.
- **pipeline overlap** — run elapsed (span of all batch traces) vs the
  summed stage wall, the same ``1 - elapsed/Σ`` shape as
  ``StreamStats.pipeline_overlap_ratio``.
- **queue-depth-over-time** — from ``stream.prefetch.deliver`` /
  ``stream.staged.deliver`` samples.
- **degraded-event audit** — VMEM-OOM retries, dense fallbacks, top-k
  block clamps, python-path hash batches, prefetch errors.

Crash-tolerant by design: the reader already tolerates a torn final
line, and spans whose ``span_end`` never made it (the run died mid-
batch) are counted as ``orphan_starts`` and excluded from attribution
instead of poisoning it — a doctor you can point at the telemetry file
of the run that just crashed.

``render_report(report)`` renders the stage waterfall + audit as text;
``cli doctor <telemetry.jsonl>`` (alias ``report``) is the command-line
face.
"""

from __future__ import annotations

from typing import Optional

from randomprojection_tpu.utils.telemetry import (
    EVENTS,
    MetricsRegistry,
    quantiles_from_buckets,
    read_events,
    registered_event,
)

__all__ = [
    "build_report",
    "render_report",
    "build_postmortem",
    "render_postmortem",
    "DEGRADED_EVENTS",
    "HEALTH_VERDICT_EVENTS",
]

# health-plane verdict events (r20): each carries a firing/cleared
# ``status`` lifecycle; the doctor folds them into a per-detector
# transition count plus the set of keys still firing at end-of-log —
# the post-hoc twin of ``doctor --live``'s verdict view
HEALTH_VERDICT_EVENTS = (
    EVENTS.HEALTH_SLO_BURN,
    EVENTS.HEALTH_STALL,
    EVENTS.HEALTH_QUEUE_PINNED,
    EVENTS.HEALTH_DEGRADED_SPIKE,
)

# event names that mark a degraded execution path; the audit reports a
# count for each even when zero, so "nothing degraded" is an explicit
# statement, not an absence.  Names come from the central registry
# (telemetry.EVENTS) — rplint rule RP02 counts a registry entry named
# here as "consumed", closing the emitter/consumer drift loop.
DEGRADED_EVENTS = (
    EVENTS.BACKEND_VMEM_OOM_RETRY,
    EVENTS.KERNEL_DMA_FALLBACK,
    EVENTS.SIMHASH_TOPK_DENSE_FALLBACK,
    EVENTS.SIMHASH_TOPK_BLOCK_CLAMP,
    EVENTS.TOPK_KERNEL_VMEM_RETRY,
    EVENTS.TOPK_KERNEL_SCAN_FALLBACK,
    EVENTS.STREAM_PREFETCH_ERROR,
    EVENTS.STREAM_PREFETCH_SHUTDOWN_TIMEOUT,
    EVENTS.STREAM_STAGED_ERROR,
    EVENTS.STREAM_STAGED_SHUTDOWN_TIMEOUT,
    EVENTS.SERVE_TOPK_ERROR,
    EVENTS.RECOVER_CHECKSUM_MISMATCH,
    # live plane (r17): a subscriber overflowing its bounded queue means
    # the live view lost events — degraded observability, on the audit
    EVENTS.TELEMETRY_SUBSCRIBER_DROPPED,
    # LSH candidate tier (ISSUE 15): a tile whose candidate set was too
    # dense/starved served through the exact scan instead — correct but
    # sublinear no more, so the fallback rate belongs on the audit
    EVENTS.INDEX_LSH_FALLBACK,
    # tiered residency (r21): a cold chunk served through the
    # synchronous-fetch rung (upload failure, budget race, worker
    # error) stayed bit-identical but lost the overlap — the rate
    # belongs on the audit
    EVENTS.INDEX_TIER_FALLBACK,
)


class _Span:
    __slots__ = ("name", "trace_id", "parent_id", "t0", "t1")

    def __init__(self, name, trace_id, parent_id, t0, t1):
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1 = t1


def _attribute(root: _Span, children: list):
    """Sweep the root interval: every elementary sub-interval goes to the
    earliest-started covering child (one stage per instant — fractions
    stay additive) or to the bubble.  Returns
    ``(stage_seconds, bubble_seconds, batch_wall_seconds)``."""
    t0, t1 = root.t0, root.t1
    ivals = []
    for c in children:
        s, e = max(c.t0, t0), min(c.t1, t1)
        if e > s:
            ivals.append((s, e, c.name, c.t0))
    bounds = sorted({t0, t1, *(s for s, _, _, _ in ivals),
                     *(e for _, e, _, _ in ivals)})
    stage_s: dict = {}
    bubble = 0.0
    for a, b in zip(bounds, bounds[1:]):
        active = [iv for iv in ivals if iv[0] <= a and iv[1] >= b]
        if active:
            winner = min(active, key=lambda iv: (iv[3], iv[2]))[2]
            stage_s[winner] = stage_s.get(winner, 0.0) + (b - a)
        else:
            bubble += b - a
    return stage_s, bubble, t1 - t0


def build_report(path: str) -> dict:
    """Reconstruct per-batch timelines from a telemetry JSONL file and
    return the critical-path report (plain-JSON dict).

    Tolerates everything a crashed run leaves behind: a torn final line
    (skipped by the reader), ``span_start``s with no end (counted as
    orphans, excluded from attribution), span events missing their ids
    (counted as malformed, skipped), traces whose root was lost, and
    files with no spans at all (flat v1 logs — the report then carries
    only the event counts and the audit).

    Single streaming pass: a trace is attributed and dropped the moment
    its ROOT span ends (children always end before the root in the
    pipeline's trace shape), so memory is bounded by in-flight traces
    plus whatever a crash orphaned — a multi-GB event log never has to
    fit in host memory, matching ``read_events``' own O(1) contract."""
    starts: dict = {}          # span_id -> span_start event (unclosed)
    children_of: dict = {}     # trace_id -> [completed child _Span]
    event_counts: dict = {}
    orphan_ends = 0
    malformed_spans = 0
    complete_spans = 0
    hash_python = 0
    n_events = 0
    queue_n = 0
    queue_max = 0
    queue_sum = 0.0
    queue_capacity: Optional[int] = None

    stage_total: dict = {}
    bubble_total = 0.0
    wall_total = 0.0
    n_batches = 0
    incomplete = 0
    empty_roots = 0
    t_min, t_max = None, None
    child_wall = 0.0
    recover_resumes: list = []
    orphan_chunks = 0
    topk_dispatches = 0
    topk_queries = 0
    xform_dispatches = {"dma": 0, "single": 0}
    xform_rows = {"dma": 0, "single": 0}
    xform_fused_calls = 0
    xform_fused_rows = 0
    xform_fused_steps = 0
    shard_tiles = 0
    shard_fanout = 0
    shard_merges = 0
    shard_merge_wall = 0.0
    shard_batches = 0
    shard_batch_rows = 0
    shard_replicas: set = set()
    # per-request serving latency (r17): folded into the same fixed
    # log2 buckets the registry histograms use, keyed "<server>" and
    # "<server>[label]" — O(1) memory however long the run, quantiles
    # extracted at the end by the shared bucket math
    lat_hists: dict = {}
    loadgen_runs: list = []
    # health plane (r20): per-detector firing/cleared transition counts,
    # the per-key last-seen status (what was STILL firing when the log
    # ended), flight-recorder dumps, and the per-subscriber drop tally
    # the live-plane overflow events carry
    health_counts: dict = {}       # event -> {"firing": n, "cleared": n}
    health_last: dict = {}         # (event, key) -> last status
    flight_dumps: list = []
    subscriber_drops: dict = {}    # subscriber name -> dropped total
    # LSH candidate tier (ISSUE 15): per-tile candidate generation,
    # fallback reasons, bucket-build folds
    lsh_tiles = 0
    lsh_queries = 0
    lsh_probes = 0
    lsh_candidates = 0
    lsh_frac_sum = 0.0
    lsh_fallbacks: dict = {}
    lsh_builds = 0
    lsh_build_rows = 0
    # device-fused probe tier (ISSUE 16)
    lsh_dev_tiles = 0
    lsh_dev_uploads = 0
    lsh_dev_upload_bytes = 0
    lsh_adaptive_tiles = 0
    lsh_adaptive_queries = 0
    lsh_adaptive_rounds = 0
    lsh_adaptive_probes_sum = 0.0
    lsh_adaptive_early = 0
    lsh_adaptive_budget = 0
    # tiered residency (r21): per-tile hot/cold row split, the cold
    # fetch ledger (wall, overlapped share, per-fetch walls for p99),
    # promotion/demotion churn, and the degraded sync-fallback reasons
    tier_tiles = 0
    tier_hot_rows = 0
    tier_cold_rows = 0
    tier_fetches = 0
    tier_fetch_rows = 0
    tier_fetch_bytes = 0
    tier_fetch_wall = 0.0
    tier_overlap_wall = 0.0
    tier_sync_fetches = 0
    tier_fetch_walls: list = []
    tier_promotions = 0
    tier_evictions = 0
    tier_evict_wall = 0.0
    tier_fallbacks: dict = {}

    def _lat_observe(key: str, seconds: float) -> None:
        h = lat_hists.setdefault(key, {"sum": 0.0, "count": 0,
                                       "buckets": {}})
        h["sum"] += seconds
        h["count"] += 1
        b = MetricsRegistry._bucket(seconds)
        h["buckets"][b] = h["buckets"].get(b, 0) + 1

    for e in read_events(path):
        n_events += 1
        name = e["event"]
        event_counts[name] = event_counts.get(name, 0) + 1
        if name == EVENTS.SPAN_START:
            if "span_id" not in e or "trace_id" not in e:
                malformed_spans += 1
                continue
            starts[e["span_id"]] = e
        elif name == EVENTS.SPAN_END:
            if "span_id" not in e:
                malformed_spans += 1
                continue
            s = starts.pop(e["span_id"], None)
            if s is None:
                orphan_ends += 1
                continue
            complete_spans += 1
            t0 = s["ts"]
            # prefer the monotonic duration over wall-clock subtraction:
            # ts comes from time.time(), dur_s from perf_counter
            t1 = t0 + e.get("dur_s", max(e["ts"] - t0, 0.0))
            trace_id = s["trace_id"]
            if s.get("parent_id") is not None:
                children_of.setdefault(trace_id, []).append(
                    _Span(s["name"], trace_id, s["parent_id"], t0, t1)
                )
                continue
            # a ROOT ended: finalize its trace now and drop the buffers
            children = children_of.pop(trace_id, [])
            if e.get("empty"):
                # iter_traced's end-of-stream probe: production began but
                # there was no next batch — a healthy artifact, not an
                # incomplete batch
                empty_roots += 1
                continue
            if e.get("error") or e.get("abandoned"):
                incomplete += 1
                continue
            root = _Span(s["name"], trace_id, None, t0, t1)
            n_batches += 1
            t_min = root.t0 if t_min is None else min(t_min, root.t0)
            t_max = root.t1 if t_max is None else max(t_max, root.t1)
            child_wall += sum(c.t1 - c.t0 for c in children)
            stage_s, bubble, wall = _attribute(root, children)
            for k, v in stage_s.items():
                stage_total[k] = stage_total.get(k, 0.0) + v
            bubble_total += bubble
            wall_total += wall
        elif name in (
            EVENTS.STREAM_PREFETCH_DELIVER, EVENTS.STREAM_STAGED_DELIVER
        ):
            d = e.get("queue_depth", 0)
            queue_n += 1
            queue_max = max(queue_max, d)
            queue_sum += d
            if queue_capacity is None:
                queue_capacity = e.get("capacity")
        elif name == EVENTS.HASH_BATCH and e.get("path") == "python":
            hash_python += 1
        elif name == EVENTS.RECOVER_RESUME:
            # a durable ingest resumed from its committed cursor: the
            # replayed row range is the crash's footprint, on the record
            recover_resumes.append({
                "rows_done": e.get("rows_done"),
                "replay_rows": e.get("replay_rows"),
            })
        elif name == EVENTS.RECOVER_ORPHAN_CHUNK:
            orphan_chunks += 1
        elif name == EVENTS.KERNEL_DMA_DISPATCH:
            # fused transform-kernel host dispatches (ISSUE 9): which
            # route (manual double-buffered DMA vs the single-buffered
            # automatic tiling) served how many rows — the doctor's view
            # of whether the default DMA path is actually the one running
            route = e.get("path") if e.get("path") in xform_dispatches \
                else "single"
            xform_dispatches[route] += 1
            xform_rows[route] += e.get("rows", 0) or 0
        elif name == EVENTS.BACKEND_DISPATCH_FUSED:
            # multi-step dispatch fusion: K row-blocks chained through
            # one traced dispatch — call-boundary gaps amortize by 1/K
            xform_fused_calls += 1
            xform_fused_rows += e.get("rows", 0) or 0
            xform_fused_steps += e.get("steps", 0) or 0
        elif name == EVENTS.TOPK_KERNEL_DISPATCH:
            # fused serving-kernel dispatches (one per query tile per
            # chunk): the doctor's view of how much top-k traffic the
            # kernel path actually served
            topk_dispatches += 1
            topk_queries += e.get("queries", 0) or 0
        elif name == EVENTS.SHARD_TOPK_TILE:
            # sharded-tier fanout: one event per query tile, carrying
            # how many shard devices the tile was dispatched across
            shard_tiles += 1
            shard_fanout += e.get("shards", 0) or 0
        elif name == EVENTS.SHARD_MERGE:
            shard_merges += 1
            shard_merge_wall += e.get("wall_s", 0.0) or 0.0
        elif name == EVENTS.SERVE_SHARD_BATCH:
            # replica-routed coalesced dispatches from ShardedTopKServer
            shard_batches += 1
            shard_batch_rows += e.get("rows", 0) or 0
            if e.get("replica") is not None:
                shard_replicas.add(e["replica"])
        elif name == EVENTS.SERVE_LATENCY_REQUEST:
            # per-request enqueue→complete stamps from the serving tier
            total = e.get("total_s")
            if isinstance(total, (int, float)):
                server = str(e.get("server") or "topk")
                _lat_observe(server, total)
                if e.get("label") is not None:
                    _lat_observe(f"{server}[{e['label']}]", total)
        elif name == EVENTS.INDEX_LSH_DISPATCH:
            # one LSH-served query tile: how many buckets were probed
            # and what fraction of the corpus the re-rank touched — the
            # doctor's view of whether retrieval is actually sublinear.
            # Bucket lookups = queries x bands x probes, matching the
            # index.lsh.probe_buckets registry counter exactly
            lsh_tiles += 1
            lsh_queries += e.get("queries", 0) or 0
            lsh_probes += (
                (e.get("queries", 0) or 0)
                * (e.get("probes", 0) or 0)
                * (e.get("bands", 0) or 0)
            )
            lsh_candidates += e.get("candidates", 0) or 0
            lsh_frac_sum += e.get("candidate_fraction", 0.0) or 0.0
        elif name == EVENTS.INDEX_LSH_DEVICE_DISPATCH:
            # device-fused tile (ISSUE 16): same tile accounting as the
            # host probe path — the split between the two shows how
            # much of retrieval runs without the host CSR-walk hop
            lsh_tiles += 1
            lsh_dev_tiles += 1
            lsh_queries += e.get("queries", 0) or 0
            lsh_probes += (
                (e.get("queries", 0) or 0)
                * (e.get("probes", 0) or 0)
                * (e.get("bands", 0) or 0)
            )
            lsh_candidates += e.get("candidates", 0) or 0
            lsh_frac_sum += e.get("candidate_fraction", 0.0) or 0.0
        elif name == EVENTS.INDEX_LSH_ADAPTIVE:
            # adaptive tile: counts as a device tile; the per-query
            # probe escalation summary aggregates separately
            lsh_tiles += 1
            lsh_dev_tiles += 1
            lsh_queries += e.get("queries", 0) or 0
            lsh_candidates += e.get("candidates", 0) or 0
            lsh_frac_sum += e.get("candidate_fraction", 0.0) or 0.0
            lsh_adaptive_tiles += 1
            lsh_adaptive_queries += e.get("queries", 0) or 0
            lsh_adaptive_rounds += e.get("rounds", 0) or 0
            lsh_adaptive_probes_sum += (
                (e.get("probes_used_mean", 0.0) or 0.0)
                * (e.get("queries", 0) or 0)
            )
            lsh_adaptive_early += e.get("early_exits", 0) or 0
            lsh_adaptive_budget += e.get("budget_stops", 0) or 0
        elif name == EVENTS.INDEX_LSH_DEVICE_UPLOAD:
            lsh_dev_uploads += 1
            lsh_dev_upload_bytes += e.get("bytes", 0) or 0
        elif name == EVENTS.INDEX_LSH_FALLBACK:
            reason = str(e.get("reason") or "unknown")
            lsh_fallbacks[reason] = lsh_fallbacks.get(reason, 0) + 1
        elif name == EVENTS.INDEX_LSH_BUILD:
            lsh_builds += 1
            lsh_build_rows += e.get("rows", 0) or 0
        elif name == EVENTS.INDEX_TIER_HIT:
            # one tile served by a tiered index: how many candidate rows
            # sat in HBM vs the cold tier — the doctor's hot-hit ratio
            tier_tiles += 1
            tier_hot_rows += e.get("hot_rows", 0) or 0
            tier_cold_rows += e.get("cold_rows", 0) or 0
        elif name == EVENTS.INDEX_TIER_FETCH:
            # one cold H2D upload; promote=True means the background
            # worker re-admitted a chunk (churn), not a serving fetch
            if e.get("promote"):
                tier_promotions += 1
            else:
                tier_fetches += 1
                tier_fetch_rows += e.get("rows", 0) or 0
                tier_fetch_bytes += e.get("bytes", 0) or 0
                w = e.get("wall_s", 0.0) or 0.0
                tier_fetch_wall += w
                tier_fetch_walls.append(w)
                tier_overlap_wall += e.get("overlap_s", 0.0) or 0.0
                if e.get("sync"):
                    tier_sync_fetches += 1
        elif name == EVENTS.INDEX_TIER_EVICT:
            tier_evictions += 1
            tier_evict_wall += e.get("wall_s", 0.0) or 0.0
        elif name == EVENTS.INDEX_TIER_FALLBACK:
            reason = str(e.get("reason") or "unknown")
            tier_fallbacks[reason] = tier_fallbacks.get(reason, 0) + 1
        elif name in HEALTH_VERDICT_EVENTS:
            status = str(e.get("status") or "firing")
            d = health_counts.setdefault(name, {"firing": 0, "cleared": 0})
            d[status] = d.get(status, 0) + 1
            health_last[(name, str(e.get("key")))] = status
        elif name == EVENTS.HEALTH_FLIGHT_DUMP:
            flight_dumps.append({
                "reason": e.get("reason"),
                "path": e.get("path"),
                "events": e.get("events"),
            })
        elif name == EVENTS.TELEMETRY_SUBSCRIBER_DROPPED:
            # the rate-limited overflow report names its subscriber and
            # carries the running total — keep the max (totals are
            # monotonic per subscriber) so the audit says WHO overran
            sub = str(e.get("subscriber") or "?")
            total = e.get("dropped_total", e.get("dropped", 0)) or 0
            subscriber_drops[sub] = max(subscriber_drops.get(sub, 0),
                                        int(total))
        elif name == EVENTS.LOADGEN_RUN:
            loadgen_runs.append({
                "requests": e.get("requests"),
                "rows": e.get("rows"),
                "rejects": e.get("rejects"),
                "errors": e.get("errors"),
                "elapsed_s": e.get("elapsed_s"),
                "max_lag_s": e.get("max_lag_s"),
                "schedule_sha256": e.get("schedule_sha256"),
            })

    # traces whose root never ended: their buffered children are orphaned
    # work of a crashed run — count the traces as incomplete
    incomplete += len(children_of)

    stages = {
        name: {
            "wall_s": round(secs, 6),
            "pct": round(100.0 * secs / wall_total, 2) if wall_total else 0.0,
        }
        for name, secs in sorted(stage_total.items())
    }
    elapsed = (t_max - t_min) if (t_min is not None) else 0.0
    overlap = (
        max(0.0, 1.0 - elapsed / child_wall) if child_wall > 0 else 0.0
    )
    degraded = {name: event_counts.get(name, 0) for name in DEGRADED_EVENTS}
    degraded["hash.batch[path=python]"] = hash_python
    # emitter/consumer drift guard: event names this registry version
    # does not know (an emitter ahead of the registry, a file from a
    # newer build, or a stray literal that dodged the lint) — surfaced
    # in the degraded-event audit rather than silently counted
    unregistered = {
        name: c
        for name, c in sorted(event_counts.items())
        if not registered_event(name)
    }
    queue = None
    if queue_n:
        queue = {
            "samples": queue_n,
            "max": queue_max,
            "mean": round(queue_sum / queue_n, 3),
            "capacity": queue_capacity,
        }
    return {
        "file": path,
        "events": n_events,
        "event_counts": dict(sorted(event_counts.items())),
        "spans": {
            "complete": complete_spans,
            "orphan_starts": len(starts),
            "orphan_ends": orphan_ends,
            "malformed": malformed_spans,
        },
        "traces": {
            "batches": n_batches,
            "incomplete": incomplete,
            "empty": empty_roots,
        },
        "batch": {
            "wall_s": round(wall_total, 6),
            "stages": stages,
            "bubble": {
                "wall_s": round(bubble_total, 6),
                "pct": (
                    round(100.0 * bubble_total / wall_total, 2)
                    if wall_total else 0.0
                ),
            },
        },
        "pipeline": {
            "elapsed_s": round(elapsed, 6),
            "stage_wall_s": round(child_wall, 6),
            "overlap_ratio_est": round(overlap, 3),
        },
        "queue_depth": queue,
        "transform": (
            {
                "kernel_dispatches": dict(xform_dispatches),
                "kernel_rows": dict(xform_rows),
                **(
                    {
                        "fused_dispatch_calls": xform_fused_calls,
                        "fused_dispatch_rows": xform_fused_rows,
                        "fused_dispatch_mean_steps": round(
                            xform_fused_steps / xform_fused_calls, 2
                        ),
                    }
                    if xform_fused_calls
                    else {}
                ),
            }
            if (any(xform_dispatches.values()) or xform_fused_calls)
            else None
        ),
        "serving": (
            {
                "topk_kernel_dispatches": topk_dispatches,
                "topk_kernel_queries": topk_queries,
                **(
                    {
                        "shard_tiles": shard_tiles,
                        "shard_dispatches": shard_fanout,
                        "shard_merges": shard_merges,
                        "shard_merge_wall_s": round(shard_merge_wall, 6),
                    }
                    if shard_tiles
                    else {}
                ),
                **(
                    {
                        "shard_batches": shard_batches,
                        "shard_batch_rows": shard_batch_rows,
                        "shard_replicas_used": sorted(shard_replicas),
                    }
                    if shard_batches
                    else {}
                ),
            }
            if (topk_dispatches or shard_tiles or shard_batches)
            else None
        ),
        "candidate_generation": (
            {
                "lsh_tiles": lsh_tiles,
                "lsh_queries": lsh_queries,
                "probed_buckets_per_tile": (
                    round(lsh_probes / lsh_tiles, 2) if lsh_tiles else 0.0
                ),
                "candidates": lsh_candidates,
                "candidate_fraction_mean": (
                    round(lsh_frac_sum / lsh_tiles, 6) if lsh_tiles
                    else None
                ),
                "fallbacks": dict(sorted(lsh_fallbacks.items())),
                "fallback_rate": (
                    round(
                        sum(lsh_fallbacks.values())
                        / (lsh_tiles + sum(lsh_fallbacks.values())),
                        4,
                    )
                    if (lsh_tiles or lsh_fallbacks)
                    else None
                ),
                "builds": lsh_builds,
                "build_rows": lsh_build_rows,
                "device_tiles": lsh_dev_tiles,
                "device_uploads": lsh_dev_uploads,
                "device_upload_bytes": lsh_dev_upload_bytes,
                "adaptive": (
                    {
                        "tiles": lsh_adaptive_tiles,
                        "rounds": lsh_adaptive_rounds,
                        "probes_used_mean": (
                            round(
                                lsh_adaptive_probes_sum
                                / max(lsh_adaptive_queries, 1),
                                3,
                            )
                        ),
                        "early_exits": lsh_adaptive_early,
                        "budget_stops": lsh_adaptive_budget,
                    }
                    if lsh_adaptive_tiles
                    else None
                ),
            }
            if (lsh_tiles or lsh_fallbacks or lsh_builds)
            else None
        ),
        "residency": (
            {
                "tiles": tier_tiles,
                "hot_rows": tier_hot_rows,
                "cold_rows": tier_cold_rows,
                "hot_hit_ratio": (
                    round(
                        tier_hot_rows / (tier_hot_rows + tier_cold_rows), 4
                    )
                    if (tier_hot_rows + tier_cold_rows)
                    else None
                ),
                "cold_fetches": tier_fetches,
                "cold_fetch_rows": tier_fetch_rows,
                "cold_fetch_bytes": tier_fetch_bytes,
                "cold_fetch_wall_s": round(tier_fetch_wall, 6),
                # the share of fetch wall that rode UNDER the hot-tier
                # kernel (the overlap the tier exists to buy)
                "cold_fetch_overlapped_s": round(tier_overlap_wall, 6),
                # nearest-rank p99: index ceil(0.99 n) - 1, exact over
                # the full per-fetch wall list (doctor runs offline, so
                # no bucket estimate needed here)
                "cold_fetch_p99_s": (
                    round(
                        sorted(tier_fetch_walls)[
                            (99 * len(tier_fetch_walls) + 99) // 100 - 1
                        ],
                        6,
                    )
                    if tier_fetch_walls
                    else None
                ),
                "sync_fetches": tier_sync_fetches,
                "promotions": tier_promotions,
                "demotions": tier_evictions,
                "demotion_wall_s": round(tier_evict_wall, 6),
                "fallbacks": dict(sorted(tier_fallbacks.items())),
            }
            if (tier_tiles or tier_fetches or tier_evictions
                or tier_promotions or tier_fallbacks)
            else None
        ),
        "latency": (
            {
                key: quantiles_from_buckets(
                    {str(b): c for b, c in h["buckets"].items()},
                    h["count"], h["sum"],
                )
                for key, h in sorted(lat_hists.items())
            }
            if lat_hists
            else None
        ),
        "loadgen": loadgen_runs or None,
        "health": (
            {
                "verdicts": {
                    name: dict(c)
                    for name, c in sorted(health_counts.items())
                },
                "still_firing": sorted(
                    f"{ev} {key}"
                    for (ev, key), st in health_last.items()
                    if st == "firing"
                ),
                "flight_dumps": flight_dumps,
            }
            if (health_counts or flight_dumps)
            else None
        ),
        "subscriber_drops": (
            dict(sorted(subscriber_drops.items()))
            if subscriber_drops else None
        ),
        "degraded": degraded,
        "unregistered_events": unregistered,
        "recovery": (
            {
                "resumes": recover_resumes,
                "orphan_chunks_swept": orphan_chunks,
            }
            if (recover_resumes or orphan_chunks)
            else None
        ),
    }


def _bar(pct: float, width: int = 28) -> str:
    n = int(round(pct / 100.0 * width))
    return "#" * n + "." * (width - n)


def render_report(report: dict) -> str:
    """Human-readable doctor view: stage waterfall, bubble, pipeline
    overlap, queue depth, degraded-event audit, and (when the caller
    attached one — see ``cli.cmd_doctor``) the regression-tripwire
    status."""
    lines = []
    tr = report["traces"]
    sp = report["spans"]
    lines.append(
        f"run doctor: {report['file']} — {report['events']} events, "
        f"{tr['batches']} batch traces"
        + (f" ({tr['incomplete']} incomplete)" if tr["incomplete"] else "")
        + (
            f", {sp['orphan_starts']} orphaned span(s)"
            if sp["orphan_starts"] else ""
        )
    )
    b = report["batch"]
    if tr["batches"]:
        lines.append("")
        lines.append(
            f"per-batch critical path (% of {b['wall_s']:.4f}s total "
            "batch wall):"
        )
        rows = list(b["stages"].items()) + [("(bubble)", b["bubble"])]
        for name, d in rows:
            lines.append(
                f"  {name:<14} {_bar(d['pct'])} {d['pct']:6.2f}%  "
                f"{d['wall_s']:.4f}s"
            )
        total_pct = sum(d["pct"] for _, d in rows)
        lines.append(f"  {'':14} stages + bubble = {total_pct:.1f}% of "
                     "batch wall")
        p = report["pipeline"]
        lines.append("")
        lines.append(
            f"pipeline: elapsed {p['elapsed_s']:.4f}s over "
            f"{p['stage_wall_s']:.4f}s summed stage wall -> overlap ratio "
            f"~{p['overlap_ratio_est']:.3f}"
        )
    else:
        lines.append("")
        lines.append(
            "no complete batch traces (flat v1 log, or the run died before "
            "any batch committed) — audit below still applies"
        )
    q = report.get("queue_depth")
    if q:
        lines.append(
            f"prefetch queue: {q['samples']} samples, depth max {q['max']}"
            f"/mean {q['mean']}"
            + (f" (capacity {q['capacity']})" if q.get("capacity") else "")
        )
    xf = report.get("transform")
    if xf:
        kd, kr = xf["kernel_dispatches"], xf["kernel_rows"]
        lines.append(
            f"transform kernel: {kd['dma']} DMA dispatch(es) "
            f"({kr['dma']} rows), {kd['single']} single-buffered "
            f"({kr['single']} rows)"
        )
        if xf.get("fused_dispatch_calls"):
            lines.append(
                f"  dispatch fusion: {xf['fused_dispatch_calls']} chained "
                f"call(s), {xf['fused_dispatch_rows']} rows, mean "
                f"{xf['fused_dispatch_mean_steps']} steps/call"
            )
    sv = report.get("serving")
    if sv:
        lines.append(
            f"serving: {sv['topk_kernel_dispatches']} fused top-k kernel "
            f"dispatch(es), {sv['topk_kernel_queries']} query rows"
        )
        if sv.get("shard_tiles"):
            lines.append(
                f"  sharded tier: {sv['shard_tiles']} tile(s) fanned over "
                f"{sv['shard_dispatches']} shard dispatch(es), "
                f"{sv['shard_merges']} cross-shard merge(s) "
                f"({sv['shard_merge_wall_s']:.4f}s merge wall)"
            )
        if sv.get("shard_batches"):
            reps = sv.get("shard_replicas_used") or []
            lines.append(
                f"  replica routing: {sv['shard_batches']} coalesced "
                f"batch(es), {sv['shard_batch_rows']} rows over "
                f"{len(reps)} replica(s)"
            )
    cg = report.get("candidate_generation")
    if cg:
        lines.append("")
        lines.append("candidate generation (multi-probe LSH):")
        frac = cg.get("candidate_fraction_mean")
        lines.append(
            f"  {cg['lsh_tiles']} LSH tile(s), {cg['lsh_queries']} query "
            f"rows, mean {cg['probed_buckets_per_tile']} probed "
            f"buckets/tile"
        )
        lines.append(
            f"  candidates re-ranked: {cg['candidates']}"
            + (
                f" (mean {100.0 * frac:.2f}% of the live corpus per tile)"
                if frac is not None else ""
            )
        )
        if cg.get("device_tiles"):
            lines.append(
                f"  device-fused probe tiles: {cg['device_tiles']} "
                f"({cg.get('device_uploads', 0)} CSR upload(s), "
                f"{cg.get('device_upload_bytes', 0)} bytes)"
            )
        ad = cg.get("adaptive")
        if ad:
            lines.append(
                f"  adaptive probing: {ad['tiles']} tile(s), "
                f"{ad['rounds']} round(s), mean {ad['probes_used_mean']} "
                f"probes/query, {ad['early_exits']} early exit(s), "
                f"{ad['budget_stops']} budget stop(s)"
            )
        fb = cg.get("fallbacks") or {}
        if fb:
            detail = ", ".join(f"{k} {v}" for k, v in fb.items())
            lines.append(
                f"  fallbacks to the exact path: {sum(fb.values())} "
                f"({detail}; rate {cg['fallback_rate']})"
            )
        else:
            lines.append("  fallbacks to the exact path: none")
        if cg.get("builds"):
            lines.append(
                f"  bucket builds: {cg['builds']} fold(s), "
                f"{cg['build_rows']} rows"
            )
    rs = report.get("residency")
    if rs:
        lines.append("")
        lines.append("residency (tiered hot/cold corpus, r21):")
        ratio = rs.get("hot_hit_ratio")
        lines.append(
            f"  {rs['tiles']} tiered tile(s): {rs['hot_rows']} hot row(s) "
            f"/ {rs['cold_rows']} cold row(s)"
            + (f" — hot-hit ratio {ratio:.4f}" if ratio is not None else "")
        )
        if rs.get("cold_fetches"):
            p99 = rs.get("cold_fetch_p99_s")
            lines.append(
                f"  cold fetches: {rs['cold_fetches']} "
                f"({rs['cold_fetch_rows']} rows, "
                f"{rs['cold_fetch_bytes']} bytes) — wall "
                f"{rs['cold_fetch_wall_s']:.4f}s, overlapped "
                f"{rs['cold_fetch_overlapped_s']:.4f}s under the hot "
                f"kernel"
                + (f", p99 {p99 * 1e3:.2f}ms" if p99 is not None else "")
                + (
                    f", {rs['sync_fetches']} synchronous"
                    if rs.get("sync_fetches") else ""
                )
            )
        if rs.get("promotions") or rs.get("demotions"):
            lines.append(
                f"  churn: {rs['promotions']} promotion(s), "
                f"{rs['demotions']} demotion(s) "
                f"({rs['demotion_wall_s']:.4f}s demotion wall, all "
                "background)"
            )
        fb = rs.get("fallbacks") or {}
        if fb:
            detail = ", ".join(f"{k} {v}" for k, v in fb.items())
            lines.append(
                f"  degraded sync fallbacks: {sum(fb.values())} ({detail})"
            )
        else:
            lines.append("  degraded sync fallbacks: none")
    lat = report.get("latency")
    if lat:
        lines.append("")
        lines.append(
            "serve latency (enqueue→complete, per server / [label]; "
            "bucket-estimated quantiles, exact count/mean):"
        )
        for key, q in lat.items():
            qtxt = "  ".join(
                f"{p}={q[p] * 1e3:.2f}ms" if q[p] is not None else f"{p}=-"
                for p in ("p50", "p90", "p99", "p99.9")
            )
            lines.append(
                f"  {key:<24} n={q['count']:<7} "
                f"mean={q['mean'] * 1e3:.2f}ms  {qtxt}"
            )
    lg = report.get("loadgen")
    if lg:
        lines.append("")
        lines.append("loadgen (open-loop) runs:")
        for r in lg:
            lines.append(
                f"  {r['requests']} requests / {r['rows']} rows in "
                f"{r['elapsed_s']}s — rejects {r['rejects']}, errors "
                f"{r['errors']}, max submit lag {r['max_lag_s']}s, "
                f"schedule {str(r['schedule_sha256'])[:12]}"
            )
    hp = report.get("health")
    if hp:
        lines.append("")
        lines.append("health verdicts (r20 detectors):")
        for name, c in hp["verdicts"].items():
            lines.append(
                f"  {name:<28} fired {c.get('firing', 0)}x, "
                f"cleared {c.get('cleared', 0)}x"
            )
        if hp["still_firing"]:
            lines.append(
                "  STILL FIRING at end of log: "
                + ", ".join(hp["still_firing"])
            )
        for d in hp["flight_dumps"]:
            lines.append(
                f"  flight dump: {d['path']} ({d['reason']}, "
                f"{d['events']} ring events)"
            )
    lines.append("")
    lines.append("degraded-event audit:")
    worst = [(k, v) for k, v in report["degraded"].items() if v]
    for k, v in report["degraded"].items():
        lines.append(f"  {k:<36} {v}")
    subs = report.get("subscriber_drops")
    if subs:
        # WHICH observer overran its bounded queue, not just how often
        # the overflow report fired (r20 satellite)
        for sub, n in subs.items():
            lines.append(f"    subscriber[{sub}] dropped {n}")
    lines.append(
        "  -> " + (
            "DEGRADED paths taken: " + ", ".join(k for k, _ in worst)
            if worst else "no degraded paths recorded"
        )
    )
    rec = report.get("recovery")
    if rec:
        lines.append("")
        lines.append("crash recovery:")
        for r in rec["resumes"]:
            lines.append(
                f"  resumed at rows_done={r['rows_done']} "
                f"(replayed {r['replay_rows']} uncommitted rows)"
            )
        if rec["orphan_chunks_swept"]:
            lines.append(
                f"  {rec['orphan_chunks_swept']} orphan spill file(s) "
                "swept (uncommitted chunk writes from the crash)"
            )
    unreg = report.get("unregistered_events")
    if unreg:
        lines.append(
            "  WARNING: event name(s) not in the telemetry.EVENTS "
            "registry this report was built against:"
        )
        for k, v in sorted(unreg.items()):
            lines.append(f"    {k:<34} {v}")
    tw = report.get("tripwire")
    if tw is not None:
        lines.append("")
        if tw.get("error"):
            lines.append(f"regression tripwire: unavailable ({tw['error']})")
        else:
            regs = tw.get("regressions")
            vs = tw.get("regressions_vs")
            if regs:
                lines.append(
                    f"regression tripwire ({tw['baseline']}): "
                    f"{len(regs)} recorded vs {vs}:"
                )
                for r in regs:
                    lines.append(
                        f"  {r['metric']}: {r['previous']} -> {r['current']} "
                        f"(-{r['drop_pct']}%)"
                    )
            elif tw.get("regressions_skipped"):
                lines.append(
                    f"regression tripwire ({tw['baseline']}): skipped — "
                    f"{tw['regressions_skipped']}"
                )
            elif regs == [] and vs:
                # the tripwire actually RAN in that round and compared
                # clean against a named baseline
                lines.append(
                    f"regression tripwire ({tw['baseline']}): no >10% "
                    f"drops recorded vs {vs}"
                )
            else:
                # record predates the tripwire (no verdict on file): say
                # so — never report a comparison that was never computed
                lines.append(
                    f"regression tripwire ({tw['baseline']}): no verdict "
                    "recorded in that round's record"
                )
    return "\n".join(lines) + "\n"


# -- flight-recorder postmortem (r20) ----------------------------------------


def build_postmortem(dump: dict) -> dict:
    """Reconstruct the final seconds from a ``FlightRecorder`` dump
    (the JSON ``telemetry.FlightRecorder.dump`` writes): last-known
    per-stage activity, spans in flight at death, the detectors firing
    at death, and a counter digest.  Raises ``ValueError`` on a file
    that is not a flight-recorder dump — the doctor must never render
    a confident postmortem from the wrong artifact."""
    if dump.get("format") != "rp-flight-recorder":
        raise ValueError(
            "not a flight-recorder dump (format="
            f"{dump.get('format')!r}, want 'rp-flight-recorder')"
        )
    death_ts = dump.get("ts")
    events = dump.get("events") or []
    stages: dict = {}        # stage -> {"last_ts", "events"}
    open_spans: dict = {}    # span_id -> span_start record
    window_t0 = None
    for e in events:
        if not isinstance(e, dict):
            continue
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            window_t0 = ts if window_t0 is None else min(window_t0, ts)
        name = e.get("event")
        if name in (EVENTS.SPAN_START, EVENTS.SPAN_END):
            stage = str(e.get("name"))
            st = stages.setdefault(stage, {"last_ts": None, "events": 0})
            st["events"] += 1
            if isinstance(ts, (int, float)):
                st["last_ts"] = ts if st["last_ts"] is None else max(
                    st["last_ts"], ts
                )
            sid = e.get("span_id")
            if sid is not None:
                if name == EVENTS.SPAN_START:
                    open_spans[sid] = e
                else:
                    open_spans.pop(sid, None)

    def _age(ts):
        if ts is None or not isinstance(death_ts, (int, float)):
            return None
        return round(death_ts - ts, 3)

    stage_rows = [
        {
            "stage": stage,
            "events": st["events"],
            "last_ts": st["last_ts"],
            "age_s": _age(st["last_ts"]),
        }
        for stage, st in sorted(
            stages.items(),
            key=lambda kv: kv[1]["last_ts"] or 0.0,
            reverse=True,
        )
    ]
    # "the stage active at death": most-recently-heartbeating stage,
    # preferring one with a span still OPEN in the ring window
    last_active = None
    open_stages = {str(s.get("name")) for s in open_spans.values()}
    for row in stage_rows:
        if row["stage"] in open_stages:
            last_active = row["stage"]
            break
    if last_active is None and stage_rows:
        last_active = stage_rows[0]["stage"]
    in_flight = [
        {
            "name": str(s.get("name")),
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "age_s": _age(s.get("ts")),
        }
        for s in sorted(
            open_spans.values(), key=lambda s: s.get("ts") or 0.0
        )
    ]
    tail = [
        {"event": e.get("event"), "age_s": _age(e.get("ts"))}
        for e in events[-10:]
        if isinstance(e, dict)
    ]
    counters = {}
    snap = dump.get("counters") or {}
    for k, v in sorted((snap.get("counters") or {}).items()):
        if v:
            counters[k] = v
    health = dump.get("health")
    return {
        "format": dump.get("format"),
        "v": dump.get("v"),
        "pid": dump.get("pid"),
        "reason": dump.get("reason"),
        "death_ts": death_ts,
        "ring": {
            "events": len(events),
            "capacity": dump.get("capacity"),
            "window_s": (
                round(death_ts - window_t0, 3)
                if (window_t0 is not None
                    and isinstance(death_ts, (int, float)))
                else None
            ),
        },
        "last_active_stage": last_active,
        "stages": stage_rows,
        "in_flight": in_flight,
        "firing": health if isinstance(health, list) else [],
        "health_error": (
            health.get("error") if isinstance(health, dict) else None
        ),
        "tail": tail,
        "counters": counters,
    }


def render_postmortem(pm: dict) -> str:
    """Human-readable postmortem (``cli doctor --postmortem``)."""
    lines = [
        f"flight-recorder postmortem: pid {pm['pid']}, "
        f"reason {pm['reason']!r}",
        f"  ring: {pm['ring']['events']} events"
        + (
            f" over the final {pm['ring']['window_s']}s"
            if pm["ring"]["window_s"] is not None else ""
        )
        + f" (capacity {pm['ring']['capacity']})",
    ]
    if pm["last_active_stage"]:
        lines.append(f"  last active stage: {pm['last_active_stage']}")
    if pm["stages"]:
        lines.append("")
        lines.append("last-known per-stage activity (age at death):")
        for row in pm["stages"]:
            age = row["age_s"]
            lines.append(
                f"  {row['stage']:<18} x{row['events']:<6}"
                + (f" last {age:.3f}s before death" if age is not None
                   else " (no timestamp)")
            )
    if pm["in_flight"]:
        lines.append("")
        lines.append("spans in flight at death:")
        for s in pm["in_flight"]:
            lines.append(
                f"  {s['name']:<18} trace {str(s['trace_id'])[:12]}"
                + (f"  open {s['age_s']:.3f}s" if s["age_s"] is not None
                   else "")
            )
    if pm["firing"]:
        lines.append("")
        lines.append("detectors firing at death:")
        for v in pm["firing"]:
            lines.append(
                f"  {v.get('detector', '?'):<28} key={v.get('key')}"
                + ("  [critical]" if v.get("critical") else "")
            )
    elif pm.get("health_error"):
        lines.append("")
        lines.append(
            f"  (health snapshot failed at dump: {pm['health_error']})"
        )
    if pm["tail"]:
        lines.append("")
        lines.append("final events:")
        for e in pm["tail"]:
            lines.append(
                f"  {str(e['event']):<34}"
                + (f" {e['age_s']:.3f}s before death"
                   if e["age_s"] is not None else "")
            )
    if pm["counters"]:
        lines.append("")
        lines.append("nonzero counters at death:")
        for k, v in pm["counters"].items():
            lines.append(f"  {k:<44} {v:g}")
    return "\n".join(lines) + "\n"
