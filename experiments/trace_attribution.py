"""Trace-backed attribution of the lazy_split2 residual (VERDICT r4 #2).

Runs the bench's own scan harness for ``lazy_split2`` at the headline
shape under ``jax.profiler.trace``, then parses the captured xplane and
prints the device-time decomposition: how much of each while-loop step is
the fused Pallas kernel vs the harness fold, and how much wall time falls
between calls (dispatch).  The findings are recorded in BASELINE.md
("Attribution of the residual", r5 trace paragraph).

Needs the real chip.  Beware the call cache: a process that measured
nothing else first has been observed serving the harness at impossible
rates (37 GROWS/s once) — this script warms with a dense mode first, the
way the full bench does, and prints the untraced rate so a cache-served
run is self-evident.

Usage: python experiments/trace_attribution.py [trace_dir]
"""

import glob
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_traced(trace_dir: str) -> None:
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu import benchmark as B

    d, k, density = 4096, 256, 1 / 3
    cfg = dict(batch=131072, steps=64, calls=2)
    Rf = jax.random.normal(jax.random.key(0), (k, d), jnp.float32)
    r0 = B.measure_mode(jax, jnp, Rf, "bf16", 1.0, d=d, **cfg)
    print(f"bf16 warm: {r0['rows_per_s'] / 1e6:.1f}M rows/s")
    kw = dict(k=k, density=density, lazy_seed=0)
    r1 = B.measure_mode(jax, jnp, None, "lazy_split2", 1.0, d=d, **cfg, **kw)
    print(f"lazy_split2 untraced: {r1['rows_per_s'] / 1e6:.1f}M rows/s")
    with jax.profiler.trace(trace_dir):
        r2 = B.measure_mode(
            jax, jnp, None, "lazy_split2", 1.0, d=d, **cfg, **kw
        )
    print(f"lazy_split2 traced: {r2['rows_per_s'] / 1e6:.1f}M rows/s")


def analyze(trace_dir: str) -> None:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    path = sorted(glob.glob(f"{trace_dir}/plugins/profile/*/*.xplane.pb"))[-1]
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    plane = next(p for p in xs.planes if p.name == "/device:TPU:0")
    emeta = {m.id: m.name for m in plane.event_metadata.values()}
    ops = next(ln for ln in plane.lines if ln.name == "XLA Ops")
    agg = defaultdict(lambda: [0, 0.0])
    for e in ops.events:
        name = emeta.get(e.metadata_id, "?")
        agg[name][0] += 1
        agg[name][1] += e.duration_ps / 1e12
    whiles = {n: v for n, v in agg.items() if n.startswith("%while")}
    # the jitted transform kernel is named after its raw body (_fused_raw
    # since r14; _fused_impl in pre-r14 profiles) — match both so old
    # captures keep decomposing
    kernel = {
        n: v for n, v in agg.items()
        if "_fused_raw" in n or "_fused_impl" in n
    }
    w_total = sum(v[1] for v in whiles.values())
    # the kernel can appear under several event names (custom-call plus
    # async wrappers); the STEP count is the count of any single name
    steps = max((v[0] for v in kernel.values()), default=0)
    k_total = sum(v[1] for v in kernel.values())
    print(f"\nwhile loops: {w_total:.3f}s total")
    print(
        f"fused kernel custom-call: {steps} steps, {k_total:.3f}s "
        f"({k_total / max(w_total, 1e-9):.0%} of loop time, "
        f"{k_total / max(steps, 1) * 1e3:.2f} ms/step)"
    )
    others = sorted(
        (
            (n, v)
            for n, v in agg.items()
            if n not in whiles and n not in kernel and v[1] > 1e-3
        ),
        key=lambda kv: -kv[1][1],
    )
    for n, (c, t) in others[:6]:
        print(f"  {t:7.3f}s x{c:5d}  {n[:80]}")


if __name__ == "__main__":
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/rp_trace"
    run_traced(trace_dir)
    time.sleep(1)
    analyze(trace_dir)
