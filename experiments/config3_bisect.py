"""Config-3 regression bisect (ROADMAP #3 sub-item; ISSUE 9 satellite).

The decay: config-3 (very-sparse Li 16384→512, lazy_split2) went
3.30M rows/s in `BENCH_r04.json` to 2.88M in `BENCH_r05.json` — −13% —
with BIT-IDENTICAL checksum and distortion (same kernel, same values),
so the regression is pure wall-clock: +11.6 ms per timed call
(0.0795 → 0.0910 s/call at 16 steps × 16384 rows/call).  Three suspects
were named in VERDICT r5 and never separated:

- **mask machinery** — the r5 round added the VMEM mask-cache sizing;
  if cache setup/regen slots cost wall at this shape, disabling the
  cache (and, since r14, switching the DMA route) moves the rate.
- **block shape** — `_auto_block_n` resolves the row tile per shape;
  if r5's sizing picked a different tile, pinning `block_n` moves it.
- **dispatch count** — config-3 runs only 16 steps/call, so per-call
  host overhead (~100-133 ms dispatch latency on this virtualized box,
  observed to wander round-to-round) is a large share of elapsed; if
  the decay is call-boundary, the rate recovers as steps/call grows
  and the per-call overhead intercept — not the steady-state rate —
  is what moved.

This script isolates the three at the exact config-3 shape by sweeping
ONE lever at a time through `benchmark.measure_config3` (the committed
methodology — same `_scan_harness`, same anti-cache defenses):

- route sweep:  {dma+cache, single+cache, dma+nocache}        (A)
- tile sweep:   block_n ∈ {auto, 256, 512, 1024}              (B)
- steps sweep:  steps ∈ {4, 8, 16, 64, 256}, then a least-squares fit
  of ``elapsed = calls·overhead + rows·per_row`` — the intercept is
  the per-call host overhead, the slope the steady-state rate.   (C)

Reading the output: the lever whose sweep reproduces a ≥13% swing is
the cause.  If (C)'s fitted overhead is ≥11 ms/call while (A) and (B)
are flat, the r5 decay was call-boundary/box variance and the recovery
lever is dispatch fusion (more steps chained per traced dispatch —
exactly the r14 ``dispatch_steps`` knob); BASELINE.md records the
verdict.

TPU required for real numbers (the lazy kernel's hardware PRNG has no
CPU lowering); ``--smoke`` runs the SAME three sweeps with the same
harness at a toy shape under the Pallas interpreter, so the bisect
plumbing is CI-provable off-chip (rates meaningless there).

Run: python experiments/config3_bisect.py [--smoke] [--json PATH]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _fit_overhead(samples):
    """Least squares for elapsed = calls*overhead + rows*per_row over
    [(rows_timed, calls, elapsed_s)] samples."""
    A = np.array([[c, r] for r, c, _ in samples], dtype=np.float64)
    b = np.array([e for _, _, e in samples])
    (overhead, per_row), *_ = np.linalg.lstsq(A, b, rcond=None)
    return float(overhead), float(per_row)


def _smoke_measure(dma=None, steps=None, block_n=None, no_cache=False):
    """Toy-shape stand-in for ``measure_config3`` under the interpreter:
    identical sweep surface and harness, CPU-feasible shape."""
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu import benchmark as bm
    from randomprojection_tpu.ops.pallas_kernels import fused_sparse_project

    batch, d, k, calls = 64, 1024, 16, 2
    steps = 2 if steps is None else min(int(steps), 4)

    def project(x):
        return fused_sparse_project(
            x, 0, k, 1.0 / 32, mxu_mode="split2", dma=dma, block_n=block_n,
            no_cache=no_cache, interpret=True,
        )

    x0 = jax.random.normal(jax.random.key(3), (batch, d), jnp.float32)
    rate, elapsed, _ = bm._scan_harness(jax, jnp, project, x0, steps, calls)
    return {"rows_per_s": round(rate, 1), "elapsed_s": round(elapsed, 4),
            "rows_timed": batch * steps * calls}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="toy shape under the Pallas interpreter (CPU): "
                         "proves the bisect plumbing, not the rates")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the full sweep record here")
    args = ap.parse_args(argv)

    import jax

    on_tpu = jax.default_backend() not in ("cpu", "gpu", "cuda", "rocm")
    if not on_tpu and not args.smoke:
        print("config3_bisect: no TPU attached (lazy kernel has no CPU "
              "lowering) — re-run on a chip, or --smoke for the "
              "interpreter plumbing check", file=sys.stderr)
        return 2

    if args.smoke:
        measure = _smoke_measure
        rows_per_call_steps = 64  # batch rows at the toy shape
        steps_grid = [1, 2, 4]
    else:
        from randomprojection_tpu import benchmark as bm

        def measure(**kw):
            return bm.measure_config3("full", **kw)

        rows_per_call_steps = 16384
        steps_grid = [4, 8, 16, 64, 256]

    record = {"on_tpu": on_tpu, "smoke": args.smoke, "sweeps": {}}

    # (A) route sweep: mask machinery / DMA routing, everything else fixed
    route = {}
    for label, kw in [
        ("dma+cache", dict()),
        ("single+cache", dict(dma=False)),
        ("dma+nocache", dict(no_cache=True)),
    ]:
        r = measure(**kw)
        route[label] = {"rows_per_s": r["rows_per_s"],
                        "elapsed_s": r["elapsed_s"]}
        print(f"A route   {label:<14} {r['rows_per_s']:>12,.0f} rows/s "
              f"({r['elapsed_s']:.4f}s)")
    record["sweeps"]["route"] = route

    # (B) tile sweep: block shape at the default route
    tile = {}
    for bn in (None, 256, 512, 1024):
        label = "auto" if bn is None else str(bn)
        try:
            r = measure(block_n=bn)
        except Exception as e:  # a pinned tile can legitimately blow VMEM
            tile[label] = {"error": str(e)[:120]}
            print(f"B tile    {label:<14} failed: {str(e)[:60]}")
            continue
        tile[label] = {"rows_per_s": r["rows_per_s"],
                       "elapsed_s": r["elapsed_s"]}
        print(f"B tile    {label:<14} {r['rows_per_s']:>12,.0f} rows/s "
              f"({r['elapsed_s']:.4f}s)")
    record["sweeps"]["tile"] = tile

    # (C) dispatch-count sweep: vary steps/call, fit per-call overhead
    # (intercept) against steady-state rate (slope)
    samples = []
    steps_sweep = {}
    for s in steps_grid:
        r = measure(steps=s)
        ran_calls = r["rows_timed"] // (rows_per_call_steps * s)
        samples.append((r["rows_timed"], ran_calls, r["elapsed_s"]))
        steps_sweep[str(s)] = {"rows_per_s": r["rows_per_s"],
                               "elapsed_s": r["elapsed_s"],
                               "calls": ran_calls}
        print(f"C steps   {s:<14} {r['rows_per_s']:>12,.0f} rows/s "
              f"({ran_calls} calls, {r['elapsed_s']:.4f}s)")
    overhead_s, per_row_s = _fit_overhead(samples)
    asymptote = 1.0 / per_row_s if per_row_s > 0 else float("nan")
    record["sweeps"]["steps"] = steps_sweep
    record["fit"] = {
        "per_call_overhead_s": round(overhead_s, 5),
        "steady_state_rows_per_s": round(asymptote, 1),
    }
    print(f"C fit     per-call overhead {overhead_s * 1e3:.1f} ms, "
          f"steady-state {asymptote:,.0f} rows/s")

    # verdict heuristic: which lever moved >= 10%?
    def spread(d):
        rs = [v["rows_per_s"] for v in d.values() if "rows_per_s" in v]
        return (max(rs) - min(rs)) / max(rs) if rs else 0.0

    verdict = {
        "route_spread": round(spread(route), 3),
        "tile_spread": round(spread(tile), 3),
        "fitted_overhead_ms_per_call": round(overhead_s * 1e3, 2),
        "r5_decay_ms_per_call": 11.6,
    }
    if not on_tpu:
        verdict["note"] = ("interpreter smoke — plumbing only, rates "
                           "meaningless; run on TPU for the verdict")
    record["verdict"] = verdict
    print("verdict:", json.dumps(verdict))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
