"""Round-4 kernel attribution probe (VERDICT r3 missing #1).

Measures, on the real chip, where fused_sparse_project's time goes:
- current kernel at block_n in {256, 512, 1024}
- a mask-free variant (constant mask, same dots) = matmul-only ceiling
- a regen-once variant is approximated by the ratio of the two

HISTORICAL: this probe predates the VMEM mask-block cache and the auto
row tile that its constant-mask finding motivated (see
ops/pallas_kernels.py round-4 comments and BASELINE.md for the outcome:
mask machinery now costs ~7%, kernel at ~93% of its own dot ceiling).

All numbers go through the bench's anti-cache scan harness; on this box
wall-clock is dispatch-polluted, so only RELATIVE comparisons within one
run are meaningful (BASELINE.md).  Run: python experiments/kernel_probe.py
"""

import functools
import math
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from randomprojection_tpu.benchmark import _scan_harness  # noqa: E402
from randomprojection_tpu.ops.pallas_kernels import (  # noqa: E402
    BLOCK_D,
    _mask_block,
    fused_sparse_project,
)
from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair  # noqa: E402

_DOT_KD = (((1,), (1,)), ((), ()))


def _probe_kernel(seed_ref, x_ref, o_ref, *, k, density, scale, n_blocks_d,
                  mxu_mode, mask_mode):
    j = pl.program_id(1)
    if mask_mode == "regen":
        pltpu.prng_seed(seed_ref[0], j)
        r = _mask_block(density)((k, x_ref.shape[1]))
    else:  # constant mask: isolates the dots
        r = jnp.full((k, x_ref.shape[1]), 0.001, jnp.float32)

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    if mxu_mode == "split2":
        x_hi, x_lo = split_f32_to_bf16_pair(x_ref[:])
        r16 = r.astype(jnp.bfloat16)
        acc = jax.lax.dot_general(x_hi, r16, dimension_numbers=_DOT_KD,
                                  preferred_element_type=jnp.float32)
        acc += jax.lax.dot_general(x_lo, r16, dimension_numbers=_DOT_KD,
                                   preferred_element_type=jnp.float32)
        o_ref[:] += acc
    else:
        o_ref[:] += jax.lax.dot_general(x_ref[:], r, dimension_numbers=_DOT_KD,
                                        preferred_element_type=jnp.float32)

    @pl.when(j == n_blocks_d - 1)
    def _():
        o_ref[:] = o_ref[:] * scale


@functools.partial(jax.jit, static_argnames=("k", "density", "block_n",
                                             "mxu_mode", "mask_mode"))
def probe_project(x, k, density, block_n, mxu_mode, mask_mode):
    n, d = x.shape
    scale = 1.0 / math.sqrt(density * k)
    ni, nj = n // block_n, d // BLOCK_D
    seed_arr = jnp.asarray([0, 0], dtype=jnp.int32)
    return pl.pallas_call(
        functools.partial(_probe_kernel, k=k, density=density, scale=scale,
                          n_blocks_d=nj, mxu_mode=mxu_mode, mask_mode=mask_mode),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block_n, BLOCK_D), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_n, k), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.float32),
    )(seed_arr, x)


def main():
    d, k, density = 4096, 256, 1.0 / 3.0
    batch, steps, calls = 16384, 32, 3
    x0 = jax.random.normal(jax.random.key(1), (batch, d), jnp.float32)
    print(f"probe: batch={batch} d={d} k={k} steps={steps} calls={calls}")
    for mxu_mode in ("split2", "f32"):
        passes = 2 if mxu_mode == "split2" else 1
        for mask_mode in ("regen", "const"):
            for block_n in (256, 512, 1024, 2048):
                fn = lambda x: probe_project(  # noqa: E731
                    x, k, density, block_n, mxu_mode, mask_mode)
                rate, elapsed, _ = _scan_harness(jax, jnp, fn, x0, steps, calls)
                tflops = rate * passes * 2 * d * k / 1e12
                print(f"  {mxu_mode:6s} mask={mask_mode:5s} block_n={block_n:4d}"
                      f"  {rate/1e6:7.2f}M rows/s  executed {tflops:6.1f}"
                      f" TFLOP/s  ({100*tflops/197:.0f}% peak)")


if __name__ == "__main__":
    main()
