"""Sketch-family tests: SimHash sign-RP and Count-Sketch (configs 4–5)."""

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import (
    CountSketch,
    NotFittedError,
    SignRandomProjection,
    cosine_from_hamming,
    pairwise_hamming,
)


# ---------------------------------------------------------------------------
# SignRandomProjection / SimHash
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_sign_rp_shapes_and_determinism(backend):
    X = np.random.default_rng(0).normal(size=(50, 128)).astype(np.float32)
    est = SignRandomProjection(n_components=64, random_state=0, backend=backend)
    C = est.fit(X).transform(X)
    assert C.shape == (50, 8) and C.dtype == np.uint8
    C2 = SignRandomProjection(
        n_components=64, random_state=0, backend=backend
    ).fit(X).transform(X)
    np.testing.assert_array_equal(np.asarray(C), np.asarray(C2))


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_sign_rp_ragged_bit_width(backend):
    X = np.random.default_rng(0).normal(size=(10, 64)).astype(np.float32)
    C = SignRandomProjection(
        n_components=20, random_state=0, backend=backend
    ).fit(X).transform(X)
    assert C.shape == (10, 3)  # ceil(20/8)
    # pad bits beyond k are zero in every row → byte values < 2^4 in last byte
    assert np.all(np.asarray(C)[:, -1] < 16)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_simhash_estimates_cosine(backend):
    """Hamming/k must estimate angle: cos(π·h/k) ≈ true cosine (Charikar)."""
    rng = np.random.default_rng(1)
    base = rng.normal(size=(1, 256))
    # construct vectors at controlled angles to base
    perp = rng.normal(size=(1, 256))
    perp -= perp @ base.T / (base @ base.T) * base
    X = [base[0]]
    true_cos = [1.0]
    for theta in (np.pi / 6, np.pi / 3, np.pi / 2):
        v = np.cos(theta) * base / np.linalg.norm(base) + np.sin(theta) * (
            perp / np.linalg.norm(perp)
        )
        X.append(v[0])
        true_cos.append(np.cos(theta))
    X = np.asarray(X, dtype=np.float32)

    k = 4096  # many bits → tight estimate
    est = SignRandomProjection(n_components=k, random_state=2, backend=backend)
    C = np.asarray(est.fit(X).transform(X))
    H = pairwise_hamming(C)
    est_cos = cosine_from_hamming(H[0], k)
    np.testing.assert_allclose(est_cos, true_cos, atol=0.06)


def test_sign_rp_jax_numpy_hamming_consistency():
    """Backends use different PRNGs, but both must satisfy the SimHash
    collision bound: hamming/k ≈ θ/π for the same data."""
    rng = np.random.default_rng(3)
    a = rng.normal(size=256)
    b = a + 0.5 * rng.normal(size=256)
    X = np.stack([a, b]).astype(np.float32)
    theta = np.arccos(a @ b / np.linalg.norm(a) / np.linalg.norm(b))
    k = 4096
    for backend in ("numpy", "jax"):
        C = np.asarray(
            SignRandomProjection(n_components=k, random_state=4, backend=backend)
            .fit(X).transform(X)
        )
        h = pairwise_hamming(C)[0, 1]
        np.testing.assert_allclose(h / k, theta / np.pi, atol=0.03)


def test_sign_rp_has_no_inverse():
    X = np.random.default_rng(0).normal(size=(10, 32)).astype(np.float32)
    est = SignRandomProjection(n_components=16, random_state=0,
                               backend="numpy").fit(X)
    with pytest.raises(NotImplementedError):
        est.inverse_transform(est.transform(X))


def test_pairwise_hamming_matches_bruteforce():
    rng = np.random.default_rng(0)
    A = rng.integers(0, 256, size=(5, 4), dtype=np.uint8)
    B = rng.integers(0, 256, size=(3, 4), dtype=np.uint8)
    H = pairwise_hamming(A, B)
    for i in range(5):
        for j in range(3):
            expect = sum(bin(a ^ b).count("1") for a, b in zip(A[i], B[j]))
            assert H[i, j] == expect


# ---------------------------------------------------------------------------
# CountSketch
# ---------------------------------------------------------------------------


def test_countsketch_dense_backends_agree():
    """Same h_/s_ on both backends ⇒ the same sketch; the jax MXU path
    (one-hot split2) agrees at f32 grade with the host scatter.  Error
    model: each split term carries ~|x|·2^-16, a bucket sums ~d/k of them
    → atol ~1e-4 for O(1) inputs at d/k≈5."""
    X = np.random.default_rng(0).normal(size=(40, 300)).astype(np.float32)
    Yj = CountSketch(64, random_state=0, backend="jax").fit(X).transform(X)
    Yn = CountSketch(64, random_state=0, backend="numpy").fit(X).transform(X)
    np.testing.assert_allclose(Yj, Yn, rtol=1e-4, atol=2e-4)


def test_countsketch_scatter_fallback_above_mask_cap(monkeypatch):
    """Huge hashed feature spaces must take the scatter path (the one-hot
    matrix would not fit); results still agree with the host scatter."""
    monkeypatch.setattr(CountSketch, "_MXU_MASK_BYTES_CAP", 1024)
    X = np.random.default_rng(0).normal(size=(20, 300)).astype(np.float32)
    Yj = CountSketch(16, random_state=0, backend="jax").fit(X).transform(X)
    Yn = CountSketch(16, random_state=0, backend="numpy").fit(X).transform(X)
    np.testing.assert_allclose(Yj, Yn, rtol=2e-5, atol=2e-5)


def test_countsketch_csr_matches_dense():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(30, 500))
    X[np.abs(X) < 1.0] = 0.0
    cs = CountSketch(32, random_state=0, backend="numpy").fit(X)
    np.testing.assert_allclose(
        cs.transform(sp.csr_array(X)), cs.transform(X), rtol=1e-12
    )


def test_countsketch_decode_unbiased():
    """E[s(j)·Y[h(j)]] = x[j]: average decode over independent sketches."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(1, 64))
    decodes = []
    for seed in range(400):
        cs = CountSketch(32, random_state=seed, backend="numpy").fit_schema(1, 64)
        decodes.append(cs.inverse_transform(cs.transform(x)))
    # per-coordinate std of one decode ≈ sqrt(63/32) ≈ 1.4; averaging 400
    # sketches → ≈0.07, so a 0.35 cap is ≈5σ even for the max over 64 coords
    err = np.abs(np.mean(decodes, axis=0) - x).max()
    assert err < 0.35, err


def test_countsketch_preserves_inner_products():
    """⟨sketch(x), sketch(y)⟩ ≈ ⟨x, y⟩ in expectation (AMS)."""
    rng = np.random.default_rng(3)
    x, y = rng.normal(size=(2, 2000))
    dots = []
    for seed in range(100):
        cs = CountSketch(256, random_state=seed).fit_schema(2, 2000)
        S = cs.transform(np.stack([x, y]))
        dots.append(S[0] @ S[1])
    rel_err = abs(np.mean(dots) - x @ y) / (np.linalg.norm(x) * np.linalg.norm(y))
    assert rel_err < 0.05, rel_err


def test_countsketch_validation():
    with pytest.raises(ValueError):
        CountSketch(0)
    with pytest.raises(NotFittedError):
        CountSketch(8).transform(np.ones((2, 4)))
    cs = CountSketch(8, random_state=0).fit_schema(10, 16)
    with pytest.raises(ValueError, match="features"):
        cs.transform(np.ones((2, 5)))
    with pytest.raises(ValueError, match="components"):
        cs.inverse_transform(np.ones((2, 5)))


def test_countsketch_use_mxu_opt_out():
    """use_mxu=False forces the scatter path regardless of mask size (the
    exact-reproducibility opt-out, ADVICE r2); use_mxu=True above the mask
    cap refuses instead of silently scattering."""
    X = np.random.default_rng(0).normal(size=(20, 300)).astype(np.float32)
    Ys = CountSketch(
        16, random_state=0, backend="jax", use_mxu=False
    ).fit(X).transform(X)
    Yn = CountSketch(16, random_state=0, backend="numpy").fit(X).transform(X)
    # scatter path: same accumulation structure as the host scatter —
    # f32-rounding-tight agreement (same tolerance as the cap-fallback test)
    np.testing.assert_allclose(Ys, Yn, rtol=2e-5, atol=2e-5)

    big = CountSketch(16, random_state=0, backend="jax", use_mxu=True)
    big._MXU_MASK_BYTES_CAP = 1024
    big.fit(X)
    with pytest.raises(ValueError, match="use_mxu=True"):
        big.transform(X)

    # clone-compat: the new kwarg participates in get_params
    assert CountSketch(16, use_mxu=False).get_params()["use_mxu"] is False


def test_countsketch_use_mxu_refuses_host_fallbacks():
    """use_mxu=True must refuse every input that would silently take a
    host path (f64, sparse) and set_params(use_mxu=...) must invalidate
    the cached device fn."""
    X = np.random.default_rng(0).normal(size=(20, 300)).astype(np.float32)
    cs = CountSketch(16, random_state=0, backend="jax", use_mxu=True).fit(X)
    with pytest.raises(ValueError, match="float64"):
        cs.transform(X.astype(np.float64))
    with pytest.raises(ValueError, match="sparse"):
        cs.transform(sp.csr_array(X))
    with pytest.raises(ValueError, match="requires the jax backend"):
        CountSketch(16, random_state=0, backend="numpy", use_mxu=True).fit(X)

    # set_params toggling the path drops the cached fn and takes effect
    auto = CountSketch(16, random_state=0, backend="jax").fit(X)
    auto.transform(X)
    assert hasattr(auto, "_jax_fn")
    auto.set_params(use_mxu=False)
    assert not hasattr(auto, "_jax_fn")
    Ys = auto.transform(X)
    Yn = CountSketch(16, random_state=0, backend="numpy").fit(X).transform(X)
    np.testing.assert_allclose(Ys, Yn, rtol=2e-5, atol=2e-5)


def test_countsketch_csr_f32_on_device_matches_host():
    """f32 CSR routes to the device gather/scatter path (the config-5 hot
    loop); it must agree with the host scatter at f32 grade, including
    empty rows and duplicate hashed columns within a row."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(37, 400)).astype(np.float32)
    X[np.abs(X) < 1.2] = 0.0
    X[5] = 0.0  # an empty row
    Xs = sp.csr_array(X)
    cs = CountSketch(32, random_state=0, backend="jax").fit(Xs)
    Yd = cs.transform(Xs)
    Yh = CountSketch(32, random_state=0, backend="numpy").fit(Xs).transform(Xs)
    assert Yd.dtype == np.float32
    np.testing.assert_allclose(Yd, Yh, rtol=2e-5, atol=2e-5)
    # zero-nnz batch: all-zero sketch, right shape
    empty = sp.csr_array(np.zeros((4, 400), dtype=np.float32))
    np.testing.assert_array_equal(cs.transform(empty), np.zeros((4, 32)))


def test_countsketch_csr_device_at_hashing_space_scale():
    """d = 2^20 (the BL:11 hash space): the device CSR path must engage —
    no one-hot matrix exists at this width — and decode correctly."""
    d = 1 << 20
    rng = np.random.default_rng(6)
    nnz_per_row = 50
    n = 16
    indices = rng.integers(0, d, size=n * nnz_per_row).astype(np.int32)
    data = rng.normal(size=n * nnz_per_row).astype(np.float32)
    indptr = np.arange(0, n * nnz_per_row + 1, nnz_per_row)
    Xs = sp.csr_array((data, indices, indptr), shape=(n, d))
    cs = CountSketch(256, random_state=0, backend="jax").fit_schema(n, d)
    Y = cs.transform(Xs)
    assert Y.shape == (n, 256) and Y.dtype == np.float32
    ref = CountSketch(256, random_state=0, backend="numpy").fit_schema(n, d)
    np.testing.assert_allclose(Y, ref.transform(Xs), rtol=2e-5, atol=2e-5)


def test_countsketch_csr_f64_stays_on_host_exact():
    """f64 CSR keeps the host scatter (device would truncate): exact."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(20, 300))
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)
    cs = CountSketch(16, random_state=0, backend="jax").fit(Xs)
    Y = cs.transform(Xs)
    assert Y.dtype == np.float64
    ref = CountSketch(16, random_state=0, backend="numpy").fit(Xs).transform(Xs)
    np.testing.assert_allclose(Y, ref, rtol=1e-12)


def test_countsketch_csr_async_returns_device_handle():
    """CSR f32 batches stream lazily (device handle) like dense f32."""
    import jax

    rng = np.random.default_rng(8)
    X = rng.normal(size=(32, 200)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)
    cs = CountSketch(16, random_state=0, backend="jax").fit(Xs)
    y = cs._transform_async(Xs)
    assert isinstance(y, jax.Array)
    np.testing.assert_allclose(np.asarray(y), cs.transform(Xs), rtol=1e-6)


def test_countsketch_csr_device_guard_uses_padded_rows():
    """ADVICE r4: the int32 flat-index guard must count the PADDED rows —
    ``_transform_csr_jax`` buckets rows up to +25% (``row_bucket``) and the
    flat scatter index spans ``n_pad*k``, so a batch in the narrow band
    where ``n*k < 2^31 <= row_bucket(n)*k`` would silently overflow int32
    on device if the guard used the raw row count."""
    from types import SimpleNamespace

    cs = CountSketch(256, random_state=0, backend="jax").fit_schema(
        8, 16, np.float32
    )
    ok = SimpleNamespace(dtype=np.dtype(np.float32), shape=(1024, 16))
    assert cs._csr_on_device(ok)
    # raw product (2^23-1)*256 = 2^31-256 passes a raw-row guard, but
    # row_bucket pads to 2^23 rows and 2^23*256 == 2^31 overflows
    edge = SimpleNamespace(dtype=np.dtype(np.float32), shape=(2**23 - 1, 16))
    assert not cs._csr_on_device(edge)

    # under a mesh the token-balanced row cuts (ISSUE 8 satellite) can
    # hand one shard EVERY row of a fully-skewed batch, so the guard no
    # longer divides by the shard count: the same edge batch must route
    # to the host path rather than risk a wrapped per-shard flat index
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    cs8 = CountSketch(
        256, random_state=0, backend="jax", mesh=mesh
    ).fit_schema(8, 16, np.float32)
    assert not cs8._csr_on_device(edge)
    assert cs8._csr_on_device(ok)


@pytest.mark.parametrize("force", ["docmajor", "flat"])
def test_countsketch_csr_kernel_selection_both_match_host(monkeypatch, force):
    """r5 bake-off: the device CSR sketch picks the doc-major
    compare-reduce kernel for low-skew batches and the flat
    gather+scatter for skewed ones.  Both must match the f64 host
    scatter at f32 grade, including ragged rows and empty docs."""
    rng = np.random.default_rng(21)
    X = rng.normal(size=(101, 300)).astype(np.float32)
    X[np.abs(X) < 0.8] = 0.0
    X[7] = 0.0  # an empty doc
    X[11] = 1.0  # a dense doc (skew)
    Xs = sp.csr_array(X)
    if force == "docmajor":
        monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_INFLATION", 1e9)
        monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_WIDTH", 1 << 20)
    else:
        monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_INFLATION", 0.0)
    cs = CountSketch(32, random_state=0, backend="jax").fit(Xs)
    Y = cs.transform(Xs)
    kinds = [k[0] for k in cs._csr_fns]
    if force == "docmajor":
        assert "docmajor" in kinds, kinds
    else:
        assert "docmajor" not in kinds, kinds
    ref = CountSketch(32, random_state=0, backend="numpy").fit(Xs).transform(
        Xs.astype(np.float64)
    )
    np.testing.assert_allclose(Y, ref, rtol=2e-5, atol=2e-5)


@pytest.mark.mesh_env
def test_countsketch_csr_docmajor_mesh_matches(monkeypatch):
    """Doc-major kernel under the 8-device mesh: row-sharded DP, same
    values as single-device and host."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from jax.sharding import Mesh

    monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_INFLATION", 1e9)
    rng = np.random.default_rng(22)
    X = rng.normal(size=(101, 200)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    csm = CountSketch(32, random_state=0, backend="jax", mesh=mesh).fit(Xs)
    Ym = csm.transform(Xs)
    assert "docmajor" in [k[0] for k in csm._csr_fns], list(csm._csr_fns)
    Y1 = CountSketch(32, random_state=0, backend="jax").fit(Xs).transform(Xs)
    np.testing.assert_allclose(Ym, Y1, rtol=1e-6, atol=1e-6)
    Yn = CountSketch(32, random_state=0, backend="numpy").fit(Xs).transform(Xs)
    np.testing.assert_allclose(Ym, Yn, rtol=2e-5, atol=2e-5)


def test_simhash_index_int32_id_guard():
    """ADVICE r5: device-side ids are int32 end to end, so the index must
    refuse to grow past 2^31 - 1 codes instead of silently wrapping global
    ids in query_topk."""
    from randomprojection_tpu.models.sketch import SimHashIndex

    codes = np.random.default_rng(0).integers(
        0, 256, size=(16, 8), dtype=np.uint8
    )
    idx = SimHashIndex(codes)
    idx.n_codes = 2**31 - 10  # simulate a near-capacity index
    with pytest.raises(ValueError, match="2\\*\\*31"):
        idx.add(codes)
    assert idx.n_codes == 2**31 - 10, "a refused add must not mutate state"


def test_query_topk_dense_fallback_when_host_scale(monkeypatch):
    """ADVICE r5 / ISSUE 7: when no device path can represent a request
    (genuinely host-scale m), query_topk must serve it through the dense
    query() + host-selection path — same results and tie order — instead
    of raising."""
    from randomprojection_tpu.models import sketch as sk

    rng = np.random.default_rng(11)
    B = rng.integers(0, 256, size=(96, 8), dtype=np.uint8)
    A = rng.integers(0, 256, size=(7, 8), dtype=np.uint8)
    idx = sk.SimHashIndex(B)
    ref_d, ref_i = idx.query_topk(A, 5)

    monkeypatch.setattr(
        sk.SimHashIndex, "_topk_route", lambda self, t, m: "dense"
    )
    got_d, got_i = idx.query_topk(A, 5)
    np.testing.assert_array_equal(got_d, ref_d)
    np.testing.assert_array_equal(got_i, ref_i)
    brute_d, brute_i = sk.topk_bruteforce(A, B, 5)
    np.testing.assert_array_equal(got_d, brute_d)
    np.testing.assert_array_equal(got_i, brute_i)


# ---------------------------------------------------------------------------
# top-k serving: overlapped d2h + TopKServer micro-batcher (ISSUE r9)
# ---------------------------------------------------------------------------


def _serving_fixture(n_codes=5000, n_add=300, nq=1000, nb=8, seed=0):
    from randomprojection_tpu.models.sketch import SimHashIndex

    rng = np.random.default_rng(seed)
    idx = SimHashIndex(rng.integers(0, 256, size=(n_codes, nb), dtype=np.uint8))
    if n_add:
        idx.add(rng.integers(0, 256, size=(n_add, nb), dtype=np.uint8))
    q = rng.integers(0, 256, size=(nq, nb), dtype=np.uint8)
    return idx, q


def test_query_topk_multi_tile_overlap_matches_bruteforce():
    """The overlapped d2h restructure (per-chunk copy_to_host_async,
    tiles materializing one behind) must not change a single result —
    multi-tile, multi-chunk, against the host brute-force oracle."""
    from randomprojection_tpu.models import sketch as sk

    idx, q = _serving_fixture()
    full = np.concatenate([np.asarray(c.b)[: c.n] for c in idx._chunks])
    d, i = idx.query_topk(q, 5, tile=128)  # 8 tiles x 2 chunks in flight
    ref_d, ref_i = sk.topk_bruteforce(q, full, 5)
    np.testing.assert_array_equal(d, ref_d)
    np.testing.assert_array_equal(i, ref_i)
    # the dense path's tile overlap too
    np.testing.assert_array_equal(
        idx.query(q, tile=128), sk.pairwise_hamming(q, full)
    )


def test_topk_server_matches_direct_and_coalesces():
    """Concurrent mixed-size requests through the server must return the
    identical (dist, idx) a direct query_topk gives, in request row
    order — while coalescing many requests into few dispatches."""
    from randomprojection_tpu.models.sketch import TopKServer

    idx, q = _serving_fixture()
    ref_d, ref_i = idx.query_topk(q, 5)
    with TopKServer(idx, 5, max_batch=256, max_delay_s=0.005) as srv:
        futs, off = [], 0
        for size in [1, 7, 64, 3, 128, 33] * 4:
            futs.append((off, size, srv.submit(q[off : off + size])))
            off += size
        for o, s, f in futs:
            d, i = f.result(timeout=60)
            assert d.shape == i.shape == (s, 5)
            np.testing.assert_array_equal(d, ref_d[o : o + s])
            np.testing.assert_array_equal(i, ref_i[o : o + s])
        st = srv.stats()
        assert st["requests"] == 24
        assert st["batches"] < st["requests"], "requests must coalesce"
        assert st["queries"] == off
        # 1-D convenience: one row in, (1, m) out
        d1, i1 = srv.query(q[0])
        np.testing.assert_array_equal(d1, ref_d[:1])
        np.testing.assert_array_equal(i1, ref_i[:1])


def test_topk_server_threaded_clients_bit_identical():
    from randomprojection_tpu.models.sketch import TopKServer
    import threading

    idx, q = _serving_fixture(nq=960)
    ref_d, ref_i = idx.query_topk(q, 3)
    out = {}
    with TopKServer(idx, 3, max_batch=512, max_delay_s=0.01) as srv:
        def client(ci):
            futs = [
                (o, srv.submit(q[o : o + 32]))
                for o in range(ci * 240, (ci + 1) * 240, 32)
            ]
            out[ci] = [(o, f.result(timeout=60)) for o, f in futs]

        threads = [
            threading.Thread(target=client, args=(ci,)) for ci in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for ci in range(4):
        for o, (d, i) in out[ci]:
            np.testing.assert_array_equal(d, ref_d[o : o + 32])
            np.testing.assert_array_equal(i, ref_i[o : o + 32])


def test_topk_server_lifecycle_and_validation():
    import threading

    from randomprojection_tpu.models.sketch import TopKServer

    idx, q = _serving_fixture(n_codes=200, n_add=0, nq=8)
    with pytest.raises(ValueError, match="m must be"):
        TopKServer(idx, 0)
    with pytest.raises(ValueError, match="max_batch"):
        TopKServer(idx, 2, max_batch=0)
    with pytest.raises(ValueError, match="max_delay_s"):
        TopKServer(idx, 2, max_delay_s=-1)
    srv = TopKServer(idx, 2, max_delay_s=0.0)
    with pytest.raises(ValueError, match="queries must be"):
        srv.submit(np.zeros((2, 3), np.uint8))  # wrong code width
    with pytest.raises(ValueError, match="empty request"):
        srv.submit(np.zeros((0, 8), np.uint8))
    # close serves already-submitted requests, then refuses new ones
    fut = srv.submit(q[:4])
    srv.close()
    d, i = fut.result(timeout=60)
    assert d.shape == (4, 2)
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit(q[:1])
    srv.close()  # idempotent
    assert not [
        t for t in threading.enumerate() if t.name.startswith("rp-topk")
    ]


def test_topk_server_submit_after_close_fails_fast():
    """ISSUE 6 satellite regression: submit()/query() after close()
    raise a clear 'server closed' RuntimeError instead of enqueueing
    into a dead dispatcher, and a closed server cannot be start()ed
    back into a queue whose sentinel already drained."""
    from randomprojection_tpu.models.sketch import TopKServer

    idx, q = _serving_fixture(n_codes=200, n_add=0, nq=8)
    srv = TopKServer(idx, 2, max_delay_s=0.0)
    srv.close()
    with pytest.raises(RuntimeError, match="server closed"):
        srv.submit(q[:1])
    with pytest.raises(RuntimeError, match="server closed"):
        srv.query(q[:1])
    with pytest.raises(RuntimeError, match="server closed"):
        srv.start()
    # a never-started server closes cleanly and still refuses submits
    srv2 = TopKServer(idx, 2, start=False)
    srv2.close()
    with pytest.raises(RuntimeError, match="server closed"):
        srv2.submit(q[:1])
    with pytest.raises(RuntimeError, match="server closed"):
        srv2.start()


def test_topk_server_bounded_queue_rejects_when_stalled():
    """The submit queue is bounded (ISSUE r10): with the dispatcher not
    draining, the max_pending+1'th submit fails fast instead of growing
    host memory — and close() still never blocks (the sentinel slot is
    reserved past the bound)."""
    from randomprojection_tpu.models.sketch import TopKServer

    idx, q = _serving_fixture(n_codes=200, n_add=0, nq=8)
    with pytest.raises(ValueError, match="max_pending"):
        TopKServer(idx, 2, max_pending=0)
    # start=False = a permanently stalled dispatcher
    srv = TopKServer(idx, 2, max_pending=2, start=False)
    f1 = srv.submit(q[:1])
    f2 = srv.submit(q[:1])
    with pytest.raises(RuntimeError, match="queue is full"):
        srv.submit(q[:1])
    from randomprojection_tpu.utils import telemetry

    assert telemetry.registry().counter("serve.topk.rejects") >= 1
    srv.close()  # sentinel fits in the reserved slot: returns immediately
    assert not f1.done() and not f2.done()  # never served: stalled drain


def test_topk_server_failed_dispatch_emits_error_event(tmp_path):
    """A coalesced dispatch that fails on device reaches every caller
    through its future AND the telemetry spine (serve.topk.error +
    serve.topk.errors counter) — ISSUE r10's silent-swallow audit."""
    from randomprojection_tpu.models.sketch import TopKServer
    from randomprojection_tpu.utils import telemetry

    idx, q = _serving_fixture(n_codes=200, n_add=0, nq=8)
    srv = TopKServer(idx, 2, start=False)
    srv.index = _Boom(idx)
    tel = str(tmp_path / "serve.jsonl")
    telemetry.configure(tel)
    try:
        srv.start()
        fut = srv.submit(q[:4])
        with pytest.raises(RuntimeError, match="device exploded"):
            fut.result(timeout=60)
        srv.close()
    finally:
        telemetry.shutdown()
    evs = [e for e in telemetry.read_events(tel)
           if e["event"] == "serve.topk.error"]
    assert len(evs) == 1
    assert evs[0]["requests"] == 1 and "device exploded" in evs[0]["error"]
    assert telemetry.registry().counter("serve.topk.errors") >= 1


class _Boom:
    """Index stand-in whose query_topk always fails on 'device'."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def query_topk(self, *a, **k):
        raise RuntimeError("device exploded")


def test_topk_bench_composition(monkeypatch):
    """The config-4 serving bench (single-stream + micro-batched modes)
    runs end to end at toy shapes and records both rates with their own
    suspect flags."""
    from randomprojection_tpu import benchmark

    monkeypatch.setitem(
        benchmark.TOPK_BENCH_SHAPES, "smoke",
        dict(n_idx=2048, q_tile=128, clients=2, req_rows=16,
             reqs_per_client=2, max_batch=64, shards=2, replicas=2),
    )
    # the LSH leg (ISSUE 15) rides the same bench: patch its shape to
    # toy sizes too (the gate-level assertions live in test_ann.py)
    monkeypatch.setitem(
        benchmark.LSH_BENCH_SHAPES, "smoke",
        dict(n_idx=512, n_bytes=8, cluster=8, nq=8, m=5, bands=4,
             band_bits=8, noise_bits=2, probe_counts=(1,), calls=1,
             rerank_tile=8),
    )
    tk = benchmark.measure_config4_topk("smoke")
    assert tk["queries_per_s"] > 0
    assert tk["single_stream_queries_per_s"] > 0
    assert tk["index_codes"] == 2048
    assert isinstance(tk["timing_suspect"], bool)
    assert isinstance(tk["single_stream_timing_suspect"], bool)
    assert tk["server_rows_per_batch_mean"] > 0
    # the sharded config (ISSUE 8) rides the same bench: layout, rate,
    # per-shard dispatch counts, merge wall and replica spread recorded
    sh = tk["sharded"]
    assert sh["shards"] == 2 and sh["replicas"] == 2
    assert sh["queries_per_s"] > 0
    assert sh["merges"] > 0 and sh["shard_dispatches"] == 2 * sh["merges"]
    assert sh["merge_wall_s"] >= 0
    assert sum(sh["replica_batches"]) >= sh["merges"] // 2
    assert isinstance(sh["timing_suspect"], bool)
    # all three rates feed the regression tripwire under their own flags
    rates = benchmark.bench_rates({"config4": {"topk_serving": tk}})
    assert rates["config4.topk.queries_per_s"][0] == tk["queries_per_s"]
    assert rates["config4.topk.single_stream_queries_per_s"][0] == (
        tk["single_stream_queries_per_s"]
    )
    assert rates["config4.topk.sharded_queries_per_s"][0] == (
        sh["queries_per_s"]
    )
    # the compact digest flattens the sharded rate (≤2 KB bound is
    # re-validated by tests/test_telemetry.py against a real cli bench)
    c = benchmark.compact_summary(
        {"mode": "x", "value": 1.0, "config4": {"topk_serving": tk}}
    )
    sig_qps = benchmark._sig(sh["queries_per_s"])  # digest stores sig digits
    assert c["config4"]["topk_sharded_queries_per_s"] == sig_qps
    assert c["config4"]["topk_sharded_shards"] == 2
    # a compact-line-only record still gates the sharded rate
    rates2 = benchmark.bench_rates({"config4": c["config4"]})
    assert rates2["config4.topk.sharded_queries_per_s"][0] == sig_qps


# ---------------------------------------------------------------------------
# token-balanced CSR mesh partitioning (ISSUE 8 satellite, VERDICT weak #3)
# ---------------------------------------------------------------------------


def _skewed_csr(n=53, d=400, seed=31):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        nnz = 60 if i % 11 == 0 else rng.integers(1, 4)
        cols = rng.choice(d, size=nnz, replace=False)
        r = np.zeros(d, np.float32)
        r[cols] = rng.normal(size=nnz).astype(np.float32)
        rows.append(r)
    return sp.csr_array(np.stack(rows))


def test_token_balanced_bounds_properties():
    from randomprojection_tpu.parallel.sharded import token_balanced_bounds

    X = _skewed_csr()
    max_row = int(np.diff(X.indptr).max())
    for p in (1, 2, 3, 8):
        b = token_balanced_bounds(X.indptr, p)
        assert b.shape == (p + 1,)
        assert b[0] == 0 and b[-1] == X.shape[0]
        assert (np.diff(b) >= 0).all()
        toks = np.diff(np.asarray(X.indptr, dtype=np.int64)[b])
        assert toks.sum() == X.nnz
        # every shard within one row's tokens of the ideal split
        assert toks.max() <= X.nnz // p + max_row, (p, toks.tolist())
    # degenerate: empty batch
    empty = sp.csr_array((0, 4), dtype=np.float32)
    b = token_balanced_bounds(empty.indptr, 4)
    assert (b == 0).all()
    with pytest.raises(ValueError, match="p must be"):
        token_balanced_bounds(X.indptr, 0)


def test_flat_mesh_layout_algebra_matches_host():
    """The token-balanced layout's scatter/permutation algebra,
    simulated on host (no mesh execution needed): per-shard scatter
    into its rows_blk block, gather through perm, must equal the host
    scatter reference for every shard count — including the pad tokens
    (index 0, value 0) contributing nothing."""
    from randomprojection_tpu.models.sketch import _flat_mesh_layout

    X = _skewed_csr()
    n, k = X.shape[0], 16
    cs = CountSketch(k, random_state=3, backend="numpy")
    cs.fit_schema(n, X.shape[1], dtype=np.float32)
    ref = cs._transform_csr(X.astype(np.float64)).astype(np.float32)
    for p in (1, 2, 4, 8):
        rows_l, idx_s, vals_s, rows_blk, t_pad, perm = _flat_mesh_layout(
            X, p
        )
        assert rows_l.shape == (p, t_pad)
        assert perm.shape == (n,) and perm.dtype == np.int32
        assert len(np.unique(perm)) == n and perm.max() < p * rows_blk
        y = np.zeros((p * rows_blk, k), np.float32)
        for s in range(p):
            acc = np.zeros((rows_blk, k), np.float32)
            np.add.at(
                acc, (rows_l[s], cs.h_[idx_s[s]]),
                vals_s[s] * cs.s_[idx_s[s]],
            )
            y[s * rows_blk : (s + 1) * rows_blk] = acc
        np.testing.assert_allclose(y[perm], ref, rtol=1e-5, atol=1e-5)


def test_flat_mesh_layout_stops_worst_shard_padding():
    """The point of the satellite: one token-heavy region must no
    longer set t_pad for every shard.  All heavy rows land in the first
    quarter; the balanced split keeps t_pad near nnz/p where the old
    equal-row split padded every shard to the heavy quarter's count."""
    from randomprojection_tpu.models.sketch import _flat_mesh_layout
    from randomprojection_tpu.parallel.sharded import row_bucket

    rng = np.random.default_rng(33)
    n, d, p = 64, 600, 8
    rows = []
    for i in range(n):
        nnz = 80 if i < 8 else 2  # the old split gave shard 0 all of these
        cols = rng.choice(d, size=nnz, replace=False)
        r = np.zeros(d, np.float32)
        r[cols] = 1.0
        rows.append(r)
    X = sp.csr_array(np.stack(rows))
    _, _, _, rows_blk, t_pad, _ = _flat_mesh_layout(X, p)
    old_equal_row_tpad = row_bucket(8 * 80)  # shard 0 under the old split
    assert t_pad <= row_bucket(X.nnz // p + 80)
    assert t_pad < old_equal_row_tpad


@pytest.mark.mesh_env
def test_countsketch_csr_flat_mesh_matches(monkeypatch):
    """The flat kernel under the 8-device mesh with token-balanced
    partitioning: same values as single-device and host, skew and all."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from jax.sharding import Mesh

    # force the flat route (doc-major would win this shape otherwise)
    monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_INFLATION", 0.0)
    X = _skewed_csr()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    csm = CountSketch(32, random_state=0, backend="jax", mesh=mesh).fit(X)
    Ym = csm.transform(X)
    assert any(
        isinstance(key, tuple) and key[0] == "flat_mesh"
        for key in csm._csr_fns
    ), list(csm._csr_fns)
    Y1 = CountSketch(32, random_state=0, backend="jax").fit(X).transform(X)
    np.testing.assert_allclose(Ym, Y1, rtol=1e-6, atol=1e-6)
    Yn = CountSketch(32, random_state=0, backend="numpy").fit(X).transform(X)
    np.testing.assert_allclose(Ym, Yn, rtol=2e-5, atol=2e-5)
