"""Split-precision (2-pass bf16) mode tests — plain XLA, runs on CPU."""

import numpy as np
import pytest

from randomprojection_tpu import SignRandomProjection, SparseRandomProjection


def pdist2(a):
    sq = (a * a).sum(1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (a @ a.T)
    iu = np.triu_indices(a.shape[0], k=1)
    return np.maximum(d2[iu], 1e-30)


def test_split_pair_reconstructs_exactly():
    import jax.numpy as jnp

    from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair

    x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 256)),
                    dtype=jnp.float32)
    hi, lo = split_f32_to_bf16_pair(x)
    # the low half must be NON-zero (the XLA convert-elision trap) ...
    assert float(jnp.abs(lo.astype(jnp.float32)).max()) > 0
    # ... and hi+lo must reconstruct x to ~2^-16 relative
    recon = hi.astype(jnp.float32) + lo.astype(jnp.float32)
    err = np.abs(np.asarray(recon) - np.asarray(x)).max()
    assert err < np.abs(np.asarray(x)).max() * 2**-15


@pytest.mark.parametrize("density", [1.0, 1 / 3, 0.1])
def test_split2_backend_accuracy(density):
    """split2 output must track the exact f64 product to ~1e-5 distances."""
    X = np.random.default_rng(0).normal(size=(256, 1024)).astype(np.float32)
    est = SparseRandomProjection(
        n_components=64, density=density, random_state=0, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(X)
    Y = np.asarray(est.transform(X), dtype=np.float64)
    R = np.asarray(est.components_as_numpy(), dtype=np.float64)
    Y_ref = X.astype(np.float64) @ R.T
    dist_err = np.abs(pdist2(Y) / pdist2(Y_ref) - 1.0).max()
    assert dist_err < 1e-4, dist_err


def test_split2_mask_values_exact():
    est = SparseRandomProjection(
        n_components=32, density=1 / 3, random_state=1, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(np.zeros((10, 512), dtype=np.float32))
    state = est.components_
    mask = np.asarray(state.mask, dtype=np.float64)
    assert set(np.unique(mask)) <= {-1.0, 0.0, 1.0}
    R = est.components_as_numpy()
    v = 1.0 / np.sqrt((1 / 3) * 32)
    np.testing.assert_allclose(np.unique(np.abs(R[R != 0])), [v], rtol=1e-6)


def test_split2_determinism_and_matches_dense_state():
    """Same seed: split2 and dense materialization hold the same matrix."""
    X = np.random.default_rng(2).normal(size=(100, 512)).astype(np.float32)
    kw = dict(n_components=32, density=0.25, random_state=3, backend="jax")
    est_split = SparseRandomProjection(
        **kw, backend_options={"precision": "split2"}
    ).fit(X)
    est_dense = SparseRandomProjection(**kw).fit(X)
    np.testing.assert_allclose(
        est_split.components_as_numpy(), est_dense.components_as_numpy(),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(est_split.transform(X)), np.asarray(est_dense.transform(X)),
        rtol=1e-3, atol=1e-3,
    )
    np.testing.assert_array_equal(
        np.asarray(est_split.transform(X)), np.asarray(est_split.transform(X))
    )


def test_split2_sign_rp_packed():
    X = np.random.default_rng(0).normal(size=(50, 256)).astype(np.float32)
    # SignRandomProjection is gaussian-kind → split2 must refuse
    with pytest.raises(ValueError, match="split2"):
        SignRandomProjection(
            64, random_state=0, backend="jax",
            backend_options={"precision": "split2"},
        ).fit(X)


def test_split2_rejects_gaussian():
    from randomprojection_tpu import GaussianRandomProjection

    with pytest.raises(ValueError, match="split2"):
        GaussianRandomProjection(
            8, random_state=0, backend="jax",
            backend_options={"precision": "split2"},
        ).fit(np.zeros((10, 64), dtype=np.float32))


def test_split2_inverse_roundtrip():
    X = np.random.default_rng(1).normal(size=(128, 512)).astype(np.float32)
    est = SparseRandomProjection(
        n_components=48, density=1 / 3, random_state=0, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(X)
    Y = np.asarray(est.transform(X))
    Xhat = est.inverse_transform(Y)
    np.testing.assert_allclose(
        np.asarray(est.transform(Xhat)), Y, rtol=1e-2, atol=1e-3
    )


def test_invalid_precision_rejected():
    from randomprojection_tpu.backends.jax_backend import JaxBackend

    with pytest.raises(ValueError, match="precision"):
        JaxBackend(precision="bogus")
