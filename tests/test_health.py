"""Health plane (ISSUE 18 / r20): the --health spec grammar, the four
detectors' fire/clear hysteresis and window math under an explicit
clock (no threads, no sleeps), the HealthEngine's verdict lifecycle +
gauge mirroring + watchdog-tripped flight dump, the FlightRecorder ring
/ atomic dump / install-uninstall, the doctor's postmortem
reconstruction, GET /health over real HTTP (503 while a critical
detector fires, 200 after it clears), the drop-never-block pin with the
engine subscribed, and a subprocess SIGTERM kill leg."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from randomprojection_tpu.utils import health, metrics_server, telemetry
from randomprojection_tpu.utils.health import (
    BurnRateDetector,
    DegradedSpikeDetector,
    HealthEngine,
    QueuePinnedDetector,
    StallWatchdog,
    parse_slo_spec,
)
from randomprojection_tpu.utils.telemetry import EVENTS, FlightRecorder
from randomprojection_tpu.utils.trace_report import (
    build_postmortem,
    render_postmortem,
)


def _latency(total_s, label=None, server="topk", ts=None):
    rec = {"event": EVENTS.SERVE_LATENCY_REQUEST, "total_s": total_s,
           "server": server}
    if label is not None:
        rec["label"] = label
    if ts is not None:
        rec["ts"] = ts
    return rec


# -- parse_slo_spec ----------------------------------------------------------


def test_parse_slo_spec_grammar():
    assert parse_slo_spec(None) == {
        "default_ms": None, "labels": {}, "config": {}
    }
    assert parse_slo_spec("") == {
        "default_ms": None, "labels": {}, "config": {}
    }
    spec = parse_slo_spec("25, tenant-a=10, budget=0.05, stall=2.5")
    assert spec["default_ms"] == 25.0
    assert spec["labels"] == {"tenant-a": 10.0}
    assert spec["config"] == {"budget": 0.05, "stall": 2.5}
    # every reserved key routes to config, never to labels
    spec = parse_slo_spec(
        "budget=0.01,fast=1,slow=5,fire=8,clear=4,stall=3,tick=0.1"
    )
    assert not spec["labels"]
    assert set(spec["config"]) == set(health._SPEC_KEYS)


@pytest.mark.parametrize("bad", [
    "not-a-number",           # bare entry that isn't a float
    "tenant-a=fast",          # label value that isn't a float
    "tenant-a=0",             # non-positive target
    "budget=-1",              # non-positive config value
    "-1",                     # non-positive bare default
    "=5",                     # empty label
])
def test_parse_slo_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_slo_spec(bad)


# -- BurnRateDetector --------------------------------------------------------


def test_burn_rate_windows_fire_independently():
    """A burst confined to the fast window fires ONLY the fast key: the
    slow window amortizes the same violations below fire_burn."""
    det = BurnRateDetector(parse_slo_spec("10,fast=5,slow=60"))
    t0 = 1000.0
    # 300 in-SLO requests spread over the 50s before the burst...
    for i in range(300):
        det.on_event(_latency(0.001), t0 + i * (50.0 / 300.0))
    # ...then a 4s burst of hard violations
    for i in range(20):
        det.on_event(_latency(0.5), t0 + 50.0 + i * 0.2)
    now = t0 + 55.0
    det.evaluate(now)
    fired = dict(det.firing_keys())
    assert "topk[*]/fast" in fired
    assert "topk[*]/slow" not in fired
    assert fired["topk[*]/fast"]["burn"] == pytest.approx(100.0)
    assert fired["topk[*]/fast"]["window"] == "fast"
    # once the burst ages out of the fast window the key clears, with
    # the held duration stamped on the transition
    det.drain()
    det.evaluate(t0 + 62.0)
    trans = det.drain()
    assert [t["status"] for t in trans] == ["cleared"]
    assert trans[0]["key"] == "topk[*]/fast"
    assert trans[0]["held_s"] >= 0


def test_burn_rate_min_count_gates_thin_evidence():
    """5 violations out of 5 samples is burn 100 — but below min_count
    it must NOT fire (one slow request at startup is not an incident)."""
    det = BurnRateDetector(parse_slo_spec("10"), min_count=10)
    for i in range(5):
        det.on_event(_latency(0.5), 1000.0 + i * 0.1)
    det.evaluate(1001.0)
    assert det.firing_keys() == []


def test_burn_rate_hysteresis_band_holds():
    """Between clear_burn and fire_burn the verdict keeps its previous
    state: a not-firing key stays off, a firing key stays on."""
    spec = parse_slo_spec("10,fast=5,slow=60,fire=10,clear=5")
    det = BurnRateDetector(spec)
    # 7% violations => burn 7: inside the band, never fired => stays off
    for i in range(100):
        det.on_event(_latency(0.5 if i < 7 else 0.001), 1000.0 + i * 0.04)
    det.evaluate(1004.5)
    assert det.firing_keys() == []
    # push to burn 100 => fires
    for i in range(50):
        det.on_event(_latency(0.5), 1004.5 + i * 0.01)
    det.evaluate(1005.1)
    assert any(k.endswith("/fast") for k, _ in det.firing_keys())
    det.drain()
    # decay back into the band (burn 7): the firing key must HOLD
    det2_now = 1012.0  # violations aged out of fast; seed band-rate mix
    for i in range(100):
        det.on_event(
            _latency(0.5 if i < 7 else 0.001), det2_now + i * 0.04
        )
    det.evaluate(det2_now + 4.5)
    assert any(k.endswith("/fast") for k, _ in det.firing_keys())
    assert all(t["status"] != "cleared" for t in det.drain()
               if t["key"].endswith("/fast"))


def test_burn_rate_per_label_targets():
    """A per-label target grades that label's requests; other labels
    fall back to the default."""
    det = BurnRateDetector(parse_slo_spec("100,tenant-a=1,fast=5,slow=60"))
    for i in range(20):
        # 10ms requests: violate tenant-a's 1ms, honor tenant-b's 100ms
        det.on_event(_latency(0.010, label="tenant-a"), 1000.0 + i * 0.1)
        det.on_event(_latency(0.010, label="tenant-b"), 1000.0 + i * 0.1)
    det.evaluate(1002.5)
    keys = [k for k, _ in det.firing_keys()]
    assert any(k.startswith("topk[tenant-a]/") for k in keys)
    assert not any(k.startswith("topk[tenant-b]/") for k in keys)
    fields = dict(det.firing_keys())["topk[tenant-a]/fast"]
    assert fields["target_ms"] == 1.0


def test_burn_rate_constructor_validation():
    with pytest.raises(ValueError):
        BurnRateDetector(parse_slo_spec("10,budget=2"))  # budget > 1
    with pytest.raises(ValueError):
        BurnRateDetector(parse_slo_spec("10,fast=60,slow=60"))
    with pytest.raises(ValueError):
        BurnRateDetector(parse_slo_spec("10,fire=5,clear=5"))


def test_burn_rate_refire_is_rate_limited():
    """A still-firing key re-emits at most every refire_s — not once
    per tick."""
    det = BurnRateDetector(parse_slo_spec("10,fast=5,slow=60"),
                           refire_s=10.0)
    for i in range(20):
        det.on_event(_latency(0.5), 1000.0 + i * 0.1)
    det.evaluate(1002.5)
    assert sum(t["status"] == "firing" for t in det.drain()) >= 1
    for dt in (0.25, 0.5, 0.75, 1.0):  # four more ticks, well inside
        det.on_event(_latency(0.5), 1002.5 + dt)
        det.evaluate(1002.5 + dt)
    assert det.drain() == []  # dedup: no re-emission inside refire_s
    det.on_event(_latency(0.5), 1013.5)
    det.evaluate(1013.5)  # past refire_s: one rate-limited re-emit
    refires = [t for t in det.drain() if t["status"] == "firing"]
    assert len(refires) >= 1
    assert all(t["since"] <= 1002.5 for t in refires)


# -- StallWatchdog -----------------------------------------------------------


def _feed_stall(det, t0, beats=5, stage="hash", depth=2):
    for i in range(beats):
        det.on_event(
            {"event": EVENTS.SPAN_START, "name": stage}, t0 + i * 0.1
        )
    det.on_event(
        {"event": EVENTS.STREAM_PREFETCH_DELIVER, "queue_depth": depth,
         "capacity": 2},
        t0 + beats * 0.1,
    )


def test_stall_fires_after_timeout_with_pinned_queue():
    det = StallWatchdog(timeout_s=5.0, min_events=3)
    _feed_stall(det, 1000.0)
    det.evaluate(1003.0)   # only ~2.5s silent: not yet
    assert det.firing_keys() == []
    det.evaluate(1006.0)   # >5s silent, queue sample stale at depth 2
    fired = dict(det.firing_keys())
    assert "hash" in fired
    assert fired["hash"]["silent_s"] >= 5.0
    assert fired["hash"]["queue_depth"] == 2
    # a fresh heartbeat clears the stall
    det.drain()
    det.on_event({"event": EVENTS.SPAN_END, "name": "hash"}, 1007.0)
    det.evaluate(1007.5)
    assert det.firing_keys() == []
    assert [t["status"] for t in det.drain()] == ["cleared"]


def test_stall_drained_queue_is_end_of_run_not_stall():
    """Silence with the last delivered depth at 0 is a FINISHED run —
    the queue guard must hold the verdict down."""
    det = StallWatchdog(timeout_s=5.0, min_events=3)
    _feed_stall(det, 1000.0, depth=0)
    det.evaluate(1020.0)
    assert det.firing_keys() == []


def test_stall_min_events_gates_stage_that_never_started():
    det = StallWatchdog(timeout_s=5.0, min_events=3)
    det.on_event({"event": EVENTS.SPAN_START, "name": "h2d"}, 1000.0)
    det.on_event(
        {"event": EVENTS.STREAM_PREFETCH_DELIVER, "queue_depth": 2,
         "capacity": 2},
        1000.0,
    )
    det.evaluate(1020.0)
    assert det.firing_keys() == []


# -- QueuePinnedDetector -----------------------------------------------------


def test_queue_pinned_fires_after_window_and_clears_below_capacity():
    det = QueuePinnedDetector(window_s=5.0)
    assert det.critical is False
    det.on_event(
        {"event": EVENTS.STREAM_PREFETCH_DELIVER, "queue_depth": 4,
         "capacity": 4},
        1000.0,
    )
    det.evaluate(1003.0)
    assert det.firing_keys() == []      # pinned 3s < window
    det.evaluate(1006.0)
    fired = dict(det.firing_keys())
    assert "queue" in fired and fired["queue"]["depth"] == 4
    det.drain()
    # one below-capacity sample clears immediately
    det.on_event(
        {"event": EVENTS.STREAM_PREFETCH_DELIVER, "queue_depth": 3,
         "capacity": 4},
        1007.0,
    )
    det.evaluate(1007.5)
    assert det.firing_keys() == []
    assert [t["status"] for t in det.drain()] == ["cleared"]


# -- DegradedSpikeDetector ---------------------------------------------------


def test_degraded_spike_steady_rate_is_a_known_condition():
    """A counter that has ALWAYS ticked at 5/s must not fire — the
    spike threshold grades the fast rate against the slow baseline."""
    det = DegradedSpikeDetector(counters=("c",), fast_window_s=5.0,
                                slow_window_s=60.0, min_rate=1.0,
                                spike_ratio=10.0)
    for i in range(61):
        det.observe("c", 5.0 * i, 1000.0 + i)   # steady 5/s
    det.evaluate(1060.0)
    assert det.firing_keys() == []


def test_degraded_spike_burst_fires_and_clears():
    det = DegradedSpikeDetector(counters=("c",), fast_window_s=5.0,
                                slow_window_s=60.0, min_rate=1.0,
                                spike_ratio=10.0)
    # near-flat for 55s, then +100 in the final 3s
    for i in range(56):
        det.observe("c", 0.0, 1000.0 + i)
    for i in range(4):
        det.observe("c", 25.0 * i, 1057.0 + i)
    det.evaluate(1060.0)
    fired = dict(det.firing_keys())
    assert "c" in fired
    assert fired["c"]["fast_rate"] > fired["c"]["baseline_rate"]
    det.drain()
    # the counter stops moving: fast rate decays to 0 and the key clears
    for i in range(8):
        det.observe("c", 75.0, 1061.0 + i)
    det.evaluate(1069.0)
    assert det.firing_keys() == []
    assert [t["status"] for t in det.drain()] == ["cleared"]


# -- HealthEngine ------------------------------------------------------------


def test_engine_emits_typed_verdicts_and_mirrors_gauges():
    """A manually-clocked engine pass emits the EVENTS-registered
    verdict on the spine and mirrors a firing-count gauge."""
    eng = HealthEngine(slo=parse_slo_spec("10,fast=5,slow=60"),
                       detectors=[
                           BurnRateDetector(parse_slo_spec("10,fast=5,slow=60"))
                       ])
    got = []
    sub = telemetry.subscribe(got.append, name="t-health")
    try:
        for i in range(20):
            eng._on_event(_latency(0.5, ts=1000.0 + i * 0.1))
        out = eng.evaluate(now=1002.5)
        assert any(
            o["event"] == EVENTS.HEALTH_SLO_BURN
            and o["status"] == "firing" for o in out
        )
        assert not eng.ok()
        active = eng.active()
        assert active and all(v["critical"] for v in active)
        snap = telemetry.registry().snapshot()
        assert snap["gauges"]["health.slo_burn.firing"]["last"] >= 1
        # clear: windows empty after slow horizon
        out = eng.evaluate(now=1002.5 + 61.0)
        assert any(o["status"] == "cleared" for o in out)
        assert eng.ok() and eng.active() == []
        assert telemetry.registry().snapshot()["gauges"][
            "health.slo_burn.firing"
        ]["last"] == 0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            stats = {r.get("status") for r in got
                     if r.get("event") == EVENTS.HEALTH_SLO_BURN}
            if {"firing", "cleared"} <= stats:
                break
            time.sleep(0.01)
        assert {"firing", "cleared"} <= stats
    finally:
        telemetry.unsubscribe(sub)


def test_engine_ignores_its_own_verdict_events():
    """health.* events must never feed back into detectors."""

    class Probe(health._Detector):
        event = EVENTS.HEALTH_QUEUE_PINNED
        seen: list = []

        def on_event(self, rec, now):
            self.seen.append(rec["event"])

        def evaluate(self, now):
            pass

    probe = Probe()
    eng = HealthEngine(detectors=[probe])
    eng._on_event({"event": EVENTS.HEALTH_SLO_BURN, "status": "firing",
                   "ts": 1.0})
    eng._on_event({"event": EVENTS.HEALTH_FLIGHT_DUMP, "ts": 1.0})
    eng._on_event({"event": EVENTS.STREAM_COMMIT, "ts": 1.0})
    assert probe.seen == [EVENTS.STREAM_COMMIT]


def test_engine_noncritical_detector_keeps_health_ok():
    """queue_pinned / degraded_spike grade but do not 503."""
    det = QueuePinnedDetector(window_s=5.0)
    eng = HealthEngine(detectors=[det])
    eng._on_event(
        {"event": EVENTS.STREAM_PREFETCH_DELIVER, "queue_depth": 4,
         "capacity": 4, "ts": 1000.0}
    )
    eng.evaluate(now=1006.0)
    assert eng.active() and eng.ok()  # firing, but not critical


def test_engine_watchdog_trip_dumps_flight_recorder_once_per_stage():
    class StubRecorder:
        def __init__(self):
            self.reasons = []

        def dump(self, reason=None, **kw):
            self.reasons.append(reason)

    rec = StubRecorder()
    det = StallWatchdog(timeout_s=5.0, min_events=3)
    eng = HealthEngine(detectors=[det], recorder=rec)
    _feed_stall(det, 1000.0)
    eng.evaluate(now=1006.0)
    eng.evaluate(now=1007.0)  # still stalled: must NOT dump again
    assert rec.reasons == ["watchdog:hash"]


def test_engine_spec_config_reaches_detectors():
    eng = HealthEngine(slo=parse_slo_spec("10,stall=2,tick=0.05"))
    assert eng.tick_s == 0.05
    stalls = [d for d in eng.detectors if isinstance(d, StallWatchdog)]
    pinned = [d for d in eng.detectors
              if isinstance(d, QueuePinnedDetector)]
    assert stalls[0].timeout_s == 2.0 and pinned[0].window_s == 2.0
    with pytest.raises(ValueError):
        HealthEngine(tick_s=0.0)


def test_engine_close_is_idempotent_and_detaches():
    eng = HealthEngine(slo=parse_slo_spec("10")).start()
    assert telemetry.enabled()
    eng.close()
    eng.close()
    assert not telemetry.enabled()


# -- FlightRecorder ----------------------------------------------------------


def test_flight_recorder_ring_is_bounded_oldest_first():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec({"event": "e", "i": i})
    snap = rec.snapshot()
    assert [r["i"] for r in snap] == [6, 7, 8, 9]
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_flight_recorder_dump_format_and_health_section(tmp_path):
    rec = FlightRecorder(capacity=8)
    rec({"event": EVENTS.STREAM_COMMIT, "row": 1, "ts": 10.0})
    assert rec.dump() is None  # no path known yet
    path = str(tmp_path / "dump.json")
    rec.attach_health(lambda: [{"detector": "health.stall", "key": "h2d",
                                "critical": True}])
    out = rec.dump(path, reason="on_demand")
    assert out == path
    with open(path) as f:
        dump = json.load(f)
    assert dump["format"] == FlightRecorder.FORMAT
    assert dump["v"] == FlightRecorder.VERSION
    assert dump["pid"] == os.getpid()
    assert dump["reason"] == "on_demand"
    assert dump["capacity"] == 8
    assert dump["events"][0]["row"] == 1
    assert "counters" in dump
    assert dump["health"][0]["key"] == "h2d"
    # no leftover tmp file: the write is tmp -> fsync -> replace
    assert [p.name for p in tmp_path.iterdir()] == ["dump.json"]


def test_flight_recorder_install_uninstall_restores_dispositions(
    tmp_path,
):
    rec = FlightRecorder()
    prev_sig = signal.getsignal(signal.SIGUSR1)
    prev_hook = sys.excepthook
    rec.install(str(tmp_path / "d.json"), signals=(signal.SIGUSR1,))
    try:
        assert signal.getsignal(signal.SIGUSR1) is not prev_sig
        assert sys.excepthook is not prev_hook
    finally:
        rec.uninstall()
    assert signal.getsignal(signal.SIGUSR1) is prev_sig
    assert sys.excepthook is prev_hook
    rec.uninstall()  # idempotent


# -- doctor --postmortem -----------------------------------------------------


def test_build_postmortem_names_open_span_stage(tmp_path):
    """The stage with a span still OPEN in the ring wins last-active,
    even when another stage heartbeated later."""
    rec = FlightRecorder()
    sub = telemetry.subscribe(rec, name="t-pm")
    try:
        with telemetry.span("hash", new_trace=True):
            pass
        telemetry.emit(EVENTS.SPAN_START, name="dispatch", span_id="s1",
                       trace_id="t1")
        with telemetry.span("enqueue_wait", new_trace=True):
            pass
        deadline = time.monotonic() + 5.0
        while len(rec.snapshot()) < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        telemetry.unsubscribe(sub)
    path = str(tmp_path / "d.json")
    rec.dump(path, reason="on_demand")
    with open(path) as f:
        pm = build_postmortem(json.load(f))
    assert pm["last_active_stage"] == "dispatch"
    assert any(s["name"] == "dispatch" for s in pm["in_flight"])
    stages = {r["stage"] for r in pm["stages"]}
    assert {"hash", "dispatch", "enqueue_wait"} <= stages
    text = render_postmortem(pm)
    assert "last active stage: dispatch" in text
    assert "spans in flight at death:" in text


def test_build_postmortem_rejects_foreign_artifact():
    with pytest.raises(ValueError):
        build_postmortem({"format": "topk_slo", "events": []})


# -- GET /health over real HTTP ---------------------------------------------


def _http_health(port):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=5.0
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_endpoint_without_engine_is_honest():
    srv = metrics_server.MetricsServer(port=0)
    try:
        code, body = _http_health(srv.port)
        assert code == 200
        assert body == {"ok": True, "attached": False, "active": []}
    finally:
        srv.close()


def test_health_endpoint_503_while_critical_fires_then_recovers():
    eng = HealthEngine(
        detectors=[BurnRateDetector(parse_slo_spec("10,fast=5,slow=60"))]
    )
    srv = metrics_server.MetricsServer(port=0, health=eng)
    try:
        for i in range(20):
            eng._on_event(_latency(0.5, ts=1000.0 + i * 0.1))
        eng.evaluate(now=1002.5)
        code, body = _http_health(srv.port)
        assert code == 503
        assert body["ok"] is False and body["attached"] is True
        assert body["active"][0]["detector"] == EVENTS.HEALTH_SLO_BURN
        eng.evaluate(now=1002.5 + 61.0)
        code, body = _http_health(srv.port)
        assert code == 200 and body["ok"] is True and body["active"] == []
    finally:
        srv.close()


# -- drop-never-block with the engine subscribed -----------------------------


def test_engine_subscription_never_blocks_the_emitter():
    """Same bound as the r17 pin (tests/test_live_plane.py): with a
    real started HealthEngine folding every event, 500 emits stay under
    2s wall — the hot path pays only a put_nowait."""
    eng = HealthEngine(slo=parse_slo_spec("10,fast=1,slow=2")).start()
    try:
        n = 500
        t0 = time.perf_counter()
        for i in range(n):
            telemetry.emit(
                EVENTS.SERVE_LATENCY_REQUEST, total_s=0.5, server="topk"
            )
        emit_wall = time.perf_counter() - t0
        assert emit_wall < 2.0, f"emit path blocked: {emit_wall:.3f}s"
    finally:
        eng.close()


# -- subprocess kill leg -----------------------------------------------------


def test_sigterm_mid_stream_bench_leaves_renderable_postmortem(tmp_path):
    """The kill-matrix leg: SIGTERM a real stream-bench --flight-dump
    run mid-flight; the process must die BY the signal (exit -15, not a
    clean 0 that would fool a supervisor), the dump must parse, and the
    postmortem must name a real pipeline stage."""
    dump_path = str(tmp_path / "dump.json")
    jsonl = str(tmp_path / "ev.jsonl")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "randomprojection_tpu", "stream-bench",
            "--rows", "80000000", "--d", "256", "--k", "32",
            "--batch-rows", "8192", "--backend", "numpy",
            "--prefetch-batches", "2", "--flight-dump", dump_path,
            "--telemetry-jsonl", jsonl,
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            assert proc.poll() is None, (
                f"stream-bench exited rc={proc.returncode} before kill"
            )
            if os.path.exists(jsonl) and os.path.getsize(jsonl) > 4096:
                break
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == -signal.SIGTERM
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["reason"] == "signal:SIGTERM"
    assert dump["events"], "ring dumped empty mid-flight"
    pm = build_postmortem(dump)
    assert pm["last_active_stage"] in (
        "hash", "enqueue_wait", "h2d", "dispatch", "d2h", "batch"
    )
    render_postmortem(pm)  # must not raise
