"""Multi-probe LSH candidate tier (ISSUE 15): band keys, banded CSR
buckets, perturbation order, full-probe bit-parity with brute force,
the fallback ladder, sharded probing + merge, serving through the
micro-batchers, durability (incl. layout fungibility, compact remap and
pre-LSH snapshots), telemetry/doctor integration, and the bench
fixture's recall/candidate-fraction acceptance gates.

Shape discipline: the fused re-rank kernel compiles one interpreter
program per (query tile, candidate row bucket, n_bytes, m) — so these
tests standardize on ONE family (8-byte codes, bands=4/band_bits=8,
m=5, 8-row query tiles, 400-row corpora) wherever the assertion
allows, sharing compiled programs across tests instead of paying a
multi-second compile per novel shape."""

import json
import os

import numpy as np
import pytest

from randomprojection_tpu.ann import (
    BandedBuckets,
    BandPlan,
    LSHShardedSimHashIndex,
    LSHSimHashIndex,
    band_keys,
    load_lsh_index,
    load_lsh_sharded_index,
    probe_masks,
)
from randomprojection_tpu.models import sketch as sk
from randomprojection_tpu.utils import telemetry

# the shared shape family (see module docstring)
N, NB, M, FULL = 400, 8, 5, 1 << 8
BANDS = dict(bands=4, band_bits=8)


def _rand_codes(n, nbytes, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, nbytes), dtype=np.uint8
    )


def _corpus(seed=0):
    return _rand_codes(N, NB, seed=seed)


def _queries(seed=100):
    return _rand_codes(8, NB, seed=seed)


# -- band keys / plan --------------------------------------------------------


def test_band_plan_defaults_and_validation():
    p = BandPlan(256)
    assert (p.bands, p.band_bits) == (8, 16)
    p = BandPlan(64)
    assert (p.bands, p.band_bits) == (4, 16)
    p = BandPlan(8)
    assert (p.bands, p.band_bits) == (1, 8)
    with pytest.raises(ValueError, match="bands=3 x band_bits=8"):
        BandPlan(20, bands=3, band_bits=8)
    with pytest.raises(ValueError, match="band_bits"):
        BandPlan(64, band_bits=0)
    with pytest.raises(ValueError, match="band_bits"):
        BandPlan(64, band_bits=24)  # past the bucket-space ceiling


def test_band_keys_match_bit_reference():
    codes = _rand_codes(50, 3)
    # ragged: 20 real bits in 3 bytes -> 2 bands of 10
    plan = BandPlan(20, bands=2, band_bits=10)
    keys = band_keys(codes, plan)
    bits = np.unpackbits(codes, axis=1, bitorder="little")
    for j in range(2):
        ref = (
            bits[:, j * 10 : (j + 1) * 10].astype(np.uint32)
            * (1 << np.arange(10, dtype=np.uint32))
        ).sum(1)
        assert np.array_equal(keys[j], ref)
    assert keys.dtype == np.uint32 and keys.shape == (2, 50)


def test_probe_masks_popcount_then_value_order():
    masks = probe_masks(4, 16)
    assert masks[0] == 0  # the exact bucket probes first
    pops = [bin(int(v)).count("1") for v in masks]
    assert pops == sorted(pops)  # single flips before pairs before ...
    # within one popcount class, ascending numeric value
    for c in range(5):
        vals = [int(v) for v, p in zip(masks, pops) if p == c]
        assert vals == sorted(vals)
    # full coverage enumerates every bucket exactly once, and the
    # request caps there
    assert sorted(int(v) for v in masks) == list(range(16))
    assert probe_masks(4, 999).size == 16
    assert list(probe_masks(4, 3)) == [0, 1, 2]


# -- banded CSR buckets (pure host) ------------------------------------------


def test_buckets_incremental_add_matches_fresh_build():
    codes = _rand_codes(300, 4, seed=1)
    plan = BandPlan(32, bands=4, band_bits=8)
    inc = BandedBuckets(plan)
    inc.add(codes[:37])
    inc.add(codes[37:37])  # empty append is a no-op
    inc.add(codes[37:200])
    inc.add(codes[200:])
    fresh = BandedBuckets(plan)
    fresh.add(codes)
    assert np.array_equal(inc.keys, fresh.keys)
    for j in range(plan.bands):
        assert np.array_equal(inc._indptr[j], fresh._indptr[j])
        assert np.array_equal(inc._ids[j], fresh._ids[j])
        # within-bucket ids ascending (the tie-order invariant)
        nb = 1 << plan.band_bits
        for k in range(0, nb, 17):
            run = fresh.bucket_ids(j, k)
            assert np.array_equal(run, np.sort(run))


def test_buckets_candidates_are_union_of_probed_runs():
    plan = BandPlan(16, bands=2, band_bits=8)
    codes = _rand_codes(120, 2, seed=2)
    b = BandedBuckets(plan)
    b.add(codes)
    qkeys = band_keys(codes[:3], plan)
    masks = probe_masks(8, 2)  # exact bucket + lowest-bit flip
    cand, gathered = b.candidates(qkeys, masks)
    ref = set()
    total = 0
    for j in range(2):
        for q in range(3):
            for mk in masks:
                run = b.bucket_ids(j, int(qkeys[j, q]) ^ int(mk))
                ref.update(int(v) for v in run)
                total += run.size
    assert set(int(v) for v in cand) == ref
    assert np.array_equal(cand, np.sort(cand))
    assert gathered == total  # pre-dedup count on the record


# -- full-probe parity + ladder ----------------------------------------------
@pytest.mark.slow
def test_full_probe_parity_multichunk_ragged_tombstones():
    codes = _rand_codes(360, 3, seed=3)
    q = _rand_codes(6, 3, seed=4)
    # ragged 20-bit codes across 3 chunks, tombstones filtered at
    # re-rank, ragged query tiling (6 rows over tile=3 -> 2 tiles)
    idx = LSHSimHashIndex(codes[:150], n_bits=20, bands=2, band_bits=10,
                          fallback_density=1.0)
    idx.add(codes[150:280])
    idx.add(codes[280:])
    idx.delete(np.arange(50, 240, 7))
    d, i = idx.query_topk(q, M, probes=1 << 10, tile=3)
    D = sk.pairwise_hamming(q, codes).astype(np.int64)
    D[:, idx._dead] = 20 + 1
    rd, ri = sk._host_topk_select(D, M)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
@pytest.mark.slow
def test_fallback_ladder_density_and_starvation():
    codes = _corpus(seed=5)
    q = _queries(seed=6)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    reg = telemetry.registry()

    # dense: a uniform corpus at a permissive band floods the union past
    # the threshold -> the exact ladder serves, results identical
    dense = LSHSimHashIndex(codes, bands=2, band_bits=2,
                            fallback_density=0.05)
    f0 = reg.counter("index.lsh.fallbacks")
    d, i = dense.query_topk(q, M, probes=1)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    assert reg.counter("index.lsh.fallbacks") > f0

    # starved: a sparse band at 1 probe yields < m candidates -> exact
    starved = LSHSimHashIndex(codes, bands=1, band_bits=16,
                              fallback_density=1.0)
    f0 = reg.counter("index.lsh.fallbacks")
    d, i = starved.query_topk(q, M, probes=1)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    assert reg.counter("index.lsh.fallbacks") > f0
@pytest.mark.slow
def test_probes_zero_and_constructor_default():
    codes = _corpus(seed=7)
    q = _queries(seed=8)
    idx = LSHSimHashIndex(codes, **BANDS, probes=FULL,
                          fallback_density=1.0)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    # probes=0 pins the exact path outright
    d, i = idx.query_topk(q, M, probes=0)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    # no per-call override -> the constructor default (full coverage
    # here, so exact again) — the TopKServer serving path
    d, i = idx.query_topk(q, M)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    with pytest.raises(ValueError, match="probes"):
        LSHSimHashIndex(codes, probes=0)
    with pytest.raises(ValueError, match="fallback_density"):
        LSHSimHashIndex(codes, fallback_density=0.0)
    with pytest.raises(ValueError, match="single-device"):
        LSHSimHashIndex(codes, mesh=object())


def test_probes_validated_per_call():
    codes = _rand_codes(64, NB, seed=60)
    idx = LSHSimHashIndex(codes, **BANDS)
    sh = LSHShardedSimHashIndex(codes, n_shards=2, **BANDS)
    q = _queries(seed=61)
    # a float (e.g. computed from a recall target) must raise, not
    # silently truncate to fewer probes than requested — same
    # validation as the constructor knob
    for bad in (2.9, -1, "4"):
        with pytest.raises(ValueError, match="probes"):
            idx.query_topk(q, 3, probes=bad)
        with pytest.raises(ValueError, match="probes"):
            sh.query_topk(q, 3, probes=bad)


def test_rerank_vmem_oom_memoizes_host_rung(monkeypatch):
    """A re-rank shape that hits a scoped-VMEM OOM serves the host rung
    AND memoizes: the failed kernel dispatch is never re-paid at that
    shape (r6 convention, mirroring _fused_degraded)."""
    from randomprojection_tpu.ops import topk_kernels

    codes = _corpus(seed=62)
    q = _queries(seed=63)
    idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    calls = []

    def fake_oom(*a, **k):
        calls.append(1)
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: allocating scoped vmem exceeds limit"
        )

    monkeypatch.setattr(topk_kernels, "fused_topk", fake_oom)
    d, i = idx.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    assert len(calls) == 1
    # same shape again: the memo routes straight to the host rung
    d, i = idx.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    assert len(calls) == 1


def test_rerank_host_rung_parity(monkeypatch):
    """With the fused planner knocked out, the device-Hamming + host
    select rung serves the re-rank — same (dist, lower-id) results."""
    from randomprojection_tpu.ops import topk_kernels

    codes = _corpus(seed=9)
    q = _queries(seed=10)
    idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    monkeypatch.setattr(topk_kernels, "plan_fused",
                        lambda *a, **k: None)
    d, i = idx.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)


# -- sharded tier ------------------------------------------------------------


@pytest.mark.slow
def test_sharded_full_probe_parity_tombstones_id_offset():
    codes = _corpus(seed=11)
    q = _queries(seed=12)
    off = 2**31 + 7  # global ids past int32, like the shard smoke
    sh = LSHShardedSimHashIndex(codes, n_shards=3, **BANDS,
                                fallback_density=1.0, id_offset=off)
    dead = np.arange(90, 210)  # spans shard boundaries (3x~133 rows)
    sh.delete(dead + off)
    D = sk.pairwise_hamming(q, codes).astype(np.int64)
    D[:, dead] = NB * 8 + 1
    rd, ri = sk._host_topk_select(D, M)
    d, i = sh.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd)
    assert np.array_equal(i, ri.astype(np.int64) + off)
    # partial probes: every answer's distance is the true Hamming of
    # the id it returned (exact re-rank, approximate candidate set)
    dp, ip = sh.query_topk(q, M, probes=2)
    assert (np.take_along_axis(D, ip - off, axis=1) == dp).all()


@pytest.mark.slow
def test_sharded_per_shard_fallback_mix():
    """Shards decide the ladder independently: a dense shard serves
    exact while the others stay on the candidate path — the merge is
    correct either way (full probes => brute parity)."""
    codes = _corpus(seed=13)
    q = _queries(seed=14)
    sh = LSHShardedSimHashIndex(codes, n_shards=3, **BANDS,
                                fallback_density=0.5)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    d, i = sh.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# -- serving integration -----------------------------------------------------
@pytest.mark.slow
def test_topkserver_serves_lsh_index():
    codes = _corpus(seed=15)
    q = _queries(seed=16)
    # full probe coverage: coalescing cannot change the (complete)
    # candidate union, so the server is bit-identical to direct calls
    idx = LSHSimHashIndex(codes, **BANDS, probes=FULL,
                          fallback_density=1.0)
    want = idx.query_topk(q, M)  # the constructor default serves
    with sk.TopKServer(idx, M, max_delay_s=0.0) as srv:
        got = srv.query(q)
    assert np.array_equal(got[0], want[0])
    assert np.array_equal(got[1], want[1])
    # partial probes: the candidate union is tile-scoped, so coalescing
    # (row-bucket padding included) may ENLARGE a query's candidate set
    # — answers are monotone: never worse than the direct call's
    idx2 = LSHSimHashIndex(codes, **BANDS, probes=2,
                           fallback_density=1.0)
    direct = idx2.query_topk(q, M)
    with sk.TopKServer(idx2, M, max_delay_s=0.0) as srv:
        coalesced = srv.query(q)
    assert (coalesced[0] <= direct[0]).all()


@pytest.mark.slow
def test_sharded_topkserver_serves_lsh_replicas():
    from randomprojection_tpu.serving import ShardedTopKServer

    codes = _corpus(seed=17)
    q = _queries(seed=18)
    groups = [
        LSHShardedSimHashIndex(codes, n_shards=2, **BANDS, probes=FULL,
                               fallback_density=1.0)
        for _ in range(2)
    ]
    rd, ri = sk.topk_bruteforce(q, codes, M)
    with ShardedTopKServer(groups, M, max_delay_s=0.0) as srv:
        d, i = srv.query(q)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# -- durability --------------------------------------------------------------
@pytest.mark.slow
def test_durable_roundtrip_bit_identical_keys(tmp_path):
    from randomprojection_tpu import durable

    codes = _corpus(seed=19)
    idx = LSHSimHashIndex(codes, **BANDS, probes=3,
                          fallback_density=0.7)
    idx.delete([5, 9, 300])
    path = str(tmp_path / "snap")
    manifest = idx.save(path)
    assert manifest["lsh"]["bands"] == 4
    assert manifest["lsh"]["rows"] == N
    assert os.path.exists(os.path.join(path, manifest["lsh"]["file"]))
    back = load_lsh_index(path)
    assert np.array_equal(back._buckets.keys, idx._buckets.keys)
    for j in range(4):
        assert np.array_equal(back._buckets._ids[j], idx._buckets._ids[j])
    # serving knobs restore from the manifest
    assert back.probes == 3 and back.fallback_density == 0.7
    q = _queries(seed=20)
    a = idx.query_topk(q, M, probes=FULL)
    b = back.query_topk(q, M, probes=FULL)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    # re-save rewrites a new generation and sweeps the old keys spill
    manifest2 = back.save(path)
    assert manifest2["lsh"]["file"] != manifest["lsh"]["file"]
    assert not os.path.exists(
        os.path.join(path, manifest["lsh"]["file"])
    )
    # verify_snapshot checksums the keys spill like any chunk
    status = durable.verify_snapshot(path)
    assert status["ok"] and status["lsh"] == {"bands": 4, "band_bits": 8}


def test_durable_corrupt_keys_fail_loud(tmp_path):
    from randomprojection_tpu import durable

    codes = _rand_codes(120, 4, seed=21)
    idx = LSHSimHashIndex(codes, bands=2, band_bits=8)
    path = str(tmp_path / "snap")
    manifest = idx.save(path)
    keys_file = os.path.join(path, manifest["lsh"]["file"])
    # payload corruption -> checksum verification fails loudly
    arr = np.load(keys_file)
    arr[0, 0] ^= 1
    with open(keys_file, "wb") as f:
        np.save(f, arr)
    with pytest.raises(ValueError, match="checksum"):
        load_lsh_index(path)
    # a VALID checksum over DRIFTED keys still fails: persisted keys
    # must equal keys rebuilt from the codes, bit for bit
    manifest["lsh"]["sha256"] = durable._sha256(arr)
    durable._commit_manifest(path, manifest)
    with pytest.raises(ValueError, match="disagree"):
        load_lsh_index(path)


def test_durable_layout_fungible_and_pre_lsh(tmp_path):
    codes = _corpus(seed=22)
    q = _queries(seed=23)
    sh = LSHShardedSimHashIndex(codes, n_shards=2, **BANDS,
                                fallback_density=1.0)
    sh.delete([3, 40, 120])
    path = str(tmp_path / "sharded")
    sh.save(path)
    # restore under a DIFFERENT shard count: buckets re-derive per
    # shard and the loader VERIFIES them against the persisted
    # global-id-ordered keys bit-for-bit (so the keys-equality
    # assertions below are belt and braces over the loader's own gate)
    other = load_lsh_sharded_index(path, n_shards=3)
    assert np.array_equal(other._lsh_global_keys(),
                          sh._lsh_global_keys())
    assert other.n_deleted == 3
    # ... and as a plain single-device LSH index, query-parity-checked
    single = load_lsh_index(path)
    assert np.array_equal(single._buckets.keys, sh._lsh_global_keys())
    want = sh.query_topk(q, M, probes=FULL)
    got = single.query_topk(q, M, probes=FULL)
    assert np.array_equal(want[0], got[0])
    assert np.array_equal(want[1], got[1].astype(np.int64))
    # a pre-LSH (r11-format) snapshot loads cleanly, index rebuilt
    plain_path = str(tmp_path / "plain")
    sk.SimHashIndex(codes).save(plain_path)
    rebuilt = load_lsh_index(plain_path, **BANDS)
    fresh = LSHSimHashIndex(codes, **BANDS)
    assert np.array_equal(rebuilt._buckets.keys, fresh._buckets.keys)
    # ... sharded too
    resharded = load_lsh_sharded_index(plain_path, n_shards=2, **BANDS)
    assert resharded.n_shards == 2
    assert resharded.band_plan == fresh.band_plan
    assert np.array_equal(resharded._lsh_global_keys(),
                          fresh._buckets.keys)
@pytest.mark.slow
def test_compact_remaps_buckets_consistently():
    codes = _corpus(seed=24)
    idx = LSHSimHashIndex(codes[:300], **BANDS, fallback_density=1.0)
    idx.add(codes[300:])
    dead = np.arange(30, 170, 3)
    idx.delete(dead)
    pre_keys = idx._buckets.keys.copy()
    mapping = idx.compact()
    # the folded buckets equal BOTH the remap of the pre-compact keys
    # through the returned mapping AND a fresh build over the survivors
    assert np.array_equal(idx._buckets.keys, pre_keys[:, mapping])
    fresh = LSHSimHashIndex(np.delete(codes, dead, axis=0), **BANDS)
    assert np.array_equal(idx._buckets.keys, fresh._buckets.keys)
    for j in range(4):
        assert np.array_equal(idx._buckets._ids[j], fresh._buckets._ids[j])
    q = _queries(seed=25)
    a = idx.query_topk(q, M, probes=FULL)
    b = fresh.query_topk(q, M, probes=FULL)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])


@pytest.mark.slow
def test_sharded_compact_rebuilds_per_shard_buckets():
    codes = _corpus(seed=26)
    sh = LSHShardedSimHashIndex(codes, n_shards=3, **BANDS,
                                fallback_density=1.0)
    dead = np.arange(10, 250, 5)
    sh.delete(dead)
    sh.compact()
    # per-shard bucket state tracks the re-balanced shards exactly:
    # the global key view equals a fresh build over the survivors
    live = np.delete(codes, dead, axis=0)
    fresh = LSHSimHashIndex(live, **BANDS)
    assert np.array_equal(sh._lsh_global_keys(), fresh._buckets.keys)
    for s in sh._shards:
        assert s._buckets.n == s.n_codes
    q = _queries(seed=27)
    rd, ri = sk.topk_bruteforce(q, live, M)
    d, i = sh.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# -- telemetry / doctor ------------------------------------------------------


def test_lsh_events_and_doctor_section(tmp_path):
    from randomprojection_tpu.utils import trace_report

    codes = _corpus(seed=28)
    q = _queries(seed=29)
    tel = str(tmp_path / "lsh.jsonl")
    telemetry.configure(tel)
    try:
        idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0)
        idx.query_topk(q, M, probes=FULL)         # candidate path
        starved = LSHSimHashIndex(codes, bands=1, band_bits=16)
        starved.query_topk(q, M, probes=1)        # starved -> fallback
    finally:
        telemetry.shutdown()
    names = [e["event"] for e in telemetry.read_events(tel)]
    assert "index.lsh.build" in names
    assert "index.lsh.dispatch" in names
    assert "index.lsh.fallback" in names
    report = trace_report.build_report(tel)
    cg = report["candidate_generation"]
    assert cg["lsh_tiles"] >= 1 and cg["candidates"] > 0
    assert 0.0 < cg["candidate_fraction_mean"] <= 1.0
    # bucket lookups agree with the index.lsh.probe_buckets counter's
    # definition: queries x bands x probes per tile — the one LSH tile
    # here probed 8 queries x 4 bands x 256 masks
    assert cg["lsh_tiles"] == 1
    assert cg["probed_buckets_per_tile"] == 8 * 4 * 256
    assert cg["fallbacks"].get("starved", 0) >= 1
    assert cg["builds"] >= 2
    # the fallback is on the degraded audit, and every event name is
    # registered (RP02's runtime face)
    assert report["degraded"]["index.lsh.fallback"] >= 1
    assert not report["unregistered_events"]
    text = trace_report.render_report(report)
    assert "candidate generation (multi-probe LSH)" in text
    assert "fallbacks to the exact path" in text


# -- bench record + tripwire (the ISSUE 15 acceptance gates) -----------------


@pytest.mark.slow
def test_bench_lsh_curve_meets_acceptance_gates():
    """The committed bench fixture must show a probe setting with
    recall@10 >= 0.95 while re-ranking < 10% of the corpus — asserted
    here in tier-1, exactly as the acceptance criteria demand."""
    from randomprojection_tpu import benchmark

    rec = benchmark.measure_topk_lsh("smoke")
    assert rec["m"] == 10
    assert rec["recall_gate_ok"] is True
    hl = rec["headline"]
    assert hl["recall_at_m"] >= 0.95
    assert hl["candidate_fraction"] < 0.10
    assert hl["queries_per_s"] > 0
    assert hl["fallbacks"] == 0  # the curve measured the tier itself
    # the curve is monotone in coverage: more probes never lose recall
    # on this fixture, and candidate fraction grows with probes
    recalls = [p["recall_at_m"] for p in rec["curve"]]
    fracs = [p["candidate_fraction"] for p in rec["curve"]]
    assert recalls == sorted(recalls)
    assert fracs == sorted(fracs)
    assert rec["exact_queries_per_s"] > 0
    assert "speedup_vs_exact" in hl


def test_bench_lsh_rates_compact_and_recall_tripwire():
    from randomprojection_tpu import benchmark

    lsh = {
        "curve": [
            {"probes": 1, "recall_at_m": 0.6, "candidate_fraction": 0.02,
             "queries_per_s": 900.0, "timing_suspect": False},
        ],
        "headline": None,
        "recall_gate": 0.95,
        "recall_gate_ok": False,
    }
    record = {"config4": {"topk_serving": {"lsh": lsh}}}
    # a failed recall gate becomes a regression entry on EVERY path —
    # including non-full presets where rate comparison is skipped
    out = benchmark.attach_regressions(dict(record))
    regs = [r for r in out["regressions"]
            if r["metric"] == "config4.topk.lsh_recall_gate"]
    assert len(regs) == 1
    assert regs[0]["previous"] == 0.95 and regs[0]["current"] == 0.6
    # a passing record carries no gate entry
    ok = {
        "curve": lsh["curve"],
        "headline": {"probes": 1, "recall_at_m": 0.99,
                     "candidate_fraction": 0.03,
                     "queries_per_s": 900.0, "timing_suspect": False},
        "recall_gate": 0.95,
        "recall_gate_ok": True,
    }
    out2 = benchmark.attach_regressions(
        {"config4": {"topk_serving": {"lsh": ok}}}
    )
    assert not [r for r in out2["regressions"]
                if r["metric"] == "config4.topk.lsh_recall_gate"]
    # the headline rate gates like any serving rate...
    rates = benchmark.bench_rates(
        {"config4": {"topk_serving": {"lsh": ok}}}
    )
    assert rates["config4.topk.lsh_queries_per_s"] == (900.0, False)
    # ... the compact digest flattens the headline + verdict ...
    c = benchmark.compact_summary(
        {"mode": "x", "value": 1.0,
         "config4": {"topk_serving": {"queries_per_s": 5.0, "lsh": ok}}}
    )
    assert c["config4"]["topk_lsh_recall_gate_ok"] is True
    assert c["config4"]["topk_lsh_probes"] == 1
    assert c["config4"]["topk_lsh_queries_per_s"] == 900.0
    # ... and a compact-line-only record still gates the rate
    rates2 = benchmark.bench_rates({"config4": c["config4"]})
    assert rates2["config4.topk.lsh_queries_per_s"] == (900.0, False)


@pytest.mark.slow
def test_cli_topk_bench_forwards_probes(capsys, monkeypatch):
    """`cli topk-bench --probes` measures the LSH curve alongside the
    serving modes and records recall + q/s per probe count."""
    from randomprojection_tpu import cli

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cli.main([
        "topk-bench", "--index-codes", str(N), "--code-bytes", str(NB),
        "--m", str(M), "--queries", "32", "--request-rows", "8",
        "--clients", "2", "--probes", "1,2", "--lsh-bands", "4",
        "--lsh-band-bits", "8",
    ])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    lsh = rec["lsh"]
    assert lsh["bands"] == 4 and lsh["band_bits"] == 8
    assert [p["probes"] for p in lsh["curve"]] == [1, 2]
    for p in lsh["curve"]:
        assert 0.0 <= p["recall_at_m"] <= 1.0
        assert p["queries_per_s"] > 0
