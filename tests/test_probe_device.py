"""Device-fused candidate generation (ISSUE 16): the on-device probe →
gather → re-rank path, adaptive per-query probing, the candidate-budget
knob, per-label probe policies through the serving tier, and the
device-side telemetry/doctor rows.

The interpreter-run device dispatches (`probe_path="device"` on CPU) are
marked ``slow``: each one pads queries to the plan's tile and walks the
CSR under the Pallas interpreter, which costs tens of seconds — the
budgeted tier-1 run keeps the host-side contract tests, and `make
ann-smoke` (in `make verify` and CI) carries the bit-parity gate at toy
shapes.  Everything here that dispatches on-device shares ONE shape
family (8-byte codes, bands=4/band_bits=4, m=5) so interpreter programs
compile once per session."""

import numpy as np
import pytest

from randomprojection_tpu.ann import (
    BandedBuckets,
    BandPlan,
    LSHShardedSimHashIndex,
    LSHSimHashIndex,
    probe_masks,
)
from randomprojection_tpu.models import sketch as sk
from randomprojection_tpu.ops import probe_kernels
from randomprojection_tpu.utils import telemetry

# the shared device-shape family (see module docstring): 16 buckets per
# band keeps full coverage (and the adaptive level ladder) cheap under
# the interpreter
N, NB, M, FULL = 400, 8, 5, 1 << 4
BANDS = dict(bands=4, band_bits=4)


def _rand_codes(n, nbytes, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, nbytes), dtype=np.uint8
    )


def _corpus(seed=0):
    return _rand_codes(N, NB, seed=seed)


def _queries(seed=100):
    return _rand_codes(8, NB, seed=seed)


# -- host-side contracts (fast, tier-1) --------------------------------------


def test_probe_path_knob_validation_and_resolution():
    codes = _corpus(seed=1)
    with pytest.raises(ValueError, match="probe_path"):
        LSHSimHashIndex(codes, **BANDS, probe_path="bogus")
    idx = LSHSimHashIndex(codes, **BANDS)
    assert idx.probe_path == "auto"
    with pytest.raises(ValueError, match="probe_path"):
        idx.query_topk(_queries(), M, probe_path="bogus")
    # "auto" follows the kernels' interpret default: host under the
    # interpreter (this CPU run), device on chips
    assert idx._lsh_probe_device("host") is False
    assert idx._lsh_probe_device("device") is True
    assert idx._lsh_probe_device("auto") is (
        not probe_kernels.interpret_default()
    )
    # None = the constructor default
    assert idx._lsh_probe_device(None) == idx._lsh_probe_device("auto")


def test_adaptive_and_budget_knob_validation():
    codes = _corpus(seed=2)
    # bools must not pass integer validation (True == 1 would silently
    # serve a 1-probe/1-candidate tier)
    with pytest.raises(ValueError, match="probes"):
        LSHSimHashIndex(codes, **BANDS, probes=True)
    with pytest.raises(ValueError, match="candidate_budget"):
        LSHSimHashIndex(codes, **BANDS, candidate_budget=True)
    with pytest.raises(ValueError, match="candidate_budget"):
        LSHSimHashIndex(codes, **BANDS, candidate_budget=0)
    with pytest.raises(ValueError, match="candidate_budget"):
        LSHSimHashIndex(codes, **BANDS, candidate_budget=-3)
    idx = LSHSimHashIndex(codes, **BANDS, adaptive=True,
                          candidate_budget=64)
    assert idx.adaptive is True and idx.candidate_budget == 64
    # per-call probes: bool and negatives rejected (same validator)
    for bad in (True, False, -1):
        with pytest.raises(ValueError, match="probes"):
            idx.query_topk(_queries(), M, probes=bad)
    with pytest.raises(ValueError, match="candidate_budget"):
        idx.query_topk(_queries(), M, candidate_budget=True)


def test_candidates_all_empty_buckets():
    # a query whose probed buckets are ALL empty must yield an empty
    # (not crashing, not None) candidate set — the starved rung's input
    plan = BandPlan(16, bands=2, band_bits=8)
    b = BandedBuckets(plan)
    b.add(np.zeros((5, 2), np.uint8))  # everything lands in bucket 0
    qkeys = np.full((2, 3), 200, np.uint32)  # probe far-away buckets
    cand, gathered = b.candidates(qkeys, probe_masks(8, 2))
    assert cand.size == 0 and cand.dtype == np.int32
    assert gathered == 0


def test_probes_clamp_past_bucket_space():
    # probes beyond 2^band_bits clamp to full coverage instead of
    # probing phantom buckets — answers identical to the exact ceiling
    codes = _corpus(seed=3)
    q = _queries(seed=4)
    idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0)
    d1, i1 = idx.query_topk(q, M, probes=FULL)
    d2, i2 = idx.query_topk(q, M, probes=10**6)
    assert np.array_equal(d1, d2) and np.array_equal(i1, i2)


def test_candidate_fraction_uses_live_rows():
    """Majority-tombstoned regression (ISSUE 16 satellite): the
    candidate-fraction gauge and fallback density must divide by LIVE
    rows.  At full coverage over a 2/3-tombstoned corpus the union is
    exactly the live set — the gauge must read 1.0, not live/total."""
    codes = _corpus(seed=5)
    idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0)
    idx.delete(np.arange(0, 267))  # 267 of 400 dead
    assert idx.n_live == N - 267
    q = _queries(seed=6)
    d, i = idx.query_topk(q, M, probes=FULL)
    reg = telemetry.registry()
    assert reg.gauge("index.lsh.candidate_fraction")["last"] == (
        pytest.approx(1.0)
    )
    # and the answers are the masked brute force (the tier still serves)
    D = sk.pairwise_hamming(q, codes).astype(np.int64)
    D[:, :267] = NB * 8 + 1
    rd, ri = sk._host_topk_select(D, M)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)


def test_probe_policy_validation_and_serving():
    from randomprojection_tpu.serving import ShardedTopKServer

    codes = _corpus(seed=7)
    q = _queries(seed=8)
    idx = LSHSimHashIndex(codes, **BANDS, probes=2, fallback_density=1.0)
    plain = sk.SimHashIndex(codes)
    # policy requires an LSH-tier index and integer (non-bool) probes
    with pytest.raises(ValueError, match="probe_policy"):
        sk.TopKServer(plain, M, probe_policy={"a": 2}, start=False)
    with pytest.raises(ValueError, match="non-negative int"):
        sk.TopKServer(idx, M, probe_policy={"a": True}, start=False)
    with pytest.raises(ValueError, match="non-negative int"):
        sk.TopKServer(idx, M, probe_policy={"a": -1}, start=False)
    with pytest.raises(ValueError, match="probe_policy"):
        sk.TopKServer(idx, M, probe_policy=[("a", 2)], start=False)
    # every replica must carry the probes surface, not just replica 0
    with pytest.raises(ValueError, match="replica 1"):
        ShardedTopKServer([idx, plain], M, probe_policy={"a": 2},
                          start=False)
    # routing: "exact" pins probes=0 (brute-force parity), "bulk" rides
    # the label's own probe count, unlisted labels take the tier default
    rd, ri = sk.topk_bruteforce(q, codes, M)
    with sk.TopKServer(idx, M, max_delay_s=0.0,
                       probe_policy={"exact": 0, "bulk": FULL}) as srv:
        d0, i0 = srv.query(q, label="exact")
        d1, i1 = srv.query(q, label="bulk")
        d2, i2 = srv.query(q, label="other")
    assert np.array_equal(d0, rd) and np.array_equal(i0, ri)
    assert np.array_equal(d1, rd) and np.array_equal(i1, ri)  # full = exact
    assert d2.shape == (len(q), M)  # tier default (probes=2) serves


def test_plan_probe_shapes_and_clamp():
    # the planner clamps the probe count to the bucket space and refuses
    # (None) only when even the smallest tile cannot fit — at toy shapes
    # it must return a plan whose tile covers the queries
    pl = probe_kernels.plan_probe(8, N, 4, 4, 10**6, M)
    assert pl is not None
    assert pl.tq >= 8 and pl.cap >= 4 * M
    # a degenerate giant shape may legitimately return None, but must
    # not raise
    probe_kernels.plan_probe(1 << 20, 1 << 30, 64, 16, 1 << 16, 4096)


# -- interpreter-run device dispatches (slow; ann-smoke carries the
# tier-1 parity gate) ---------------------------------------------------------


@pytest.mark.slow
def test_device_path_parity_fixed_probes():
    codes = _corpus(seed=9)
    q = _queries(seed=10)
    idx = LSHSimHashIndex(codes[:300], **BANDS, fallback_density=1.0,
                          probe_path="device")
    idx.add(codes[300:])             # second chunk
    idx.delete(np.arange(280, 320))  # tombstones across the seam
    D = sk.pairwise_hamming(q, codes).astype(np.int64)
    D[:, 280:320] = NB * 8 + 1
    rd, ri = sk._host_topk_select(D, M)
    d, i = idx.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    # partial probes: device == host, bit for bit
    hd, hi = idx.query_topk(q, M, probes=3, probe_path="host")
    dd, di = idx.query_topk(q, M, probes=3)
    assert np.array_equal(dd, hd) and np.array_equal(di, hi)
    st = idx.lsh_stats()
    assert st["device_dispatches"] >= 2 and st["device_uploads"] >= 1


@pytest.mark.slow
def test_adaptive_full_ceiling_matches_brute_and_budget_monotone():
    codes = _corpus(seed=11)
    q = _queries(seed=12)
    rd, ri = sk.topk_bruteforce(q, codes, M)
    idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0,
                          probe_path="device", adaptive=True)
    # no budget, full ceiling: the early-exit bound is PROVEN, so the
    # adaptive path is exactly brute force
    d, i = idx.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd) and np.array_equal(i, ri)
    # recall is monotone in the candidate budget (each budget's scanned
    # set is a superset of every smaller budget's)
    prev = -1.0
    for budget in (M, 64, 10**9):
        d, i = idx.query_topk(q, M, probes=FULL, candidate_budget=budget)
        recall = sum(
            np.intersect1d(a, b).size for a, b in zip(i, ri)
        ) / ri.size
        assert recall >= prev
        prev = recall
    assert prev == 1.0  # an uncapped budget degenerates to the proof
    st = idx.lsh_stats()
    assert st["adaptive_tiles"] >= 4


@pytest.mark.slow
def test_device_events_doctor_rows(tmp_path):
    from randomprojection_tpu.utils import trace_report

    codes = _corpus(seed=13)
    q = _queries(seed=14)
    tel = str(tmp_path / "dev.jsonl")
    telemetry.configure(tel)
    try:
        idx = LSHSimHashIndex(codes, **BANDS, fallback_density=1.0,
                              probe_path="device")
        idx.query_topk(q, M, probes=2)
        idx.query_topk(q, M, probes=2, adaptive=True)
    finally:
        telemetry.shutdown()
    names = [e["event"] for e in telemetry.read_events(tel)]
    assert "index.lsh.device_upload" in names
    assert "index.lsh.device_dispatch" in names
    assert "index.lsh.adaptive" in names
    report = trace_report.build_report(tel)
    cg = report["candidate_generation"]
    assert cg["device_tiles"] >= 2
    assert cg["device_uploads"] >= 1 and cg["device_upload_bytes"] > 0
    assert cg["adaptive"]["tiles"] >= 1
    assert cg["adaptive"]["probes_used_mean"] > 0
    assert not report["unregistered_events"]
    text = trace_report.render_report(report)
    assert "device-fused probe tiles" in text
    assert "adaptive probing" in text


@pytest.mark.slow
def test_sharded_device_path_parity():
    codes = _corpus(seed=15)
    q = _queries(seed=16)
    sh = LSHShardedSimHashIndex(codes, n_shards=4, **BANDS,
                                fallback_density=1.0,
                                probe_path="device")
    dead = np.arange(90, 210)  # spans shard boundaries
    sh.delete(dead)
    D = sk.pairwise_hamming(q, codes).astype(np.int64)
    D[:, dead] = NB * 8 + 1
    rd, ri = sk._host_topk_select(D, M)
    d, i = sh.query_topk(q, M, probes=FULL)
    assert np.array_equal(d, rd)
    assert np.array_equal(i, ri.astype(np.int64))
