"""Benchmark harness policy tests (no device needed).

The round-1 review requirement these guard: no number whose implied
TFLOP/s exceeds 2× peak may be published unflagged, and a flagged mode
never beats a believable one for the headline (all-suspect runs report
the most accurate mode with its flag preserved).
"""

from randomprojection_tpu.benchmark import DISTORTION_BUDGET, select_headline


def mode(rows, dist, suspect):
    return {"rows_per_s": rows, "distortion": dist, "timing_suspect": suspect}


def test_fastest_in_budget_wins():
    results = {
        "bf16": mode(9e7, 2e-3, False),       # fast but out of budget
        "bf16_split2": mode(5e7, 4e-6, False),
        "f32_high": mode(3e7, 2e-5, False),
    }
    assert select_headline(results) == "bf16_split2"


def test_suspect_mode_never_headlines():
    results = {
        "bf16": mode(3e9, 2e-3, True),
        "bf16_split2": mode(2e9, 4e-6, True),  # in budget but impossible
        "f32_high": mode(3e7, 2e-5, False),
    }
    assert select_headline(results) == "f32_high"


def test_all_suspect_falls_back_to_most_accurate():
    results = {
        "bf16": mode(3e9, 2e-3, True),
        "bf16_split2": mode(2e9, 4e-6, True),
        "f32_high": mode(1e9, 2e-5, True),
    }
    # nothing believable: publish the most accurate (its flag stays set in
    # the JSON, so the reader sees the whole run is suspect)
    assert select_headline(results) == "bf16_split2"


def test_none_in_budget_picks_most_accurate_non_suspect():
    results = {
        "bf16": mode(9e7, 3.9e-3, False),
        "f32_high": mode(3e7, 2e-3, False),
    }
    assert select_headline(results) == "f32_high"


def test_budget_constant_matches_contract():
    assert DISTORTION_BUDGET == 1e-3
