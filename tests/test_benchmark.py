"""Benchmark harness policy tests (no device needed).

The round-1 review requirement these guard: no number whose implied
TFLOP/s exceeds 2× peak may be published unflagged, and a flagged mode
never beats a believable one for the headline (all-suspect runs report
the most accurate mode with its flag preserved).
"""

from randomprojection_tpu.benchmark import DISTORTION_BUDGET, select_headline


def mode(rows, dist, suspect):
    return {"rows_per_s": rows, "distortion": dist, "timing_suspect": suspect}


def test_fastest_in_budget_wins():
    results = {
        "bf16": mode(9e7, 2e-3, False),       # fast but out of budget
        "bf16_split2": mode(5e7, 4e-6, False),
        "f32_high": mode(3e7, 2e-5, False),
    }
    assert select_headline(results) == "bf16_split2"


def test_suspect_mode_never_headlines():
    results = {
        "bf16": mode(3e9, 2e-3, True),
        "bf16_split2": mode(2e9, 4e-6, True),  # in budget but impossible
        "f32_high": mode(3e7, 2e-5, False),
    }
    assert select_headline(results) == "f32_high"


def test_all_suspect_falls_back_to_most_accurate():
    results = {
        "bf16": mode(3e9, 2e-3, True),
        "bf16_split2": mode(2e9, 4e-6, True),
        "f32_high": mode(1e9, 2e-5, True),
    }
    # nothing believable: publish the most accurate (its flag stays set in
    # the JSON, so the reader sees the whole run is suspect)
    assert select_headline(results) == "bf16_split2"


def test_none_in_budget_picks_most_accurate_non_suspect():
    results = {
        "bf16": mode(9e7, 3.9e-3, False),
        "f32_high": mode(3e7, 2e-3, False),
    }
    assert select_headline(results) == "f32_high"


def test_budget_constant_matches_contract():
    assert DISTORTION_BUDGET == 1e-3


def test_pass_invariance_tripwire():
    """Near-identical elapsed across modes with different MXU pass counts
    flags the run as dispatch/cache-bound (BASELINE.md round-3 finding)."""
    from randomprojection_tpu.benchmark import detect_pass_invariance

    passes = {"a": 1, "b": 2, "c": 3}

    def res(*els):
        return {n: {"elapsed_s": e} for n, e in zip(("a", "b", "c"), els)}

    # uniform elapsed despite 1x/2x/3x work: flagged
    assert detect_pass_invariance(res(0.40, 0.41, 0.39), passes)
    # elapsed tracks pass count: healthy
    assert not detect_pass_invariance(res(0.20, 0.40, 0.60), passes)
    # same pass count everywhere: invariance is expected, not suspicious
    assert not detect_pass_invariance(res(0.40, 0.41), {"a": 2, "b": 2})


def test_host_best_of_escalates_on_suspect_spread():
    """VERDICT r4 #5: when the >2x spread flag trips, keep sampling (up to
    max_trials) and judge the spread over the best-3 window, so a couple
    of interference-polluted samples stop condemning the record."""
    from randomprojection_tpu.benchmark import _host_best_of

    # two polluted samples among good ones: escalates, then clears
    seq = iter([100.0, 30.0, 100.0, 100.0, 100.0])
    r = _host_best_of(lambda: next(seq))
    assert r["trials"] == 4 and not r["host_suspect"] and r["best"] == 100.0

    # stable from the start: no escalation
    seq = iter([100.0, 99.0, 98.0])
    r = _host_best_of(lambda: next(seq))
    assert r["trials"] == 3 and not r["host_suspect"]

    # genuinely unstable (even the best three disagree >2x): stays
    # flagged after max_trials
    seq = iter([100.0, 40.0, 10.0, 5.0, 3.0, 2.0, 1.0])
    r = _host_best_of(lambda: next(seq))
    assert r["trials"] == 7 and r["host_suspect"]


def test_gen_bench_tables_recovers_truncated_tail():
    """The BASELINE generator must rebuild mode/config records from a
    FRONT-TRUNCATED driver tail (the driver keeps only the end of the
    bench line) and re-derive the headline with select_headline."""
    import pathlib
    import sys as _sys

    repo = pathlib.Path(__file__).resolve().parents[1]
    _sys.path.insert(0, str(repo / "docs"))
    try:
        import gen_bench_tables as g
    finally:
        _sys.path.pop(0)

    tail = (
        'on": 0.001, "elapsed_s": 1.0, "timing_suspect": false}, '
        '"slow_mode": {"rows_per_s": 1000.0, "distortion": 1e-06, '
        '"executed_tflops": 1.0, "mxu_utilization": 0.1, '
        '"harness_hbm_cap_rows_per_s": 2000.0, "timing_suspect": false}, '
        '"fast_mode": {"rows_per_s": 5000.0, "distortion": 1e-06, '
        '"executed_tflops": 5.0, "mxu_utilization": 0.5, '
        '"harness_hbm_cap_rows_per_s": 9000.0, "timing_suspect": false}, '
        '"config1": {"workload": "w", "rows_per_s": 10.0, '
        '"trial_spread": 1.0, "host_suspect": false}}'
    )
    rec = g._recover_from_tail(tail)
    assert set(rec["all_modes"]) == {"slow_mode", "fast_mode"}
    assert rec["mode"] == "fast_mode" and rec["value"] == 5000.0
    assert rec["config1"]["rows_per_s"] == 10.0
    assert rec["_recovered_from_truncated_tail"]
    # and the renderer accepts the recovered record
    import json
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "BENCH_r99.json")
        with open(p, "w") as f:
            json.dump({"n": 1, "cmd": "", "rc": 0, "tail": tail,
                       "parsed": None}, f)
        block = g.render(p)
    assert "fast_mode" in block and "5.0k" in block
