"""Statistical property tests of generated projection matrices
(SURVEY.md §5 category 2; contract anchors test_random_projection.py:122-220,
:391-397) — run against BOTH the numpy and the jax kernels, plus
determinism/blocking invariance tests for the counter-based jax definition.
"""

import math

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from randomprojection_tpu.ops import kernels as jk
from randomprojection_tpu.ops import numpy_kernels as nk

K, D = 256, 2048  # big enough for ±2-decimal statistics, fast enough for CI


def _jax_gaussian():
    return np.asarray(jk.gaussian_matrix(jax.random.key(42), K, D))


def _np_gaussian():
    return nk.gaussian_random_matrix(K, D, np.random.default_rng(42))


def _jax_sparse(density):
    return np.asarray(jk.sparse_matrix(jax.random.key(42), K, D, density))


def _np_sparse(density):
    m = nk.sparse_random_matrix(K, D, density, np.random.default_rng(42))
    return m.toarray() if sp.issparse(m) else np.asarray(m)


@pytest.mark.parametrize("make", [_jax_gaussian, _np_gaussian], ids=["jax", "numpy"])
def test_gaussian_statistics(make):
    R = make()
    assert R.shape == (K, D)
    # zero mean, variance 1/k (test_random_projection.py:157-168)
    assert abs(R.mean()) < 3.0 / math.sqrt(K * D)
    np.testing.assert_allclose(R.var(), 1.0 / K, rtol=0.05)
    # unit expected column norm (test_random_projection.py:122-129)
    np.testing.assert_allclose(np.mean(np.sum(R**2, axis=0)), 1.0, rtol=0.05)


@pytest.mark.parametrize("make", [_jax_sparse, _np_sparse], ids=["jax", "numpy"])
@pytest.mark.parametrize("density", [1 / 3, 0.01, 1.0])
def test_sparse_statistics(make, density):
    R = make(density)
    assert R.shape == (K, D)
    v = 1.0 / math.sqrt(density * K)
    # value set {0, ±v} (test_random_projection.py:171-220); round at f32
    # precision since the jax kernel generates float32
    values = set(np.unique(np.round(R.astype(np.float64), 6)))
    expected = {0.0, v, -v} if density < 1 else {v, -v}
    assert {round(x, 6) for x in expected} == values
    # realized density within tolerance (test_random_projection.py:391-397)
    nnz_frac = np.mean(R != 0)
    np.testing.assert_allclose(nnz_frac, density, rtol=0.1)
    # symmetric signs => near-zero mean; per-entry variance = v^2 * density = 1/k
    np.testing.assert_allclose(R.var(), 1.0 / K, rtol=0.05)
    np.testing.assert_allclose(np.mean(np.sum(R**2, axis=0)), 1.0, rtol=0.05)


def test_numpy_sparse_is_csr():
    m = nk.sparse_random_matrix(8, 100, 0.1, np.random.default_rng(0))
    assert sp.issparse(m) and m.format == "csr"
    dense = nk.sparse_random_matrix(8, 100, 1.0, np.random.default_rng(0))
    assert isinstance(dense, np.ndarray)


def test_jax_determinism_and_key_sensitivity():
    a = _jax_gaussian()
    b = _jax_gaussian()
    np.testing.assert_array_equal(a, b)
    c = np.asarray(jk.gaussian_matrix(jax.random.key(43), K, D))
    assert not np.array_equal(a, c)


def test_blocked_definition_shard_identity():
    """A column shard built block-by-block == the slice of the full matrix.

    This is the property that makes tensor-parallel generation and lazy
    regeneration exact (SURVEY.md §8 'PRNG parity vs streaming layout').
    """
    key = jax.random.key(7)
    d = 2 * jk.COLUMN_BLOCK + 100  # ragged last block
    full = jk.gaussian_matrix(key, 16, d)
    for start, end in [(0, jk.COLUMN_BLOCK), (jk.COLUMN_BLOCK, d)]:
        shard = jk.materialize_columns(jk.gaussian_block, key, 16, d, start, end)
        np.testing.assert_array_equal(np.asarray(full[:, start:end]), np.asarray(shard))
    # misaligned shards are rejected, not silently wrong
    with pytest.raises(ValueError):
        jk.materialize_columns(jk.gaussian_block, key, 16, d, 3, 100)
    with pytest.raises(ValueError):
        jk.materialize_columns(jk.gaussian_block, key, 16, d, 0, 100)


def test_rademacher_statistics():
    R = np.asarray(jk.rademacher_matrix(jax.random.key(1), K, D))
    v = 1.0 / math.sqrt(K)
    assert set(np.round(np.unique(R), 12)) == set(np.round([v, -v], 12))
    assert abs(R.mean()) < 3.0 * v / math.sqrt(K * D)
    Rn = nk.rademacher_random_matrix(K, D, np.random.default_rng(1))
    assert set(np.round(np.unique(Rn), 12)) == set(np.round([v, -v], 12))


def test_bfloat16_dtype():
    R = jk.gaussian_matrix(jax.random.key(0), 64, 512, dtype=jnp.bfloat16)
    assert R.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(R, dtype=np.float32).var(), 1.0 / 64, rtol=0.1
    )
