"""Kernel-parity suite for the fused Pallas top-k serving kernel
(ISSUE 7): the fused kernel vs the retained scan path vs
``topk_bruteforce``, bit for bit, at toy shapes — tombstone masking,
ragged last blocks, tie-at-the-boundary ids, and an ``m`` the old
packed-int32-key ceiling rejected, now served on device.

Everything here runs on CPU through the Pallas interpreter (the same
kernel body, DMAs and merge networks as the TPU path)."""

import numpy as np
import pytest

from randomprojection_tpu.models import sketch as sk
from randomprojection_tpu.ops import topk_kernels as tk


def _rng(seed=0):
    return np.random.default_rng(seed)


def _filtered_reference(A, B, m, dead_ids=None):
    """Brute-force (dist, lower-id) top-m with tombstoned columns forced
    to lose — the same masked-selection contract as the device paths."""
    D = sk.pairwise_hamming(A, B).astype(np.int64)
    if dead_ids is not None and len(dead_ids):
        D[:, np.asarray(dead_ids)] = B.shape[1] * 8 + 1
    return sk._host_topk_select(D, m)


def _three_way(idx, A, m, ref, tile=2048):
    """Fused route, scan route, and the brute-force reference must agree
    bit for bit (dist AND id — the tie order is part of the contract)."""
    rd, ri = ref
    d_f, i_f = idx.query_topk(A, m, tile=tile)
    np.testing.assert_array_equal(d_f, rd)
    np.testing.assert_array_equal(i_f, ri)
    scan = sk.SimHashIndex.__new__(sk.SimHashIndex)
    scan.__dict__.update(idx.__dict__)
    scan.topk_impl = "scan"
    scan._topk_fns = {}
    scan._fused_degraded = set()
    scan._scan_fallback_noted = set()
    d_s, i_s = scan.query_topk(A, m, tile=tile)
    np.testing.assert_array_equal(d_s, rd)
    np.testing.assert_array_equal(i_s, ri)


# toy analogs of benchmark.TOPK_BENCH_SHAPES: (index rows, code bytes,
# queries, m, tile) — small enough for the interpreter, shaped to hit
# multiple kernel blocks, ragged tails and (case 2) ragged multi-tile
# dispatch.  Each extra distinct shape compiles fresh interpret programs
# for BOTH impls, so the list stays tight.
TOY_SHAPES = [
    (2048, 32, 96, 16, 96),   # the smoke serving shape, scaled down
    (1000, 8, 64, 9, 40),     # ragged rows AND a ragged last tile
    (257, 4, 33, 33, 33),     # m > block candidates, odd everything
]


@pytest.mark.slow
@pytest.mark.parametrize("rows,nb,nq,m,tile", TOY_SHAPES)
def test_fused_vs_scan_vs_bruteforce(rows, nb, nq, m, tile):
    rng = _rng(rows + nb)
    B = rng.integers(0, 256, size=(rows, nb), dtype=np.uint8)
    A = rng.integers(0, 256, size=(nq, nb), dtype=np.uint8)
    idx = sk.SimHashIndex(B)
    _three_way(idx, A, m, _filtered_reference(A, B, m), tile=tile)


@pytest.mark.slow
def test_parity_with_tombstones_and_chunks():
    """Multi-chunk index with tombstones in some chunks only: the
    masked fused variant runs beside the unmasked one and both match
    the filtered brute force."""
    rng = _rng(5)
    nb = 8
    parts = [rng.integers(0, 256, size=(n, nb), dtype=np.uint8)
             for n in (500, 37, 300)]
    B = np.concatenate(parts)
    A = rng.integers(0, 256, size=(24, nb), dtype=np.uint8)
    idx = sk.SimHashIndex(parts[0])
    for p in parts[1:]:
        idx.add(p)
    dead = [0, 17, 499, 520, 700]  # chunks 0 and 1 and 2 touched
    idx.delete(dead)
    m = 11
    _three_way(idx, A, m, _filtered_reference(A, B, m, dead))


@pytest.mark.slow
def test_parity_tie_heavy_boundary_ids():
    """A corpus of few distinct codes: almost every selection decision
    is a tie, broken by the LOWER global id — including ties that
    straddle kernel block boundaries and the carry/block boundary."""
    rng = _rng(9)
    nb = 16
    basis = rng.integers(0, 256, size=(3, nb), dtype=np.uint8)
    B = basis[rng.integers(0, 3, 700)]
    A = basis[rng.integers(0, 3, 24)]
    idx = sk.SimHashIndex(B)
    m = 25
    _three_way(idx, A, m, _filtered_reference(A, B, m))


@pytest.mark.slow
def test_parity_ragged_last_block_and_nbits():
    """Rows that leave a ragged last block at every block size the plan
    can pick, plus a ragged bit width (pad bits cancel)."""
    rng = _rng(3)
    nb = 4
    B = rng.integers(0, 256, size=(1025, nb), dtype=np.uint8)
    # zero the pad bits of a ragged 27-bit code (27 bits in 4 bytes)
    B[:, -1] &= 0x07
    A = rng.integers(0, 256, size=(17, nb), dtype=np.uint8)
    A[:, -1] &= 0x07
    idx = sk.SimHashIndex(B, n_bits=27)
    m = 7
    _three_way(idx, A, m, _filtered_reference(A, B, m))


@pytest.mark.slow
def test_m_above_old_int32_key_ceiling_served_on_device():
    """THE ceiling-removal acceptance (ISSUE 7): a request the old
    packed-key bound rejected — ``(n_bits+2)·(m+blk) ≥ 2^31`` even at
    the blk=8 clamp floor, the shape r5's machinery routed to the dense
    fallback — is now served by the fused kernel, on the device path,
    bit-identical to brute force.

    2^24-bit codes (2 MiB/row) make the old sentinel so wide that even
    m=120 overflowed the packed key.  The fused kernel's separate
    (dist, idx) carries never pack over the carry, so the plan exists
    and the kernel streams each huge row through byte-tiled,
    double-buffered DMA.  (~270 MB host side, a few seconds in the
    interpreter — the cheapest shape that genuinely crosses the old
    bound, which requires n_bits·m ≳ 2^31.)"""
    nb = 1 << 21
    rows, m, nq = 128, 120, 1
    sentinel = nb * 8 + 1
    # restate the old bound: clamp to the blk=8 floor, then the fit test
    blk = 32768
    while blk > 8 and (sentinel + 1) * (m + blk) >= 2**31:
        blk //= 2
    assert (sentinel + 1) * (m + blk) >= 2**31, (
        "shape no longer crosses the old int32-key ceiling — "
        "the test would not prove the removal"
    )
    # the old routing would have dense-fallback'd; the new plan exists
    assert tk.plan_fused(nq, rows, nb, m) is not None
    rng = _rng(13)
    B = rng.integers(0, 256, size=(rows, nb), dtype=np.uint8)
    A = rng.integers(0, 256, size=(nq, nb), dtype=np.uint8)
    from randomprojection_tpu.utils import telemetry

    idx = sk.SimHashIndex(B)
    assert idx._chunk_impl(nq, rows, m) == "fused"
    before = telemetry.registry().snapshot()["counters"].get(
        "simhash.topk_dense_fallbacks", 0
    )
    d, i = idx.query_topk(A, m)
    after = telemetry.registry().snapshot()["counters"].get(
        "simhash.topk_dense_fallbacks", 0
    )
    assert after == before, "the dense fallback must not fire"
    rd, ri = _filtered_reference(A, B, m)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)


def test_vmem_oom_degrades_to_scan_and_memoizes(monkeypatch):
    """The r6-convention degraded retry: a scoped-VMEM OOM from the
    fused kernel retries through the scan path (same results), records
    the retry, and memoizes the shape so later dispatches skip the
    failing kernel."""
    from randomprojection_tpu.ops import topk_kernels
    from randomprojection_tpu.utils import telemetry

    rng = _rng(21)
    B = rng.integers(0, 256, size=(300, 8), dtype=np.uint8)
    A = rng.integers(0, 256, size=(20, 8), dtype=np.uint8)
    idx = sk.SimHashIndex(B)
    rd, ri = _filtered_reference(A, B, 5)

    calls = {"n": 0}

    def boom(*a, **kw):
        calls["n"] += 1
        raise RuntimeError(
            "Mosaic failed: scoped vmem allocation exceeds the limit"
        )

    monkeypatch.setattr(topk_kernels, "fused_topk", boom)
    before = telemetry.registry().snapshot()["counters"].get(
        "backend.vmem_oom_retries", 0
    )
    d, i = idx.query_topk(A, 5)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)
    assert calls["n"] == 1
    after = telemetry.registry().snapshot()["counters"].get(
        "backend.vmem_oom_retries", 0
    )
    assert after == before + 1
    assert idx._fused_degraded  # memoized
    # second call: fused not attempted again for the memoized shape
    d2, i2 = idx.query_topk(A, 5)
    np.testing.assert_array_equal(d2, rd)
    assert calls["n"] == 1


def test_vmem_oom_on_scan_unfit_shape_degrades_to_minimal_fused(monkeypatch):
    """The ladder's other leg: when the scan path cannot represent the
    request (the over-the-old-ceiling shapes), a VMEM OOM must degrade
    WITHIN the kernel to the minimal tiling — still serving, still
    bit-identical — never hit the scan builder's overflow guard."""
    from randomprojection_tpu.ops import topk_kernels

    rng = _rng(23)
    B = rng.integers(0, 256, size=(300, 8), dtype=np.uint8)
    A = rng.integers(0, 256, size=(20, 8), dtype=np.uint8)
    idx = sk.SimHashIndex(B)
    rd, ri = _filtered_reference(A, B, 5)
    monkeypatch.setattr(
        sk.SimHashIndex, "_scan_fits", lambda self, rows, m: False
    )

    real = topk_kernels.fused_topk
    seen = {"plans": [], "oomed": False}

    def oom_once_then_real(q, codes, n_real, m, *, dead=None, plan=None,
                          interpret=None):
        seen["plans"].append(plan)
        if not seen["oomed"]:
            seen["oomed"] = True
            raise RuntimeError("scoped vmem allocation exceeds the limit")
        return real(q, codes, n_real, m, dead=dead, plan=plan,
                    interpret=interpret)

    monkeypatch.setattr(topk_kernels, "fused_topk", oom_once_then_real)
    d, i = idx.query_topk(A, 5)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)
    assert idx._fused_degraded
    # the retry carried the MINIMAL plan (smaller tiles than the auto one)
    auto, mini = seen["plans"][0], seen["plans"][1]
    assert (mini.tq, mini.blk) <= (auto.tq, auto.blk)
    assert mini == topk_kernels.plan_fused(20, 300, 8, 5, minimal=True)
    # subsequent dispatches stay on the minimal fused route
    d2, _ = idx.query_topk(A, 5)
    np.testing.assert_array_equal(d2, rd)
    assert seen["plans"][-1] == mini


def test_non_vmem_errors_are_not_swallowed(monkeypatch):
    """Only classified VMEM OOMs take the degraded retry: any other
    kernel failure must surface to the caller."""
    from randomprojection_tpu.ops import topk_kernels

    rng = _rng(22)
    idx = sk.SimHashIndex(
        rng.integers(0, 256, size=(64, 8), dtype=np.uint8)
    )
    monkeypatch.setattr(
        topk_kernels, "fused_topk",
        lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    with pytest.raises(RuntimeError, match="boom"):
        idx.query_topk(rng.integers(0, 256, size=(4, 8), dtype=np.uint8), 3)


def test_topk_impl_validation_and_env_override(monkeypatch):
    rng = _rng(30)
    codes = rng.integers(0, 256, size=(64, 8), dtype=np.uint8)
    with pytest.raises(ValueError, match="topk_impl"):
        sk.SimHashIndex(codes, topk_impl="bogus")
    idx = sk.SimHashIndex(codes)
    assert idx._chunk_impl(4, 64, 3) == "fused"
    monkeypatch.setenv("RP_TOPK_IMPL", "scan")
    assert idx._chunk_impl(4, 64, 3) == "scan"
    monkeypatch.delenv("RP_TOPK_IMPL")
    assert idx._chunk_impl(4, 64, 3) == "fused"
@pytest.mark.slow
def test_kernel_dispatch_event_on_spine(tmp_path):
    """The fused path records ``topk.kernel.dispatch`` events that the
    doctor consumes into its serving section."""
    from randomprojection_tpu.utils import telemetry, trace_report

    rng = _rng(31)
    idx = sk.SimHashIndex(
        rng.integers(0, 256, size=(256, 8), dtype=np.uint8)
    )
    A = rng.integers(0, 256, size=(16, 8), dtype=np.uint8)
    path = str(tmp_path / "events.jsonl")
    telemetry.configure(path)
    try:
        idx.query_topk(A, 4)
    finally:
        telemetry.shutdown()
    report = trace_report.build_report(path)
    assert report["serving"]["topk_kernel_dispatches"] >= 1
    assert report["serving"]["topk_kernel_queries"] >= 16
    assert report["unregistered_events"] == {}
    rendered = trace_report.render_report(report)
    assert "fused top-k kernel" in rendered


def test_plan_fused_bounds():
    """Plan feasibility: normal shapes plan; host-scale m and
    pathologically wide codes do not (the dense fallback's territory)."""
    assert tk.plan_fused(2048, 1 << 20, 32, 16) is not None
    # m whose carry cannot fit VMEM even at one query row
    assert tk.plan_fused(8, 1 << 22, 32, 1 << 22) is None
    # codes beyond f32-exact Hamming (> 2^24 bits)
    assert tk.plan_fused(8, 64, (1 << 21) + 8, 4) is None


def test_scan_fallback_event_when_unplannable(monkeypatch):
    """When auto routing wants the kernel but no tiling fits, the scan
    path serves and the degradation lands on the spine once."""
    from randomprojection_tpu.ops import topk_kernels
    from randomprojection_tpu.utils import telemetry

    rng = _rng(33)
    B = rng.integers(0, 256, size=(128, 8), dtype=np.uint8)
    A = rng.integers(0, 256, size=(8, 8), dtype=np.uint8)
    idx = sk.SimHashIndex(B)
    monkeypatch.setattr(topk_kernels, "plan_fused", lambda *a, **kw: None)
    before = telemetry.registry().snapshot()["counters"].get(
        "simhash.topk_scan_fallbacks", 0
    )
    d, i = idx.query_topk(A, 5)
    rd, ri = _filtered_reference(A, B, 5)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)
    after = telemetry.registry().snapshot()["counters"].get(
        "simhash.topk_scan_fallbacks", 0
    )
    assert after == before + 1
    idx.query_topk(A, 5)  # same shape: noted once, no double count
    again = telemetry.registry().snapshot()["counters"].get(
        "simhash.topk_scan_fallbacks", 0
    )
    assert again == after
