"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §5).

Covers: replication of R, row-sharded DP einsum, TP feature-sharding with
psum, and PRNG sharding-invariance (same values regardless of layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu.ops import kernels
from randomprojection_tpu.parallel import (
    default_mesh,
    make_mesh,
    make_sharded_projector,
    materialize_sharded,
)
from randomprojection_tpu.parallel.sharded import feature_sharded, row_sharded


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    if len(devs) < 8:
        # the default suite pins an 8-device virtual CPU mesh (conftest);
        # under RP_TEST_TPU=1 there is one real chip — skip, don't error
        pytest.skip("needs the 8-device virtual mesh (default CPU suite)")
    return devs


def test_make_mesh_shapes(devices):
    mesh = make_mesh({"data": 4, "feature": 2})
    assert mesh.shape == {"data": 4, "feature": 2}
    with pytest.raises(ValueError, match="require"):
        make_mesh({"data": 3})


@pytest.mark.mesh_env
def test_dp_projection_matches_single_device(devices):
    mesh = default_mesh()  # 8-way data parallel
    k, d, n = 16, 1024, 64
    key = jax.random.key(0)
    R = kernels.gaussian_matrix(key, k, d)
    x = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)

    project = make_sharded_projector(mesh)
    y_sharded = project(jax.device_put(x, row_sharded(mesh)), R)
    y_ref = x @ np.asarray(R).T
    np.testing.assert_allclose(np.asarray(y_sharded), y_ref, rtol=1e-5, atol=1e-5)


@pytest.mark.mesh_env
def test_tp_psum_projection_matches_single_device(devices):
    mesh = make_mesh({"data": 4, "feature": 2})
    k, d, n = 16, 2048, 32  # d/2 = 1024 = 2 COLUMN_BLOCKs per shard
    key = jax.random.key(1)
    R = kernels.gaussian_matrix(key, k, d)
    x = np.random.default_rng(1).normal(size=(n, d)).astype(np.float32)

    project = make_sharded_projector(mesh, feature_axis="feature")
    y = project(x, R)
    y_ref = x @ np.asarray(R).T
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kind", ["gaussian", "sparse", "rademacher"])
@pytest.mark.mesh_env
def test_sharded_materialization_bit_identical(devices, kind):
    """Each chip generating only its column shard must reproduce the exact
    same matrix as single-device materialization (counter-based PRNG)."""
    mesh = make_mesh({"data": 2, "feature": 4})
    k, d = 8, 2048
    key = jax.random.key(7)
    if kind == "sparse":
        fn = lambda key, k_, d_, dt: kernels.sparse_matrix(key, k_, d_, 0.1, dt)
    else:
        fn = getattr(kernels, f"{kind}_matrix")

    R_full = np.asarray(fn(key, k, d, jnp.float32))
    R_sharded = materialize_sharded(fn, key, k, d, mesh, feature_axis="feature")
    assert R_sharded.sharding.spec == feature_sharded(mesh).spec
    np.testing.assert_array_equal(np.asarray(R_sharded), R_full)


def test_replicated_materialization(devices):
    mesh = default_mesh()
    R = materialize_sharded(kernels.gaussian_matrix, jax.random.key(0), 8, 512, mesh)
    assert R.sharding.is_fully_replicated


@pytest.mark.mesh_env
def test_estimator_with_tp_mesh_backend(devices):
    """Backend-level DPxTP: R column-sharded, X feature-sharded, GSPMD
    inserts the psum; output must match the single-device run."""
    from randomprojection_tpu import GaussianRandomProjection, SparseRandomProjection

    mesh = make_mesh({"data": 4, "feature": 2})
    # 1000 rows: ragged vs the row bucket, exercising the sharded pad-slice
    X = np.random.default_rng(5).normal(size=(1000, 2048)).astype(np.float32)
    for Est in (GaussianRandomProjection, SparseRandomProjection):
        est_tp = Est(
            n_components=16, random_state=1, backend="jax",
            backend_options={"mesh": mesh, "feature_axis": "feature"},
        ).fit(X)
        state = est_tp.components_
        assert state.sharding.spec == feature_sharded(mesh).spec
        Y_tp = np.asarray(est_tp.transform(X))
        est_1 = Est(n_components=16, random_state=1, backend="jax").fit(X)
        np.testing.assert_allclose(
            Y_tp, np.asarray(est_1.transform(X)), rtol=1e-4, atol=1e-4
        )


@pytest.mark.mesh_env
def test_split2_composes_with_tp_mesh(devices):
    """precision='split2' under {'data':4,'feature':2}: per-shard hi/lo
    partial einsums + one psum must match the single-device split2 result
    exactly (same arithmetic, distributed over d) and the f64 reference at
    split2's documented f32-grade tolerance."""
    from randomprojection_tpu import SparseRandomProjection

    mesh = make_mesh({"data": 4, "feature": 2})
    X = np.random.default_rng(9).normal(size=(512, 2048)).astype(np.float32)
    est_tp = SparseRandomProjection(
        n_components=32, random_state=2, density=1 / 3, backend="jax",
        backend_options={
            "mesh": mesh, "feature_axis": "feature", "precision": "split2",
        },
    ).fit(X)
    Y_tp = np.asarray(est_tp.transform(X))

    est_1 = SparseRandomProjection(
        n_components=32, random_state=2, density=1 / 3, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(X)
    Y_1 = np.asarray(est_1.transform(X))

    # same mask (counter PRNG), same two-pass arithmetic → tight agreement
    np.testing.assert_allclose(Y_tp, Y_1, rtol=1e-6, atol=1e-6)
    # and f32-grade accuracy vs the exact f64 projection
    R = est_1.components_as_numpy().astype(np.float64)
    np.testing.assert_allclose(
        Y_tp, X.astype(np.float64) @ R.T, rtol=1e-3, atol=1e-3
    )


def test_split2_composes_with_dp_only_mesh(devices):
    """split2 under a pure-DP mesh (no feature axis): replicated mask,
    row-sharded X, no collectives."""
    from randomprojection_tpu import SparseRandomProjection

    mesh = default_mesh()
    X = np.random.default_rng(11).normal(size=(256, 1024)).astype(np.float32)
    est = SparseRandomProjection(
        n_components=16, random_state=4, density=0.1, backend="jax",
        backend_options={"mesh": mesh, "precision": "split2"},
    ).fit(X)
    est_1 = SparseRandomProjection(
        n_components=16, random_state=4, density=0.1, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(X)
    np.testing.assert_allclose(
        np.asarray(est.transform(X)), np.asarray(est_1.transform(X)),
        rtol=1e-6, atol=1e-6,
    )


def test_estimator_with_mesh_backend(devices):
    """End-to-end: estimator on a jax backend bound to an 8-device mesh."""
    from randomprojection_tpu import GaussianRandomProjection

    mesh = default_mesh()
    X = np.random.default_rng(3).normal(size=(64, 512))
    est = GaussianRandomProjection(
        n_components=16,
        random_state=0,
        backend="jax",
        backend_options={"mesh": mesh},
    ).fit(X)
    Y = est.transform(X)
    est_single = GaussianRandomProjection(
        n_components=16, random_state=0, backend="jax"
    ).fit(X)
    np.testing.assert_allclose(
        np.asarray(Y), np.asarray(est_single.transform(X)), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------------------------------
# CountSketch on a mesh (config 5 "on v5e-8") + sharded Hamming (config 4)
# ---------------------------------------------------------------------------


@pytest.mark.mesh_env
def test_countsketch_mesh_matches_single_device(devices):
    """DP row-sharded CountSketch (MXU one-hot split2 path) must match the
    single-device sketch; rows not divisible by the mesh are padded and
    sliced back."""
    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    X = np.random.default_rng(0).normal(size=(101, 300)).astype(np.float32)
    Ym = CountSketch(32, random_state=0, backend="jax", mesh=mesh).fit(X).transform(X)
    Y1 = CountSketch(32, random_state=0, backend="jax").fit(X).transform(X)
    assert Ym.shape == (101, 32)
    np.testing.assert_allclose(Ym, Y1, rtol=1e-6, atol=1e-6)


@pytest.mark.mesh_env
def test_countsketch_mesh_scatter_path(devices, monkeypatch):
    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.parallel import make_mesh

    monkeypatch.setattr(CountSketch, "_MXU_MASK_BYTES_CAP", 1024)
    mesh = make_mesh({"data": 8})
    X = np.random.default_rng(1).normal(size=(64, 300)).astype(np.float32)
    Ym = CountSketch(16, random_state=0, backend="jax", mesh=mesh).fit(X).transform(X)
    Yn = CountSketch(16, random_state=0, backend="numpy").fit(X).transform(X)
    np.testing.assert_allclose(Ym, Yn, rtol=2e-5, atol=2e-5)


def test_countsketch_async_returns_device_handle(devices):
    """The streaming pipeline only overlaps if _transform_async hands back a
    lazy device array (VERDICT r2 weak #3: it used to round-trip through the
    host per batch)."""
    import jax

    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.streaming import ArraySource, stream_to_array

    X = np.random.default_rng(2).normal(size=(96, 128)).astype(np.float32)
    est = CountSketch(16, random_state=0, backend="jax").fit(X)
    y = est._transform_async(X[:32])
    assert isinstance(y, jax.Array)  # not yet materialized
    got = stream_to_array(est, ArraySource(X, batch_rows=32))
    np.testing.assert_allclose(got, est.transform(X), rtol=1e-6, atol=1e-6)
    # host paths stay synchronous ndarray
    est_np = CountSketch(16, random_state=0, backend="numpy").fit(X)
    assert isinstance(est_np._transform_async(X[:32]), np.ndarray)


@pytest.mark.mesh_env
def test_pairwise_hamming_sharded_matches_bruteforce(devices):
    from randomprojection_tpu import pairwise_hamming, pairwise_hamming_sharded
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(3)
    A = rng.integers(0, 256, size=(37, 16), dtype=np.uint8)
    B = rng.integers(0, 256, size=(101, 16), dtype=np.uint8)  # 101 % 8 != 0
    np.testing.assert_array_equal(
        pairwise_hamming_sharded(A, B, mesh=mesh, tile=16),
        pairwise_hamming(A, B),
    )
    # B=None means self-distance, like the host/device variants
    np.testing.assert_array_equal(
        pairwise_hamming_sharded(A, mesh=mesh), pairwise_hamming(A)
    )


@pytest.mark.mesh_env
def test_jl_mesh_ragged_batch(devices):
    """Ragged (non-mesh-divisible) batches under a mesh must still produce
    exact rows (regression: the jit row-slice raised ShardingTypeError for
    n % devices != 0 — found while mesh-enabling CountSketch)."""
    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    X = np.random.default_rng(0).normal(size=(101, 64)).astype(np.float32)
    common = dict(random_state=0, backend="jax")
    Ym = np.asarray(
        GaussianRandomProjection(
            16, **common, backend_options={"mesh": mesh}
        ).fit(X).transform(X)
    )
    Y1 = np.asarray(GaussianRandomProjection(16, **common).fit(X).transform(X))
    assert Ym.shape == (101, 16)
    np.testing.assert_allclose(Ym, Y1, rtol=1e-5, atol=1e-6)


def test_row_bucket_ladder():
    """Bucket ladder contract (VERDICT r2 weak #7): pad waste <= 25% for
    n >= 64 (next-pow-2 wasted up to 100%), results are multiples of 8,
    monotone, and mesh-divisible."""
    from randomprojection_tpu.parallel.sharded import row_bucket

    prev = 0
    for n in [1, 5, 8, 9, 33, 64, 65, 100, 1000, 65536, 65537, 100000,
              131072, 131073]:
        b = row_bucket(n)
        assert b >= max(8, n)
        assert b % 8 == 0
        assert b >= prev or n < prev  # monotone in n
        if n >= 64:
            assert b <= n * 1.25 + 8, (n, b)
        prev = b
    # same n always lands in the same bucket (program cache key stability)
    assert row_bucket(65537) == row_bucket(65537)
    assert row_bucket(65537) == 81920  # 1.25 * 65536, not 131072

    class FakeMesh:
        shape = {"data": 6}

    b = row_bucket(100, FakeMesh(), "data")
    assert b % 6 == 0 and b >= 100
    # per-shard row counts keep the f32 sublane tiling on any mesh size
    assert (b // 6) % 8 == 0


@pytest.mark.mesh_env
def test_countsketch_mesh_csr_matches_single_device(devices):
    """DP CSR sketch: tokens partitioned at shard row boundaries, each
    shard scatters its own range — must match the no-mesh device path and
    the host scatter, including ragged n and uneven tokens per shard."""
    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.parallel import make_mesh

    rng = np.random.default_rng(9)
    X = rng.normal(size=(101, 500)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    X[:40] = 0.0  # shard imbalance: early shards carry almost no tokens
    Xs = sp.csr_array(X)
    mesh = make_mesh({"data": 8})
    Ym = CountSketch(
        32, random_state=0, backend="jax", mesh=mesh
    ).fit(Xs).transform(Xs)
    Y1 = CountSketch(32, random_state=0, backend="jax").fit(Xs).transform(Xs)
    assert Ym.shape == (101, 32) and Ym.dtype == np.float32
    np.testing.assert_allclose(Ym, Y1, rtol=1e-6, atol=1e-6)
    Yn = CountSketch(32, random_state=0, backend="numpy").fit(Xs).transform(Xs)
    np.testing.assert_allclose(Ym, Yn, rtol=2e-5, atol=2e-5)


@pytest.mark.mesh_env
def test_simhash_index_resident_shards(devices, monkeypatch):
    """SimHashIndex holds B row-sharded ACROSS calls (VERDICT r3 weak #5:
    pairwise_hamming_sharded re-ships B every call): repeated queries must
    perform zero new B transfers and match host brute force."""
    from randomprojection_tpu import SimHashIndex, pairwise_hamming
    from randomprojection_tpu.parallel import make_mesh

    rng = np.random.default_rng(3)
    B = rng.integers(0, 256, size=(101, 8), dtype=np.uint8)  # ragged vs p=8
    A = rng.integers(0, 256, size=(17, 8), dtype=np.uint8)
    mesh = make_mesh({"data": 8})
    idx = SimHashIndex(B, mesh=mesh)

    calls = []
    real_device_put = jax.device_put
    monkeypatch.setattr(
        jax, "device_put",
        lambda *a, **kw: calls.append(1) or real_device_put(*a, **kw),
    )
    b_resident = idx._chunks[0].b
    D1 = idx.query(A)
    D2 = idx.query(A[:5], tile=3)  # tiled path, second call
    assert not calls, "query must not re-upload the index"
    assert idx._chunks[0].b is b_resident
    np.testing.assert_array_equal(D1, pairwise_hamming(A, B))
    np.testing.assert_array_equal(D2, pairwise_hamming(A[:5], B))

    # single-device flavor + cosine with ragged bit count
    idx1 = SimHashIndex(B, n_bits=60)
    np.testing.assert_array_equal(idx1.query(A), pairwise_hamming(A, B))
    np.testing.assert_allclose(
        idx1.query_cosine(A), np.cos(np.pi * pairwise_hamming(A, B) / 60)
    )

    # add(): appended codes are scored on the next query, and the append
    # ships ONLY the new rows (VERDICT r4 weak #4: the old rebuild-on-add
    # re-uploaded the whole index per append)
    put_bytes = []
    monkeypatch.setattr(
        jax, "device_put",
        lambda x, *a, **kw: put_bytes.append(getattr(x, "nbytes", 0))
        or real_device_put(x, *a, **kw),
    )
    idx.add(B[:7])
    assert idx._chunks[0].b is b_resident, "add must not touch old chunks"
    # 7 rows pad to 8 for the p=8 mesh: 8×8 bytes, nothing near the
    # 101-row original
    assert sum(put_bytes) <= 8 * B.shape[1]
    D3 = idx.query(A)
    np.testing.assert_array_equal(
        D3, pairwise_hamming(A, np.concatenate([B, B[:7]]))
    )

    with pytest.raises(ValueError, match="codes"):
        SimHashIndex(np.zeros((3,), dtype=np.uint8))
    with pytest.raises(ValueError, match="n_bits"):
        SimHashIndex(B, n_bits=100)


def _brute_topk(A, B, m):
    """Reference top-m under the documented total order (distance, id) —
    the library's own host reference, so the encoding cannot drift."""
    from randomprojection_tpu.models.sketch import topk_bruteforce

    return topk_bruteforce(A, B, m)


@pytest.mark.parametrize(
    "use_mesh",
    [False, pytest.param(True, marks=pytest.mark.mesh_env)],
)
def test_simhash_index_query_topk_matches_bruteforce(request, use_mesh):
    """query_topk must equal brute force under the documented tie policy
    (lower global id wins) on ragged shapes, across mesh/no-mesh, small-m
    and m > n_codes, and across chunk boundaries (post-add).  The no-mesh
    variant needs no fixture, so it ALSO runs on the real chip under
    RP_TEST_TPU=1 — on-chip coverage for the serving primitive."""
    from randomprojection_tpu import SimHashIndex
    from randomprojection_tpu.parallel import make_mesh

    rng = np.random.default_rng(11)
    # few distinct codes → MANY exact Hamming ties: the tie policy is
    # load-bearing in this test, not a corner case
    pool = rng.integers(0, 256, size=(13, 6), dtype=np.uint8)
    B = pool[rng.integers(0, 13, size=333)]
    A = pool[rng.integers(0, 13, size=29)]
    if use_mesh:
        request.getfixturevalue("devices")
    mesh = make_mesh({"data": 8}) if use_mesh else None
    idx = SimHashIndex(B, mesh=mesh)

    for m in (1, 5, 64):
        d, i = idx.query_topk(A, m, tile=16)
        rd, ri = _brute_topk(A, B, min(m, B.shape[0]))
        np.testing.assert_array_equal(d, rd)
        np.testing.assert_array_equal(i, ri)

    # m larger than the index: every code comes back, ordered
    d, i = idx.query_topk(A[:3], 1000)
    assert d.shape == (3, 333)
    rd, ri = _brute_topk(A[:3], B, 333)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)

    # chunk boundary: ids stay global and insertion-ordered after add
    B2 = pool[rng.integers(0, 13, size=55)]
    idx.add(B2)
    d, i = idx.query_topk(A, 17)
    rd, ri = _brute_topk(A, np.concatenate([B, B2]), 17)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)

    with pytest.raises(ValueError, match="m must be"):
        idx.query_topk(A, 0)


def test_simhash_index_topk_crosses_scan_blocks():
    """A chunk larger than _TOPK_ROW_BLOCK exercises the scanned running
    top-k (carry merge), not just one block.  No mesh — also runs on the
    real chip under RP_TEST_TPU=1."""
    from randomprojection_tpu import SimHashIndex
    from randomprojection_tpu.models import sketch as sketch_mod

    rng = np.random.default_rng(12)
    B = rng.integers(0, 256, size=(1000, 4), dtype=np.uint8)
    A = rng.integers(0, 256, size=(7, 4), dtype=np.uint8)
    idx = SimHashIndex(B)
    old = sketch_mod.SimHashIndex._TOPK_ROW_BLOCK
    sketch_mod.SimHashIndex._TOPK_ROW_BLOCK = 128  # 8 scan steps
    try:
        idx._topk_fns.clear()
        d, i = idx.query_topk(A, 9)
    finally:
        sketch_mod.SimHashIndex._TOPK_ROW_BLOCK = old
    rd, ri = _brute_topk(A, B, 9)
    np.testing.assert_array_equal(d, rd)
    np.testing.assert_array_equal(i, ri)


@pytest.mark.mesh_env
def test_countsketch_mesh_input_arrives_row_sharded(devices):
    """The dense mesh path must device_put the batch ROW-SHARDED before
    the jitted shard_map (VERDICT r3 weak #3: jnp.asarray placed it whole
    on device 0, an extra all-to-device-0 hop per batch on a real pod)."""
    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    X = np.random.default_rng(4).normal(size=(64, 128)).astype(np.float32)
    cs = CountSketch(16, random_state=0, backend="jax", mesh=mesh).fit(X)
    cs.transform(X)  # builds _jax_fn

    seen = []
    orig = cs._jax_fn
    cs._jax_fn = lambda x: (seen.append(x.sharding), orig(x))[1]
    Y = cs.transform(X)
    assert len(seen) == 1
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert seen[0] == NamedSharding(mesh, P("data", None)), seen[0]
    Y1 = CountSketch(16, random_state=0, backend="jax").fit(X).transform(X)
    np.testing.assert_allclose(Y, Y1, rtol=1e-5, atol=1e-6)


def test_scan_clamp_keeps_key_in_int32():
    """Wide codes must shrink the RETAINED scan path's block (not
    error): its packed selection key dist*(m+blk)+pos has to fit int32
    for any code width.  (The fused kernel has no such bound — its
    carries are separate (dist, idx) planes.)"""
    from randomprojection_tpu.models.sketch import _scan_clamp

    # 256-bit codes: the default block passes untouched
    blk, fits = _scan_clamp(32768, 16, 257)
    assert blk == 32768 and fits
    # 131072-bit codes (16 KiB/code): halves until the key fits
    blk, fits = _scan_clamp(32768, 16, 131073)
    assert blk == 8192 and fits
    assert (131073 + 1) * (16 + blk) < 2**31
    # a request past even the floor block reports unfit (the routing
    # then tries fused, then dense) instead of overflowing silently
    _, fits = _scan_clamp(32768, 130000, 2**24 + 1)
    assert not fits
