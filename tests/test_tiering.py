"""Tiered hot/cold residency (ISSUE 19 / r21): the admission planner,
the ``TieredResidency`` manager surface (admit/register/residency/
manifest block), bit-parity of tiered serving with the fully resident
index on the exact and LSH paths, the synchronous-fallback rung when
the async upload dies, the disk spill + snapshot round trip, the
manifest tier-block validator, and the doctor's residency section fed
by real ``index.tier.*`` events.

Shape discipline: same family as test_ann (8-byte codes, m=5, 8-row
query tiles, 400-row corpora split into 4 chunks of 100) so compiled
interpreter programs are shared, not re-paid per test."""

import json
import os

import numpy as np
import pytest

from randomprojection_tpu import durable
from randomprojection_tpu.models import sketch as sk
from randomprojection_tpu.tiering import (
    COLD_TIERS,
    TieredResidency,
    plan_residency,
)
from randomprojection_tpu.utils import telemetry

N, NB, M, CHUNK = 400, 8, 5, 100
# one chunk hot (100 rows x 8 B), three cold: 4x over budget
BUDGET = CHUNK * NB


def _codes(seed=0, n=N):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, NB), dtype=np.uint8
    )


def _queries(seed=100):
    return np.random.default_rng(seed).integers(
        0, 256, size=(8, NB), dtype=np.uint8
    )


def _ingest(index, codes):
    for lo in range(0, codes.shape[0], CHUNK):
        index.add(codes[lo : lo + CHUNK])
    return index


def _tiered(codes, **kw):
    kw.setdefault("hbm_budget_bytes", BUDGET)
    return _ingest(sk.SimHashIndex(codes[:0], **kw), codes)


# -- the admission planner ---------------------------------------------------


def test_plan_residency_greedy_by_score_then_ordinal():
    p = plan_residency([10, 10, 10], 20)
    assert p.hot == {0, 1} and p.hot_bytes == 20
    # double-buffered staging headroom = 2 x largest cold chunk
    assert p.staging_bytes == 20
    p = plan_residency([10, 10, 10], 20, scores=[1.0, 5.0, 3.0])
    assert p.hot == {1, 2}
    # greedy, not knapsack: the best-scored chunk that fits is taken
    # even when skipping it would pack more bytes
    p = plan_residency([30, 10, 10], 20, scores=[9.0, 1.0, 1.0])
    assert p.hot == {1, 2}


def test_plan_residency_everything_fits_or_nothing():
    p = plan_residency([10, 10], 100)
    assert p.hot == {0, 1} and p.staging_bytes == 0
    p = plan_residency([10, 10], 0)
    assert p.hot == frozenset() and p.hot_bytes == 0


def test_plan_residency_validation():
    with pytest.raises(ValueError):
        plan_residency([10], -1)
    with pytest.raises(ValueError):
        plan_residency([10, 10], 10, scores=[1.0])


# -- manager surface ---------------------------------------------------------


def test_tier_ctor_validation(tmp_path):
    with pytest.raises(ValueError):
        TieredResidency(-1)
    with pytest.raises(ValueError):
        TieredResidency(1024, cold_tier="lukewarm")
    with pytest.raises(ValueError):
        TieredResidency(1024, cold_tier="disk")  # no cold_dir
    t = TieredResidency(1024, cold_tier="disk", cold_dir=str(tmp_path / "c"))
    assert os.path.isdir(tmp_path / "c")
    t.close()


def test_index_ctor_tier_validation():
    codes = _codes()
    with pytest.raises(ValueError):
        sk.SimHashIndex(codes, hbm_budget_bytes=1024, cold_tier="bogus")
    with pytest.raises(ValueError):
        sk.SimHashIndex(codes, hbm_budget_bytes=1024, cold_tier="disk")


def test_residency_snapshot_and_manifest_block():
    idx = _tiered(_codes())
    try:
        r = idx._tier.residency()
        assert r["hbm_budget_bytes"] == BUDGET
        assert r["hot_bytes"] <= BUDGET
        assert [c["rows"] for c in r["chunks"]] == [CHUNK] * 4
        tags = {c["tier"] for c in r["chunks"]}
        assert tags <= {"hot", "host"} and "host" in tags
        block = idx._tier.manifest_block()["tier"]
        assert block["format"] == 1 and block["cold_tier"] == "host"
        assert block["chunks"] == r["chunks"]
    finally:
        idx.close()


def test_untiered_index_has_no_tier():
    idx = sk.SimHashIndex(_codes())
    assert idx._tier is None
    idx.close()  # close() is safe untiered


# -- bit-parity with the resident index --------------------------------------


def test_exact_parity_4x_over_budget():
    codes, q = _codes(), _queries()
    resident = _ingest(sk.SimHashIndex(codes[:0]), codes)
    tiered = _tiered(codes)
    try:
        rd, ri = resident.query_topk(q, M)
        td, ti = tiered.query_topk(q, M)
        assert (td == rd).all() and (ti == ri).all()
        # the cold path actually ran: fetch traffic on the registry
        assert telemetry.registry().counter("index.tier.cold_rows") > 0
    finally:
        tiered.close()
        resident.close()


def test_exact_parity_with_seam_spanning_tombstones():
    codes, q = _codes(), _queries()
    dead = np.arange(CHUNK - 20, CHUNK + 20)  # spans the chunk seam
    resident = _ingest(sk.SimHashIndex(codes[:0]), codes)
    tiered = _tiered(codes)
    try:
        resident.delete(dead)
        tiered.delete(dead)
        rd, ri = resident.query_topk(q, M)
        td, ti = tiered.query_topk(q, M)
        assert (td == rd).all() and (ti == ri).all()
        assert not np.isin(ti, dead).any()
    finally:
        tiered.close()
        resident.close()


def test_sync_demote_keeps_parity():
    codes, q = _codes(), _queries()
    resident = _ingest(sk.SimHashIndex(codes[:0]), codes)
    # budget fits everything; then demote one chunk by hand
    tiered = _tiered(codes, hbm_budget_bytes=1 << 20)
    try:
        assert tiered._tier.demote(0) is True
        assert tiered._tier.demote(0) is False  # already cold
        assert tiered._tier.demote(99999) is False  # unknown row0
        tags = [c["tier"] for c in tiered._tier.residency()["chunks"]]
        assert tags[0] == "host" and set(tags[1:]) == {"hot"}
        rd, ri = resident.query_topk(q, M)
        td, ti = tiered.query_topk(q, M)
        assert (td == rd).all() and (ti == ri).all()
    finally:
        tiered.close()
        resident.close()


def test_upload_failure_degrades_to_sync_fetch(monkeypatch):
    # the LSH re-rank path stages cold candidate rows through
    # topk_kernels.stage_rows; killing it must degrade to the
    # synchronous host rung with identical answers + an audit record
    codes, q = _codes(), _queries()
    from randomprojection_tpu.ann import LSHSimHashIndex
    from randomprojection_tpu.ops import topk_kernels

    kw = dict(bands=4, band_bits=8, fallback_density=1.0,
              probe_path="host")
    resident = _ingest(LSHSimHashIndex(codes[:0], **kw), codes)
    tiered = _ingest(
        LSHSimHashIndex(codes[:0], hbm_budget_bytes=BUDGET, **kw), codes
    )

    def _boom(rows, **kw):
        raise RuntimeError("injected upload failure")

    try:
        rd, ri = resident.query_topk(q, M, probes=2)
        reg = telemetry.registry()
        fb0 = reg.counter("index.tier.fallbacks")
        monkeypatch.setattr(topk_kernels, "stage_rows", _boom)
        td, ti = tiered.query_topk(q, M, probes=2)
        assert (td == rd).all() and (ti == ri).all()
        # host zero-padded gather is the synchronous rung: answers
        # identical, the degraded audit records the dead upload
        assert reg.counter("index.tier.fallbacks") > fb0
    finally:
        tiered.close()
        resident.close()


@pytest.mark.slow
def test_lsh_parity_tiered_partial_and_full_probes():
    codes, q = _codes(), _queries()
    from randomprojection_tpu.ann import LSHSimHashIndex

    kw = dict(bands=4, band_bits=8, fallback_density=1.0,
              probe_path="host")
    resident = _ingest(LSHSimHashIndex(codes[:0], **kw), codes)
    tiered = _ingest(
        LSHSimHashIndex(codes[:0], hbm_budget_bytes=BUDGET, **kw), codes
    )
    try:
        for probes in (2, 1 << 8):  # partial + full coverage
            rd, ri = resident.query_topk(q, M, probes=probes)
            td, ti = tiered.query_topk(q, M, probes=probes)
            assert (td == rd).all(), probes
            assert (ti == ri).all(), probes
    finally:
        tiered.close()
        resident.close()


@pytest.mark.slow
def test_lsh_sharded_tiered_parity():
    codes, q = _codes(), _queries()
    from randomprojection_tpu.ann import LSHShardedSimHashIndex

    kw = dict(bands=4, band_bits=8, fallback_density=1.0,
              probe_path="host", n_shards=4)
    resident = LSHShardedSimHashIndex(codes, **kw)
    tiered = LSHShardedSimHashIndex(
        codes, hbm_budget_bytes=NB * CHUNK // 2, **kw
    )
    try:
        rd, ri = resident.query_topk(q, M, probes=1 << 8)
        td, ti = tiered.query_topk(q, M, probes=1 << 8)
        assert (td == rd).all() and (ti == ri).all()
    finally:
        tiered.close()


# -- disk tier + durability --------------------------------------------------


def test_disk_tier_spills_and_snapshot_roundtrip(tmp_path):
    codes, q = _codes(), _queries()
    resident = _ingest(sk.SimHashIndex(codes[:0]), codes)
    tiered = _tiered(
        codes, cold_tier="disk", cold_dir=str(tmp_path / "cold")
    )
    snap = str(tmp_path / "snap")
    try:
        spills = sorted(os.listdir(tmp_path / "cold"))
        assert len(spills) == 3  # 4 chunks, 1 hot
        assert all(s.startswith("chunk-") and s.endswith(".npy")
                   for s in spills)
        rd, ri = resident.query_topk(q, M)
        td, ti = tiered.query_topk(q, M)
        assert (td == rd).all() and (ti == ri).all()

        durable.save_index(tiered, snap)
        status = durable.verify_snapshot(snap)
        assert status["ok"], status
        assert status["tier"]["cold_chunks"] == 3
        restored = durable.load_index(snap)
        xd, xi = restored.query_topk(q, M)
        assert (xd == rd).all() and (xi == ri).all()
        restored.close()
    finally:
        tiered.close()
        resident.close()


def test_tier_block_validator(tmp_path):
    codes = _codes()
    tiered = _tiered(codes)
    snap = str(tmp_path / "snap")
    try:
        durable.save_index(tiered, snap)
    finally:
        tiered.close()
    manifest = durable.read_manifest(snap)
    durable._check_tier_block(manifest)  # as written: fine
    durable._check_tier_block({"chunks": []})  # pre-tier: no-op

    bad = json.loads(json.dumps(manifest))
    bad["tier"]["format"] = 2
    with pytest.raises(ValueError, match="format"):
        durable._check_tier_block(bad)
    bad = json.loads(json.dumps(manifest))
    bad["tier"]["cold_tier"] = "lukewarm"
    with pytest.raises(ValueError, match="cold_tier"):
        durable._check_tier_block(bad)
    bad = json.loads(json.dumps(manifest))
    bad["tier"]["chunks"][0]["tier"] = "lukewarm"
    with pytest.raises(ValueError, match="residency tag"):
        durable._check_tier_block(bad)
    bad = json.loads(json.dumps(manifest))
    bad["tier"]["chunks"][0]["rows"] += 1
    with pytest.raises(ValueError, match="disagrees"):
        durable._check_tier_block(bad)

    # load_index runs the same validator: a corrupted tag fails loudly
    with open(os.path.join(snap, durable.MANIFEST_NAME)) as f:
        m = json.load(f)
    m["tier"]["chunks"][0]["tier"] = "lukewarm"
    with open(os.path.join(snap, durable.MANIFEST_NAME), "w") as f:
        json.dump(m, f)
    with pytest.raises(ValueError, match="residency tag"):
        durable.load_index(snap)
    assert not durable.verify_snapshot(snap)["ok"]


def test_compact_resets_tier_generation(tmp_path):
    codes, q = _codes(), _queries()
    resident = _ingest(sk.SimHashIndex(codes[:0]), codes)
    tiered = _tiered(
        codes, cold_tier="disk", cold_dir=str(tmp_path / "cold")
    )
    try:
        dead = np.arange(50)
        resident.delete(dead)
        resident.compact()
        tiered.delete(dead)
        tiered.compact()
        # old-generation spills are unlinked; the rebuilt chunk
        # re-tiers (gen 2 spill names) under the same budget
        names = os.listdir(tmp_path / "cold")
        assert names and all("-000001-" not in n for n in names)
        # compact remaps global ids identically on both indexes
        rd, ri = resident.query_topk(q, M)
        td, ti = tiered.query_topk(q, M)
        assert (td == rd).all() and (ti == ri).all()
    finally:
        tiered.close()
        resident.close()


def test_close_is_idempotent():
    idx = _tiered(_codes())
    idx.close()
    idx.close()


# -- telemetry / doctor ------------------------------------------------------


def test_doctor_residency_section(tmp_path):
    from randomprojection_tpu.utils import trace_report

    path = str(tmp_path / "events.jsonl")
    events = [
        {"event": "index.tier.hit", "hot_rows": 300, "cold_rows": 100},
        {"event": "index.tier.fetch", "rows": 100, "bytes": 800,
         "wall_s": 0.01, "overlap_s": 0.02, "source": "host",
         "sync": False, "promote": False},
        {"event": "index.tier.fetch", "rows": 100, "bytes": 800,
         "wall_s": 0.03, "overlap_s": 0.0, "source": "host",
         "sync": True, "promote": False},
        {"event": "index.tier.fetch", "rows": 100, "bytes": 800,
         "wall_s": 0.02, "overlap_s": 0.0, "source": "host",
         "sync": False, "promote": True},
        {"event": "index.tier.evict", "rows": 100, "bytes": 800,
         "tier": "disk", "wall_s": 0.005},
        {"event": "index.tier.fallback", "reason": "upload:RuntimeError",
         "rows": 100},
    ]
    with open(path, "w") as f:
        for ts, e in enumerate(events):
            f.write(json.dumps({"ts": float(ts), "v": 2, **e}) + "\n")
    report = trace_report.build_report(path)
    rs = report["residency"]
    assert rs["tiles"] == 1
    assert rs["hot_rows"] == 300 and rs["cold_rows"] == 100
    assert rs["hot_hit_ratio"] == 0.75
    # the promote fetch is churn, not serving traffic
    assert rs["cold_fetches"] == 2 and rs["promotions"] == 1
    assert rs["sync_fetches"] == 1
    assert rs["cold_fetch_wall_s"] == pytest.approx(0.04)
    assert rs["cold_fetch_overlapped_s"] == pytest.approx(0.02)
    assert rs["cold_fetch_p99_s"] == pytest.approx(0.03)
    assert rs["demotions"] == 1
    assert rs["fallbacks"] == {"upload:RuntimeError": 1}
    # the fallback is on the degraded audit (RP02 consumption contract)
    assert report["degraded"]["index.tier.fallback"] == 1
    text = trace_report.render_report(report)
    assert "residency (tiered hot/cold corpus" in text
    assert "hot-hit ratio 0.7500" in text
    assert "degraded sync fallbacks: 1" in text


def test_no_residency_section_without_tier_events(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"ts": 0.0, "v": 2, "event": "hash.batch"})
                + "\n")
    from randomprojection_tpu.utils import trace_report

    report = trace_report.build_report(path)
    assert report["residency"] is None
    assert ("residency (tiered hot/cold corpus"
            not in trace_report.render_report(report))


def test_tier_events_registered():
    from randomprojection_tpu.utils.telemetry import EVENTS

    assert EVENTS.INDEX_TIER_HIT == "index.tier.hit"
    assert EVENTS.INDEX_TIER_FETCH == "index.tier.fetch"
    assert EVENTS.INDEX_TIER_EVICT == "index.tier.evict"
    assert EVENTS.INDEX_TIER_FALLBACK == "index.tier.fallback"
    assert COLD_TIERS == ("host", "disk")


# -- bench record ------------------------------------------------------------


@pytest.mark.slow
def test_bench_tiered_record_shape():
    from randomprojection_tpu import benchmark as B

    rec = B.measure_topk_tiered("smoke")
    assert rec["parity_ok"] is True
    assert rec["over_budget_factor"] == 4.0
    assert rec["hot_hit_fraction"] is None or 0 <= rec["hot_hit_fraction"] <= 1
    assert rec["cold_fetch_overlapped_s"] >= 0
    assert isinstance(rec["timing_suspect"], bool)
    c = B.compact_summary({
        "config4": {"topk_serving": {"queries_per_s": 1.0, "tiered": rec}}
    })
    c4 = c["config4"]
    assert c4["topk_tiered_parity_ok"] is True
    assert "topk_tiered_hot_hit_fraction" in c4
    assert "topk_tiered_cold_fetch_p99_s" in c4
