"""Feature-hashing tests: C++ murmur3 vs Python oracle vs sklearn parity."""

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu.native.build import load_murmur3
from randomprojection_tpu.ops.hashing import (
    FeatureHasher,
    _murmur3_32_py,
    hash_tokens,
    murmur3_32,
)


def test_murmur3_known_vectors():
    # Public MurmurHash3 x86_32 test vectors (unsigned)
    assert _murmur3_32_py(b"", 0) == 0
    assert _murmur3_32_py(b"", 1) == 0x514E28B7
    assert _murmur3_32_py(b"abc", 0) == 0xB3DD93FA
    assert _murmur3_32_py(b"Hello, world!", 0x9747B28C) == 0x24884CBA


def test_native_matches_python_oracle():
    lib = load_murmur3()
    assert lib is not None, "g++ is in this image; native build must succeed"
    rng = np.random.default_rng(0)
    for _ in range(200):
        n = int(rng.integers(0, 40))
        data = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        seed = int(rng.integers(0, 2**32))
        assert lib.murmur3_32(data, len(data), seed) == _murmur3_32_py(data, seed)


def test_hash_tokens_native_vs_fallback(monkeypatch):
    tokens = ["foo", "bar", "baz qux", "", "日本語", "x" * 100]
    idx_n, sign_n = hash_tokens(tokens, 1024)
    # force the pure-Python path
    monkeypatch.setattr(
        "randomprojection_tpu.ops.hashing.load_murmur3", lambda: None
    )
    idx_p, sign_p = hash_tokens(tokens, 1024)
    np.testing.assert_array_equal(idx_n, idx_p)
    np.testing.assert_array_equal(sign_n, sign_p)


def test_feature_hasher_sklearn_parity():
    """Same tokens → same CSR as sklearn's Cython FeatureHasher."""
    sk = pytest.importorskip("sklearn.feature_extraction")
    docs = [
        {"dog": 1.0, "cat": 2.0, "elephant": 4.0},
        {"dog": 2.0, "run": 5.0, "": 1.0},
        {},
    ]
    for alternate_sign in (True, False):
        ours = FeatureHasher(
            n_features=256, input_type="dict", alternate_sign=alternate_sign
        ).transform(docs)
        theirs = sk.FeatureHasher(
            n_features=256, input_type="dict", alternate_sign=alternate_sign
        ).transform(docs)
        assert (sp.csr_matrix(ours) != sp.csr_matrix(theirs)).nnz == 0


def test_feature_hasher_input_types():
    s = FeatureHasher(n_features=64, input_type="string").transform(
        [["a", "b", "a"], ["c"]]
    )
    p = FeatureHasher(n_features=64, input_type="pair").transform(
        [[("a", 2.0), ("b", 1.0)], [("c", 1.0)]]
    )
    assert s.shape == (2, 64) and p.shape == (2, 64)
    # "a" twice as strings == ("a", 2.0) as pair
    np.testing.assert_allclose(s.toarray(), p.toarray())


def test_feature_hasher_validation():
    with pytest.raises(ValueError):
        FeatureHasher(n_features=0)
    with pytest.raises(ValueError):
        FeatureHasher(input_type="nope")


def test_non_string_tokens_raise_type_error():
    """sklearn FeatureHasher contract: feature names must be str/bytes.
    (bytes(int) would silently turn n into n zero bytes — every equal int
    collapsing to one bucket.)"""
    with pytest.raises(TypeError, match="str or bytes"):
        hash_tokens([5], 64)
    with pytest.raises(TypeError, match="str or bytes"):
        hash_tokens(["ok", 3.5], 64)
    with pytest.raises(TypeError, match="str or bytes"):
        FeatureHasher(n_features=64, input_type="string").transform([[1, 2]])
    # bytes and bytearray both pass through as raw bytes
    idx_b, _ = hash_tokens([b"tok", bytearray(b"tok")], 64)
    assert idx_b[0] == idx_b[1]


def test_feature_hasher_feeds_countsketch():
    """Config 5 end-to-end: raw docs → hashed CSR → CountSketch → dense."""
    from randomprojection_tpu import CountSketch

    docs = [{"w%d" % (i % 50): float(i % 7 + 1) for i in range(j * 3, j * 3 + 30)}
            for j in range(20)]
    Xh = FeatureHasher(n_features=4096, input_type="dict").transform(docs)
    cs = CountSketch(128, random_state=0).fit(Xh)
    Y = cs.transform(Xh)
    assert Y.shape == (20, 128)
    # sketch of hashed space still approximates inner products of the CSR
    G_true = (Xh @ Xh.T).toarray()
    G_est = Y @ Y.T
    scale = np.abs(G_true).max()
    assert np.abs(G_est - G_true).max() / scale < 0.5


def test_transform_tokens_rejects_bad_indptr():
    """Non-monotone indptr must fail loudly, not as an opaque scipy internal
    error or a silently malformed CSR (ADVICE r2)."""
    from randomprojection_tpu.ops.hashing import FeatureHasher

    fh = FeatureHasher(n_features=64, input_type="string")
    toks = np.asarray(["a", "b", "c", "d"])
    with pytest.raises(ValueError, match="non-decreasing"):
        fh.transform_tokens(toks, indptr=[0, 3, 1, 4])
    with pytest.raises(ValueError, match="indptr"):
        fh.transform_tokens(toks, indptr=[1, 4])
    with pytest.raises(ValueError, match="values"):
        fh.transform_tokens(toks, indptr=[0, 4], values=[1.0, 2.0])


def test_embedded_nul_tokens_hash_consistently():
    """A token with an embedded NUL must hash identically whether it arrives
    as a numpy U/S array or a plain list (ADVICE r2: the strided path used
    to truncate at the first NUL while the list path hashed all bytes)."""
    from randomprojection_tpu.ops.hashing import hash_tokens

    tok_s = b"ab\x00cd"
    tok_u = "ab\x00cd"
    for arr, ref in (
        (np.asarray([tok_s, b"plain"]), [tok_s, b"plain"]),
        (np.asarray([tok_u, "plain"]), [tok_u, "plain"]),
    ):
        idx_a, sign_a = hash_tokens(arr, 1 << 16)
        idx_l, sign_l = hash_tokens(ref, 1 << 16)
        np.testing.assert_array_equal(idx_a, idx_l)
        np.testing.assert_array_equal(sign_a, sign_l)
    # and an embedded-NUL token is NOT the same as its truncation
    (i1, _), (i2, _) = hash_tokens([tok_s], 1 << 16), hash_tokens([b"ab"], 1 << 16)
    assert i1[0] != i2[0]


def test_threaded_hashing_bit_identical(monkeypatch):
    """Token i's outputs depend only on token i, so the threaded batch path
    must be bit-identical to serial at any thread count (RP_HASH_THREADS
    forces threads even on a 1-core box; batch >= 2^18 engages the split)."""
    from randomprojection_tpu.native.build import load_murmur3
    from randomprojection_tpu.ops.hashing import hash_tokens

    if load_murmur3() is None:
        pytest.skip("no compiler: threaded path does not exist")
    rng = np.random.default_rng(0)
    toks = np.char.add("w", rng.integers(0, 1 << 20, size=(1 << 18) + 3).astype("U8"))
    monkeypatch.setenv("RP_HASH_THREADS", "1")
    idx1, sign1 = hash_tokens(toks, 1 << 16)
    monkeypatch.setenv("RP_HASH_THREADS", "4")
    idx4, sign4 = hash_tokens(toks, 1 << 16)
    np.testing.assert_array_equal(idx1, idx4)
    np.testing.assert_array_equal(sign1, sign4)
    # list path (offsets-based hash_tokens) too
    sub = toks[: (1 << 18) + 3].tolist()
    monkeypatch.setenv("RP_HASH_THREADS", "3")
    idxl, _ = hash_tokens(sub, 1 << 16)
    np.testing.assert_array_equal(idxl, idx1)


def test_feature_hasher_dtype_param():
    """dtype selects the CSR value dtype (sklearn FeatureHasher parity);
    float32 is what feeds the device CountSketch path without a cast."""
    from randomprojection_tpu.ops.hashing import FeatureHasher

    fh32 = FeatureHasher(1 << 10, input_type="string", dtype=np.float32)
    X32 = fh32.transform_tokens(np.asarray(["a", "b", "a"]))
    assert X32.dtype == np.float32
    fh64 = FeatureHasher(1 << 10, input_type="string")
    X64 = fh64.transform_tokens(np.asarray(["a", "b", "a"]))
    assert X64.dtype == np.float64
    np.testing.assert_array_equal(X32.toarray(), X64.toarray())
    with pytest.raises(ValueError, match="dtype"):
        FeatureHasher(16, dtype=np.int32)
