"""Test harness config.

Multi-chip strategy (SURVEY.md §5): all sharding tests run on a virtual
8-device CPU mesh via XLA_FLAGS, in plain pytest, before jax is imported
anywhere.  The same sharded code then runs unmodified on a real TPU slice;
the driver's dryrun_multichip covers the compile path separately.
"""

import os
import sys

# Must happen before any jax import (jax reads these at first import).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
