"""Test harness config.

Multi-chip strategy (SURVEY.md §5): all sharding tests run on a virtual
8-device CPU mesh via XLA_FLAGS, in plain pytest, before jax is imported
anywhere.  The same sharded code then runs unmodified on a real TPU slice;
the driver's dryrun_multichip covers the compile path separately.
"""

import os
import sys

# Must happen before any jax import (jax reads these at first import).
# Force-override: the environment pre-sets JAX_PLATFORMS to the real TPU
# platform, but the test suite runs on a virtual 8-device CPU mesh; set
# RP_TEST_TPU=1 to run the suite against the real chip instead.
if os.environ.get("RP_TEST_TPU", "") in ("", "0"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _FORCE_CPU = True
else:
    _FORCE_CPU = False
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

if _FORCE_CPU:
    # The environment pre-registers an out-of-tree TPU platform plugin that
    # wins over the JAX_PLATFORMS env var; the config knob reliably pins CPU.
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ---------------------------------------------------------------------------
# mesh-path capability probe (ISSUE r8 satellite)
#
# Some installed jax versions cannot run this repo's mesh path on the
# virtual CPU mesh (e.g. jax 0.4.37 has no top-level ``jax.shard_map``,
# and its ``.at[...].get`` lacks ``out_sharding`` — the ragged-tail mesh
# slice).  Those are ENVIRONMENT failures, not code regressions, and a
# permanently red tier-1 masks real breakage.  Tests that exercise the
# mesh path carry ``@pytest.mark.mesh_env``; before each one runs, the
# probe below actually EXECUTES a tiny version of both capabilities and
# skips — with the captured error as the reason — only when the
# environment genuinely cannot run them.  On a compatible jax the probe
# passes and every marked test runs: nothing is silently skipped.
# ---------------------------------------------------------------------------

_MESH_ENV_REASON: list = []  # memo cell: [] = not probed, [None|str] = result


def _mesh_env_reason():
    """None when the installed jax can run the repo's mesh path on the
    virtual mesh; else a one-line reason.  Probes by execution (never by
    version sniffing): a tiny ``jax.shard_map`` psum program and the
    ragged ``.at[:n].get(out_sharding=...)`` gather that
    ``slice_rows_sharded`` needs for non-divisible row counts."""
    if _MESH_ENV_REASON:
        return _MESH_ENV_REASON[0]
    reason = None
    try:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from randomprojection_tpu.parallel import make_mesh

        mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
        fn = jax.jit(
            jax.shard_map(
                lambda x: jax.lax.psum(x.sum(), "data") + x,
                mesh=mesh, in_specs=(P("data", None),),
                out_specs=P("data", None),
            )
        )
        x = jnp.arange(8.0).reshape(4, 2)
        np.testing.assert_allclose(
            np.asarray(fn(x)), np.asarray(x) + float(x.sum())
        )
        # the ragged mesh slice: XLA cannot slice a sharded dim to a
        # non-divisible size, so slice_rows_sharded gathers replicated
        y = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        np.testing.assert_allclose(
            np.asarray(y.at[:3].get(out_sharding=NamedSharding(mesh, P()))),
            np.asarray(x)[:3],
        )
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"
        reason = reason.splitlines()[0][:200]
    _MESH_ENV_REASON.append(reason)
    return reason


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh_env: needs a jax that can run shard_map (and the ragged "
        "out_sharding slice) on the virtual mesh; skipped with the "
        "probe's captured error when the installed jax cannot",
    )


def pytest_runtest_setup(item):
    if item.get_closest_marker("mesh_env") is not None:
        reason = _mesh_env_reason()
        if reason is not None:
            pytest.skip(
                "installed jax cannot run the shard_map mesh path on the "
                f"virtual mesh: {reason}"
            )
