"""Test harness config.

Multi-chip strategy (SURVEY.md §5): all sharding tests run on a virtual
8-device CPU mesh via XLA_FLAGS, in plain pytest, before jax is imported
anywhere.  The same sharded code then runs unmodified on a real TPU slice;
the driver's dryrun_multichip covers the compile path separately.
"""

import os
import sys

# Must happen before any jax import (jax reads these at first import).
# Force-override: the environment pre-sets JAX_PLATFORMS to the real TPU
# platform, but the test suite runs on a virtual 8-device CPU mesh; set
# RP_TEST_TPU=1 to run the suite against the real chip instead.
if os.environ.get("RP_TEST_TPU", "") in ("", "0"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _FORCE_CPU = True
else:
    _FORCE_CPU = False
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

if _FORCE_CPU:
    # The environment pre-registers an out-of-tree TPU platform plugin that
    # wins over the JAX_PLATFORMS env var; the config knob reliably pins CPU.
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return np.random.default_rng(0)
