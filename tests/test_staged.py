"""Staged multi-worker ingest tests (ISSUE r9): pool output bit-identical
to serial (order + values), fault-injection crash/resume through the pool
(the cursor never drops or double-commits a row range), clean ``break``
closes every stage trace as abandoned and joins every worker, and the
per-stage observability (queue gauge, stage walls, staged deliver
events)."""

import threading

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import GaussianRandomProjection
from randomprojection_tpu.models.sketch import CountSketch
from randomprojection_tpu.streaming import (
    ArraySource,
    FaultInjectionSource,
    PrefetchSource,
    StagedIngestSource,
    StreamCursor,
    TokenSource,
    stream_transform,
)
from randomprojection_tpu.utils.observability import StreamStats


def staged_threads():
    return [
        t for t in threading.enumerate() if t.name.startswith("rp-staged")
    ]


@pytest.fixture
def X():
    return np.random.default_rng(0).normal(size=(1000, 128)).astype(np.float32)


def make_est(backend="numpy", k=16):
    return GaussianRandomProjection(
        n_components=k, random_state=0, backend=backend
    )


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("workers", [1, 2, 4])
def test_staged_matches_serial(X, backend, workers):
    """The pool must change WHEN batches are produced, never their order
    or values — bit-identical to the serial stream at any worker count."""
    est = make_est(backend).fit(X)
    ref = list(est.transform_stream(ArraySource(X, 128)))
    got = list(
        est.transform_stream(
            StagedIngestSource(
                ArraySource(X, 128), workers=workers, depth=2,
                prepare=est.prepare_batch,
            )
        )
    )
    assert [lo for lo, _ in got] == [lo for lo, _ in ref]
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(y) for _, y in got]),
        np.concatenate([np.asarray(y) for _, y in ref]),
    )
    assert not staged_threads()


def test_staged_token_pipeline_matches_prefetch(tmp_path):
    """The config-5 composition: TokenSource → staged pool (per-worker
    serial hashing) must reproduce the single-worker prefetch pipeline's
    output exactly."""
    from randomprojection_tpu.ops.hashing import FeatureHasher

    words = np.asarray([f"w{i}" for i in range(2000)])

    def read_tokens(lo, hi):
        rngs = [np.random.default_rng(900 + i) for i in range(lo, hi)]
        toks = np.concatenate(
            [words[r.integers(0, len(words), size=10)] for r in rngs]
        )
        return toks, np.arange(0, (hi - lo) * 10 + 10, 10)

    fh = FeatureHasher(1 << 14, input_type="string", dtype=np.float32)
    cs = CountSketch(16, random_state=0, backend="jax").fit_schema(
        128, 1 << 14, np.float32
    )
    ref = np.concatenate([
        np.asarray(y)
        for _, y in stream_transform(
            cs,
            PrefetchSource(
                TokenSource(read_tokens, 128, fh, batch_rows=32),
                depth=2, prepare=cs.prepare_batch,
            ),
        )
    ])
    got = np.concatenate([
        np.asarray(y)
        for _, y in stream_transform(
            cs,
            StagedIngestSource(
                TokenSource(
                    read_tokens, 128, fh, batch_rows=32, hash_threads=1
                ),
                workers=3, depth=2, prepare=cs.prepare_batch,
            ),
        )
    ])
    np.testing.assert_array_equal(got, ref)
    assert not staged_threads()


def test_staged_validation(X):
    with pytest.raises(ValueError, match="workers"):
        StagedIngestSource(ArraySource(X, 128), workers=0)
    with pytest.raises(ValueError, match="depth"):
        StagedIngestSource(ArraySource(X, 128), depth=0)
    with pytest.raises(ValueError, match="start_row"):
        list(StagedIngestSource(ArraySource(X, 128)).iter_batches(3))


def test_staged_schema_delegates(X):
    src = StagedIngestSource(ArraySource(X, 128), workers=2)
    assert src.schema() == ArraySource(X, 128).schema()
    assert src.batch_rows == 128


@pytest.mark.parametrize("workers", [2, 3])
def test_staged_fault_resume_never_drops_or_double_commits(X, tmp_path,
                                                           workers):
    """A fault-injected crash through the pool must surface after the
    in-order prefix — same prefix as the serial source — and the
    checkpoint resume must cover every row exactly once."""
    est = make_est().fit(X)
    Y_ref = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )

    # serial reference prefix for the same fault point
    ckpt_ref = str(tmp_path / "ref.json")
    ref_rows = []
    with pytest.raises(FaultInjectionSource.InjectedFault):
        for lo, y in est.transform_stream(
            FaultInjectionSource(ArraySource(X, 128), 3),
            checkpoint_path=ckpt_ref,
        ):
            ref_rows.append(lo)
    serial_committed = StreamCursor.load(ckpt_ref).rows_done

    ckpt = str(tmp_path / "cursor.json")
    inner = FaultInjectionSource(ArraySource(X, 128), fail_after_batches=3)
    src = StagedIngestSource(inner, workers=workers, depth=2)
    got = []
    with pytest.raises(FaultInjectionSource.InjectedFault):
        for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
            got.append((lo, y))
    assert not staged_threads(), "every stage thread joined after the fault"
    committed = StreamCursor.load(ckpt).rows_done
    # the staged pool commits the identical prefix the serial source does
    assert committed == serial_committed
    assert committed == sum(y.shape[0] for _, y in got)
    assert [lo for lo, _ in got] == ref_rows

    inner.disarm()
    for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
        assert lo == committed, "resume must continue at the cursor"
        committed += y.shape[0]
        got.append((lo, y))
    # full coverage, no overlap, bit-identical values
    assert [lo for lo, _ in got] == list(range(0, 1000, 128))
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in got]), Y_ref
    )
    assert not staged_threads()


def test_staged_worker_exception_in_prepare_propagates(X):
    class PrepareBoom(RuntimeError):
        pass

    def bad_prepare(batch):
        raise PrepareBoom("prepare failed")

    est = make_est().fit(X)
    with pytest.raises(PrepareBoom):
        list(
            est.transform_stream(
                StagedIngestSource(
                    ArraySource(X, 128), workers=2, depth=2,
                    prepare=bad_prepare,
                )
            )
        )
    assert not staged_threads()


def test_staged_break_joins_workers_and_abandons_traces(X, tmp_path):
    """Clean ``break``: every stage thread joins, and every in-flight
    trace closes as abandoned — the doctor must see zero orphaned spans
    and only deliberate abandons."""
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.trace_report import build_report

    path = str(tmp_path / "events.jsonl")
    telemetry.configure(path)
    try:
        est = make_est().fit(X)
        for i, (lo, y) in enumerate(
            est.transform_stream(
                StagedIngestSource(ArraySource(X, 128), workers=2, depth=2)
            )
        ):
            if i == 1:
                break
    finally:
        telemetry.shutdown()
    assert not staged_threads()
    report = build_report(path)
    assert report["spans"]["orphan_starts"] == 0, (
        "a clean break must close every stage trace (abandoned), never "
        "leave orphans for the doctor to misread as a crash"
    )
    # only batch 0 committed: the break lands mid-yield of batch 1, which
    # therefore closes as abandoned (ack-after-yield), like everything
    # produced ahead of it
    assert report["traces"]["batches"] == 1
    assert report["traces"]["incomplete"] >= 2
    assert report["degraded"]["stream.staged.error"] == 0


def test_staged_stats_and_deliver_events(X, tmp_path):
    """Stage walls attribute to hash/h2d/dispatch/d2h, the final-queue
    occupancy gauge samples once per delivered batch, and the doctor
    reads ``stream.staged.deliver`` into its queue-depth summary."""
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.trace_report import build_report

    words = np.asarray([f"w{i}" for i in range(500)])

    def read_tokens(lo, hi):
        rngs = [np.random.default_rng(300 + i) for i in range(lo, hi)]
        toks = np.concatenate(
            [words[r.integers(0, len(words), size=10)] for r in rngs]
        )
        return toks, np.arange(0, (hi - lo) * 10 + 10, 10)

    fh = FeatureHasher(1 << 12, input_type="string", dtype=np.float32)
    stats = StreamStats()
    source = StagedIngestSource(
        TokenSource(read_tokens, 128, fh, batch_rows=32, stats=stats),
        workers=2, depth=2, prepare=None, stats=stats,
    )
    cs = CountSketch(16, random_state=0, backend="jax").fit_source(source)
    path = str(tmp_path / "events.jsonl")
    telemetry.configure(path)
    try:
        rows = 0
        for _, y in stream_transform(cs, source, stats=stats):
            rows += y.shape[0]
    finally:
        telemetry.shutdown()
    assert rows == 128
    assert {"hash", "dispatch", "d2h"} <= set(stats.stage_wall)
    # one occupancy sample per delivered batch, from the uploader
    assert stats.registry.gauge("stream.queue_depth")["n"] == 4
    report = build_report(path)
    assert report["queue_depth"] is not None
    assert report["queue_depth"]["samples"] == 4
    assert report["queue_depth"]["capacity"] == 2
    assert report["event_counts"]["stream.staged.deliver"] == 4
    assert report["traces"]["batches"] == 4
    assert report["spans"]["orphan_starts"] == 0


def test_staged_empty_and_tail(X):
    """A completed cursor (start_row == n_rows) yields nothing; a ragged
    tail arrives in order with the right row count."""
    est = make_est().fit(X)
    src = StagedIngestSource(ArraySource(X, 300), workers=2)
    assert list(src.iter_batches(1000)) == []
    got = list(est.transform_stream(src))
    assert [lo for lo, _ in got] == [0, 300, 600, 900]
    assert got[-1][1].shape[0] == 100
    assert not staged_threads()


def test_staged_prepared_device_batches(X):
    """CountSketch.prepare_batch on the uploader thread: DeviceBatch
    operands flow through the staged queues and dispatch identically."""
    rng = np.random.default_rng(3)
    D = rng.normal(size=(300, 256)).astype(np.float32)
    D[np.abs(D) < 1.0] = 0.0
    Xs = sp.csr_array(D)
    cs = CountSketch(16, random_state=0, backend="jax").fit_schema(
        *Xs.shape, np.float32
    )
    got = np.concatenate([
        np.asarray(y)
        for _, y in stream_transform(
            cs,
            StagedIngestSource(
                ArraySource(Xs, 64), workers=2, depth=2,
                prepare=cs.prepare_batch,
            ),
        )
    ])
    ref = (
        CountSketch(16, random_state=0, backend="numpy")
        .fit(Xs)
        .transform(Xs.astype(np.float64))
    )
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    assert not staged_threads()
