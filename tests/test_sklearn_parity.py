"""Direct parity against sklearn's random_projection — the on-disk
behavioral contract ([CAP] in SURVEY.md §0).  These tests pin OUR behavior
to the canonical implementation wherever the contract is exact, and to
matched statistics where PRNGs necessarily differ."""

import numpy as np
import pytest

sklearn_rp = pytest.importorskip("sklearn.random_projection")

from randomprojection_tpu import (
    GaussianRandomProjection,
    SparseRandomProjection,
    johnson_lindenstrauss_min_dim,
)


def test_jl_min_dim_matches_sklearn_exactly():
    ns = [10, 100, 5000, 10**6]
    epss = [0.05, 0.1, 0.5, 0.999]
    for n in ns:
        for e in epss:
            assert johnson_lindenstrauss_min_dim(n, eps=e) == int(
                sklearn_rp.johnson_lindenstrauss_min_dim(n, eps=e)
            ), (n, e)
    # array broadcasting parity
    np.testing.assert_array_equal(
        johnson_lindenstrauss_min_dim(np.array(ns), eps=0.3),
        sklearn_rp.johnson_lindenstrauss_min_dim(np.array(ns), eps=0.3),
    )


def test_jl_min_dim_32bit_regression():
    # TRP.py:451-456: the bound must not overflow 32-bit ints
    assert johnson_lindenstrauss_min_dim(100, eps=1e-5) == 368416070986


def test_auto_dim_resolution_matches_sklearn():
    X = np.zeros((10, 1000))
    ours = SparseRandomProjection(n_components="auto", eps=0.5, random_state=0,
                                  backend="numpy").fit(X)
    theirs = sklearn_rp.SparseRandomProjection(
        n_components="auto", eps=0.5, random_state=0
    ).fit(X)
    assert ours.n_components_ == theirs.n_components_ == 110
    assert ours.density_ == pytest.approx(theirs.density_)


def test_gaussian_matrix_statistics_match_sklearn():
    """Different PRNGs ⇒ statistical parity: mean, variance, row norms."""
    X = np.zeros((10, 2000))
    k = 500
    ours = GaussianRandomProjection(k, random_state=0, backend="numpy").fit(X)
    theirs = sklearn_rp.GaussianRandomProjection(k, random_state=0).fit(X)
    Ro, Rt = np.asarray(ours.components_), np.asarray(theirs.components_)
    assert Ro.shape == Rt.shape == (k, 2000)
    assert abs(Ro.mean() - Rt.mean()) < 1e-3
    np.testing.assert_allclose(Ro.var(), Rt.var(), rtol=0.02)
    np.testing.assert_allclose(
        np.linalg.norm(Ro, axis=1).mean(),
        np.linalg.norm(Rt, axis=1).mean(),
        rtol=0.02,
    )


def test_sparse_matrix_statistics_match_sklearn():
    import scipy.sparse as sp

    X = np.zeros((10, 2000))
    k = 400
    ours = SparseRandomProjection(k, density=0.1, random_state=0,
                                  backend="numpy").fit(X)
    theirs = sklearn_rp.SparseRandomProjection(k, density=0.1,
                                               random_state=0).fit(X)
    Ro, Rt = ours.components_, theirs.components_
    assert sp.issparse(Ro) and sp.issparse(Rt)
    # same value set
    np.testing.assert_allclose(
        np.unique(np.abs(Ro.data)), np.unique(np.abs(Rt.data)), rtol=1e-12
    )
    # same nnz rate within sampling noise
    np.testing.assert_allclose(Ro.nnz, Rt.nnz, rtol=0.03)


def test_transform_agrees_with_sklearn_given_same_matrix():
    """With identical R, our transform must be numerically identical
    (same BLAS on the numpy backend)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(100, 300))
    theirs = sklearn_rp.GaussianRandomProjection(32, random_state=0).fit(X)
    ours = GaussianRandomProjection(32, random_state=0, backend="numpy").fit(X)
    # graft sklearn's matrix into our fitted state
    ours._state = np.ascontiguousarray(theirs.components_)
    np.testing.assert_allclose(
        ours.transform(X), theirs.transform(X), rtol=1e-12, atol=1e-12
    )


def test_warning_and_error_conditions_match_sklearn():
    from randomprojection_tpu import DataDimensionalityWarning

    X = np.ones((1000, 100))
    with pytest.raises(ValueError):
        GaussianRandomProjection("auto", eps=0.1, backend="numpy").fit(X)
    with pytest.raises(ValueError):
        sklearn_rp.GaussianRandomProjection("auto", eps=0.1).fit(X)
    with pytest.warns(DataDimensionalityWarning):
        GaussianRandomProjection(200, random_state=0, backend="numpy").fit(
            np.ones((10, 100))
        )
    with pytest.warns(Warning):
        sklearn_rp.GaussianRandomProjection(200, random_state=0).fit(
            np.ones((10, 100))
        )


def test_inverse_transform_parity():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(80, 200))
    ours = GaussianRandomProjection(
        40, random_state=0, backend="numpy", compute_inverse_components=True
    ).fit(X)
    theirs = sklearn_rp.GaussianRandomProjection(
        40, random_state=0, compute_inverse_components=True
    ).fit(X)
    # identical algebra: graft their matrix and inverse into ours
    ours._state = np.ascontiguousarray(theirs.components_)
    ours.inverse_components_ = np.ascontiguousarray(theirs.inverse_components_)
    Y = theirs.transform(X)
    np.testing.assert_allclose(
        ours.inverse_transform(Y), theirs.inverse_transform(Y),
        rtol=1e-10, atol=1e-12,
    )


@pytest.mark.parametrize(
    "ours_cls, theirs_cls_name",
    [
        (GaussianRandomProjection, "GaussianRandomProjection"),
        (SparseRandomProjection, "SparseRandomProjection"),
    ],
)
def test_get_feature_names_out_matches_sklearn(ours_cls, theirs_cls_name):
    """Mirror of sklearn test_random_projection.py:459-481: names are
    ``<classname_lowercase><i>`` for i in range(n_components_), dtype
    object — byte-identical to sklearn's output."""
    X = np.random.default_rng(0).normal(size=(40, 96))
    ours = ours_cls(n_components=7, random_state=0, backend="numpy").fit(X)
    theirs = getattr(sklearn_rp, theirs_cls_name)(
        n_components=7, random_state=0
    ).fit(X)
    names = ours.get_feature_names_out()
    np.testing.assert_array_equal(names, theirs.get_feature_names_out())
    assert names.dtype == object

    # auto-dim: names track the resolved n_components_
    auto = ours_cls(random_state=0, eps=0.9, backend="numpy").fit(
        np.random.default_rng(0).normal(size=(50, 2000))
    )
    assert len(auto.get_feature_names_out()) == auto.n_components_

    # mismatched input_features is rejected (ClassNamePrefixFeaturesOutMixin
    # semantics)
    with pytest.raises(ValueError, match="input_features"):
        ours.get_feature_names_out(["a", "b"])
    # a correctly-sized input_features list is accepted (names unchanged)
    np.testing.assert_array_equal(
        ours.get_feature_names_out([f"f{i}" for i in range(96)]), names
    )


def test_get_feature_names_out_requires_fit():
    from randomprojection_tpu import CountSketch, NotFittedError, SignRandomProjection

    with pytest.raises(NotFittedError):
        GaussianRandomProjection(4).get_feature_names_out()
    X = np.zeros((10, 32))
    # sign codes are packed 8 bits/byte: names track the actual transform
    # output columns (ceil(k/8) uint8 columns), not the bit count
    sign_est = SignRandomProjection(16, random_state=0, backend="numpy").fit(X)
    names = sign_est.get_feature_names_out()
    assert list(names) == ["signrandomprojection0", "signrandomprojection1"]
    assert len(names) == sign_est.transform(X).shape[1]
    assert list(
        CountSketch(3, random_state=0, backend="numpy")
        .fit(X).get_feature_names_out()
    ) == ["countsketch0", "countsketch1", "countsketch2"]


def test_device_hamming_matches_host():
    from randomprojection_tpu import pairwise_hamming, pairwise_hamming_device

    rng = np.random.default_rng(0)
    A = rng.integers(0, 256, size=(300, 16), dtype=np.uint8)
    B = rng.integers(0, 256, size=(70, 16), dtype=np.uint8)
    np.testing.assert_array_equal(
        pairwise_hamming_device(A, B, tile=128), pairwise_hamming(A, B)
    )


def test_clone_and_set_params_roundtrip():
    """sklearn ``clone()`` must reconstruct an identical unfitted estimator
    for all four estimator families (VERDICT r2 weak #6: ``get_params``
    without ``set_params`` broke clone/CV composition)."""
    from sklearn.base import clone

    from randomprojection_tpu import CountSketch, SignRandomProjection

    ests = [
        GaussianRandomProjection(16, eps=0.2, random_state=3, backend="numpy"),
        SparseRandomProjection(
            8, density=0.25, dense_output=True, random_state=1,
            backend="jax", backend_options={"precision": "split2"},
        ),
        SignRandomProjection(64, random_state=2, backend="numpy"),
        CountSketch(32, random_state=4, backend="numpy"),
    ]
    X = np.random.default_rng(0).normal(size=(50, 128)).astype(np.float32)
    for est in ests:
        dup = clone(est)
        assert type(dup) is type(est)
        assert dup.get_params() == est.get_params()
        # the clone is unfitted and independently usable
        y_a = np.asarray(est.fit(X).transform(X))
        y_b = np.asarray(dup.fit(X).transform(X))
        np.testing.assert_array_equal(y_a, y_b)

    # set_params updates known params and refuses unknown ones
    est = SparseRandomProjection(8, random_state=0, backend="numpy")
    assert est.set_params(density=0.5, n_components=4) is est
    assert est.density == 0.5 and est.n_components == 4
    with pytest.raises(ValueError, match="Invalid parameter"):
        est.set_params(nonsense=1)
    with pytest.raises(ValueError, match="Invalid parameter"):
        GaussianRandomProjection(4).set_params(density=0.5)  # sparse-only
