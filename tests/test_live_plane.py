"""Live observability plane (ISSUE r17): in-process subscribers
(bounded queues, drop-never-block, no-sink activation), LiveAggregator
rolling windows incl. the time-weighted queue-depth fix for stalled
consumers, histogram quantile extraction (exact edge cases, concurrent
monotonicity, OpenMetrics round trip through a real HTTP scrape), the
metrics endpoint, per-request serve latency stamps in
TopKServer/ShardedTopKServer, the doctor's latency/loadgen sections and
--live mode, the deterministic open-loop load generator, and the
live-smoke harness."""

import json
import threading
import time

import numpy as np
import pytest

from randomprojection_tpu import cli, loadgen
from randomprojection_tpu.models.sketch import SimHashIndex, TopKServer
from randomprojection_tpu.utils import metrics_server, telemetry
from randomprojection_tpu.utils.telemetry import (
    EVENTS,
    LiveAggregator,
    MetricsRegistry,
    quantiles_from_buckets,
)


def _drain(sub, predicate, timeout=5.0):
    """Wait until the subscriber-side predicate holds (dispatch is
    async)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# -- subscribers -------------------------------------------------------------


def test_subscriber_receives_events_without_a_sink():
    """subscribe() alone activates telemetry: events AND spans flow to
    the subscriber with no JSONL file configured."""
    assert not telemetry.enabled()
    got = []
    sub = telemetry.subscribe(got.append, name="t-basic")
    try:
        assert telemetry.enabled()
        telemetry.emit(EVENTS.STREAM_COMMIT, row=7)
        with telemetry.span("batch", new_trace=True):
            pass
        assert _drain(sub, lambda: len(got) >= 3)
        names = [r["event"] for r in got]
        assert EVENTS.STREAM_COMMIT in names
        assert EVENTS.SPAN_START in names and EVENTS.SPAN_END in names
        commit = next(r for r in got if r["event"] == EVENTS.STREAM_COMMIT)
        assert commit["row"] == 7 and commit["v"] == telemetry.SCHEMA_VERSION
    finally:
        telemetry.unsubscribe(sub)
    assert not telemetry.enabled()
    # after unsubscribe nothing is delivered and emit is a no-op again
    telemetry.emit(EVENTS.STREAM_COMMIT, row=8)
    assert not any(r.get("row") == 8 for r in got)


def test_slow_subscriber_drops_but_never_blocks_the_emitter():
    """THE acceptance property: a deliberately slow subscriber with a
    tiny queue loses events (counter-visible) while the emitting thread
    stays fast — emit() must never wait on the subscriber."""
    reg = telemetry.registry()
    dropped_before = reg.counter("telemetry.subscriber.dropped")

    def slow(rec):
        time.sleep(0.05)

    sub = telemetry.subscribe(slow, maxsize=4, name="t-slow")
    try:
        n = 500
        t0 = time.perf_counter()
        for i in range(n):
            telemetry.emit(EVENTS.STREAM_COMMIT, row=i)
        emit_wall = time.perf_counter() - t0
        # 500 emits against a subscriber that needs 25 s to drain them:
        # if the emitter ever blocked on the queue this takes seconds.
        # Generous bound for slow CI boxes — blocking would be ~25 s.
        assert emit_wall < 2.0, f"emit path blocked: {emit_wall:.3f}s"
        assert _drain(sub, lambda: sub.stats()["dropped"] > 0)
        st = sub.stats()
        assert st["dropped"] >= n - 4 - st["delivered"] - st["queued"] - 1
        assert (
            reg.counter("telemetry.subscriber.dropped") - dropped_before
            >= st["dropped"] > 0
        )
    finally:
        telemetry.unsubscribe(sub)


def test_subscriber_overflow_reported_as_event(tmp_path):
    """The dispatch thread surfaces accumulated drops as a rate-limited
    telemetry.subscriber.dropped EVENT on the spine (degraded audit)."""
    path = str(tmp_path / "ev.jsonl")
    telemetry.configure(path)
    sub = telemetry.subscribe(
        lambda rec: time.sleep(0.02), maxsize=2, name="t-overflow"
    )
    try:
        for i in range(100):
            telemetry.emit(EVENTS.STREAM_COMMIT, row=i)
        assert _drain(
            sub, lambda: sub.stats()["dropped"] > 0 and
            sub.stats()["delivered"] > 0, timeout=10.0,
        )
        time.sleep(0.3)  # let the dispatch thread file its report
    finally:
        telemetry.unsubscribe(sub)
        telemetry.shutdown()
    evs = [
        e for e in telemetry.read_events(path)
        if e["event"] == EVENTS.TELEMETRY_SUBSCRIBER_DROPPED
    ]
    assert evs, "no overflow event reached the sink"
    assert evs[0]["subscriber"] == "t-overflow"
    assert evs[0]["dropped"] > 0 and evs[0]["dropped_total"] > 0


def test_raising_subscriber_is_counted_and_delivery_continues():
    calls = []

    def bad(rec):
        calls.append(rec)
        raise RuntimeError("observer broke")

    sub = telemetry.subscribe(bad, name="t-raise")
    try:
        telemetry.emit(EVENTS.STREAM_COMMIT, row=1)
        telemetry.emit(EVENTS.STREAM_COMMIT, row=2)
        assert _drain(sub, lambda: sub.stats()["delivered"] >= 2)
        assert sub.stats()["errors"] >= 2
        assert len(calls) == 2  # second event still delivered
    finally:
        telemetry.unsubscribe(sub)


def test_close_detaches_like_unsubscribe():
    """Review regression: ``close()`` must REMOVE the subscription —
    a closed-but-registered subscription would keep ``enabled()`` True
    forever and count a drop on every future emit once its dead queue
    filled."""
    sub = telemetry.subscribe(lambda rec: None, maxsize=2, name="t-close")
    assert telemetry.enabled()
    sub.close()
    assert not telemetry.enabled()
    before = telemetry.registry().counter("telemetry.subscriber.dropped")
    for i in range(10):
        telemetry.emit(EVENTS.STREAM_COMMIT, row=i)
    assert (
        telemetry.registry().counter("telemetry.subscriber.dropped")
        == before
    ), "a closed subscription still received (and dropped) emits"


def test_close_discards_pending_events_quickly():
    """Review regression: close() on a slow subscriber with a full
    queue must discard the backlog (documented), not deliver it — a
    1024-deep queue at 50 ms/event would block close() for ~51 s."""
    sub = telemetry.subscribe(
        lambda rec: time.sleep(0.2), maxsize=64, name="t-discard"
    )
    for i in range(64):
        telemetry.emit(EVENTS.STREAM_COMMIT, row=i)
    t0 = time.perf_counter()
    telemetry.unsubscribe(sub)
    # worst case: one in-flight callback (0.2 s) + one poll interval
    assert time.perf_counter() - t0 < 2.0
    assert sub.stats()["delivered"] < 64


def test_unsubscribe_is_idempotent_and_validates_args():
    sub = telemetry.subscribe(lambda rec: None, name="t-idem")
    telemetry.unsubscribe(sub)
    telemetry.unsubscribe(sub)  # no-op, no raise
    with pytest.raises(TypeError):
        telemetry.subscribe("not-callable")
    with pytest.raises(ValueError):
        telemetry.subscribe(lambda rec: None, maxsize=0)


# -- LiveAggregator ----------------------------------------------------------


def test_live_aggregator_span_windows_and_pruning():
    agg = LiveAggregator(window_s=10.0)
    t0 = 1000.0
    for i in range(5):
        agg({"v": 2, "ts": t0 + i, "event": "span_end",
             "name": "dispatch", "dur_s": 0.1})
    s = agg.snapshot(now=t0 + 5)
    assert s["stages"]["dispatch"]["count"] == 5
    assert s["stages"]["dispatch"]["wall_s"] == pytest.approx(0.5)
    # 11 s later the window has slid past every sample
    s = agg.snapshot(now=t0 + 16)
    assert "dispatch" not in s["stages"]


def test_live_aggregator_queue_depth_survives_a_stalled_consumer():
    """The satellite fix, regression-pinned: deliver events stop (the
    consumer stalled) but the queue signal must NOT go blind — the last
    depth persists into the window mean and ages visibly.  The post-hoc
    report only sees depth AT deliveries; the live window sees it
    BETWEEN them."""
    agg = LiveAggregator(window_s=10.0)
    t0 = 2000.0
    agg({"v": 2, "ts": t0, "event": EVENTS.STREAM_PREFETCH_DELIVER,
         "queue_depth": 0, "capacity": 4})
    agg({"v": 2, "ts": t0 + 1, "event": EVENTS.STREAM_PREFETCH_DELIVER,
         "queue_depth": 4, "capacity": 4})
    # ... then the consumer stalls: no deliveries for 8 seconds
    q = agg.snapshot(now=t0 + 9)["queue"]
    assert q["last"] == 4
    assert q["age_s"] == pytest.approx(8.0)
    assert q["capacity"] == 4
    # depth 0 held 1 s, depth 4 held 8 s over a 9 s signal
    assert q["time_weighted_mean"] == pytest.approx(32 / 9, abs=0.01)
    # an event-count view would say "2 samples, mean 2" — the stall is
    # precisely what it cannot see
    # once the window slides past the old samples the pinned depth still
    # dominates (it persists as the piecewise-constant tail)
    q = agg.snapshot(now=t0 + 12)["queue"]
    assert q["last"] == 4 and q["time_weighted_mean"] == pytest.approx(
        4.0, abs=0.01
    )


def test_live_aggregator_registry_snapshot_renders_gauges():
    agg = LiveAggregator(window_s=10.0)
    now = time.time()
    agg({"v": 2, "ts": now, "event": "span_end", "name": "h2d",
         "dur_s": 0.25})
    agg({"v": 2, "ts": now, "event": EVENTS.STREAM_STAGED_DELIVER,
         "queue_depth": 3, "capacity": 8})
    snap = agg.registry_snapshot(now=now + 1)
    g = snap["gauges"]
    assert g["live.span.h2d.wall_s"]["last"] == pytest.approx(0.25)
    assert g["live.queue.depth"]["last"] == 3
    assert g["live.queue.capacity"]["last"] == 8
    om = telemetry.to_openmetrics(snap)
    assert "rp_live_span_h2d_wall_s" in om and om.endswith("# EOF\n")


# -- histogram quantiles -----------------------------------------------------


def test_quantiles_empty_single_and_one_bucket():
    reg = MetricsRegistry()
    assert reg.hist_quantiles("never") is None
    q = quantiles_from_buckets({}, 0, 0.0)
    assert q["count"] == 0 and q["p50"] is None and q["mean"] is None
    # single sample: EXACT via the sum, whatever its bucket says
    reg.observe("one", 0.0123)
    q = reg.hist_quantiles("one")
    assert q["count"] == 1
    for k in ("p50", "p90", "p99", "p99.9"):
        assert q[k] == pytest.approx(0.0123)
    # all samples in one bucket: every quantile stays inside its edges
    reg2 = MetricsRegistry()
    for _ in range(100):
        reg2.observe("bkt", 0.003)  # bucket [2048, 4096) µs
    q = reg2.hist_quantiles("bkt")
    for k in ("p50", "p90", "p99", "p99.9"):
        assert 0.002048 <= q[k] <= 0.004096
    assert q["sum"] == pytest.approx(0.3) and q["count"] == 100


def test_quantiles_factor_of_two_bound_and_monotone():
    reg = MetricsRegistry()
    rng = np.random.default_rng(0)
    vals = rng.uniform(1e-4, 1e-1, size=2000)
    for v in vals:
        reg.observe("h", float(v))
    q = reg.hist_quantiles("h")
    assert q["count"] == 2000
    assert q["sum"] == pytest.approx(vals.sum(), rel=1e-9)
    prev = 0.0
    for k, pct in (("p50", 50), ("p90", 90), ("p99", 99),
                   ("p99.9", 99.9)):
        true = np.percentile(vals, pct)
        assert q[k] >= prev, "quantiles must be monotone"
        assert true / 2 <= q[k] <= true * 2, (k, q[k], true)
        prev = q[k]


def test_quantiles_monotone_under_concurrent_recording():
    """4 threads hammer one histogram; the final count is exact and the
    extracted quantiles are monotone (snapshot under the registry
    lock)."""
    reg = MetricsRegistry()
    n_per = 500

    def worker(seed):
        rng = np.random.default_rng(seed)
        for v in rng.uniform(1e-5, 1e-1, size=n_per):
            reg.observe("conc", float(v))

    threads = [
        threading.Thread(target=worker, args=(s,), daemon=True)
        for s in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q = reg.hist_quantiles("conc")
    assert q["count"] == 4 * n_per  # no lost updates
    assert q["p50"] <= q["p90"] <= q["p99"] <= q["p99.9"]


def test_quantiles_round_trip_openmetrics_and_http_scrape():
    """Histogram → to_openmetrics quantile summary → real HTTP scrape →
    parse_openmetrics reproduces the extracted values."""
    reg = MetricsRegistry()
    for v in [0.001] * 90 + [0.064] * 10:
        reg.observe("serve.latency.rt", v)
    want = reg.hist_quantiles("serve.latency.rt")
    om = telemetry.to_openmetrics(reg.snapshot())
    assert '# TYPE rp_serve_latency_rt_seconds_quantile summary' in om
    with metrics_server.MetricsServer(
        port=0, sources=[reg.snapshot]
    ) as ms:
        text = metrics_server.fetch_metrics("127.0.0.1", ms.port)
    plain, labeled = metrics_server.parse_openmetrics(text)
    qs = labeled["rp_serve_latency_rt_seconds_quantile"]
    assert qs['quantile="0.5"'] == pytest.approx(want["p50"])
    assert qs['quantile="0.999"'] == pytest.approx(want["p99.9"])
    assert plain["rp_serve_latency_rt_seconds_quantile_count"] == 100
    # the histogram itself rode along, cumulative and EOF-terminated
    assert "rp_serve_latency_rt_seconds_bucket" in labeled
    assert text.endswith("# EOF\n")


# -- metrics endpoint --------------------------------------------------------


def test_metrics_server_serves_404_and_sources_and_close_idempotent():
    reg = MetricsRegistry()
    reg.counter_inc("probe.hits", 3)
    ms = metrics_server.MetricsServer(port=0)
    try:
        import urllib.error
        import urllib.request

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/nope", timeout=5
            )
        assert ei.value.code == 404
        text = metrics_server.fetch_metrics("127.0.0.1", ms.port)
        assert "rp_probe_hits_total" not in text  # not registered yet
        ms.add_source(reg.snapshot)
        text = metrics_server.fetch_metrics("127.0.0.1", ms.port)
        assert "rp_probe_hits_total 3" in text
        ms.remove_source(reg.snapshot)
        text = metrics_server.fetch_metrics("127.0.0.1", ms.port)
        assert "rp_probe_hits_total" not in text
    finally:
        ms.close()
        ms.close()  # idempotent


def test_metrics_server_skips_a_raising_source():
    def broken():
        raise RuntimeError("torn down")

    with metrics_server.MetricsServer(port=0, sources=[broken]) as ms:
        text = metrics_server.fetch_metrics("127.0.0.1", ms.port)
    assert text.endswith("# EOF\n")  # scrape survives the bad source


# -- per-request serving latency ---------------------------------------------


@pytest.fixture(scope="module")
def small_index():
    rng = np.random.default_rng(0)
    return SimHashIndex(
        rng.integers(0, 256, size=(600, 8), dtype=np.uint8)
    )


def test_topk_server_latency_histograms_and_event(small_index, tmp_path):
    path = str(tmp_path / "lat.jsonl")
    telemetry.configure(path)
    rng = np.random.default_rng(1)
    try:
        with TopKServer(
            small_index, 5, max_delay_s=0.001, name="lat-test"
        ) as srv:
            futs = []
            for i in range(12):
                futs.append(srv.submit(
                    rng.integers(0, 256, size=(3, 8), dtype=np.uint8),
                    label=f"tenant-{i % 3}",
                ))
            for f in futs:
                f.result()
            st = srv.stats()
    finally:
        telemetry.shutdown()
    lat = st["latency"]
    assert lat["count"] == 12
    assert lat["p50"] is not None and lat["p50"] <= lat["p99.9"]
    reg = telemetry.registry()
    for t in range(3):
        q = reg.hist_quantiles(f"serve.latency.lat-test.client.tenant-{t}")
        assert q is not None and q["count"] == 4
    qw = reg.hist_quantiles("serve.latency.lat-test.queue_wait")
    assert qw is not None and qw["count"] == 12
    evs = [
        e for e in telemetry.read_events(path)
        if e["event"] == EVENTS.SERVE_LATENCY_REQUEST
    ]
    assert len(evs) == 12
    for e in evs:
        assert e["server"] == "lat-test"
        assert e["label"].startswith("tenant-")
        assert 0 <= e["queue_wait_s"] <= e["total_s"]


def test_labels_are_sanitized_for_metric_names(small_index):
    rng = np.random.default_rng(2)
    with TopKServer(
        small_index, 3, max_delay_s=0.0, name="lat-sane"
    ) as srv:
        srv.query(
            rng.integers(0, 256, size=(2, 8), dtype=np.uint8),
            label='evil {label="x"} \n',
        )
    reg = telemetry.registry()
    hits = [
        k for k in reg.snapshot()["histograms"]
        if k.startswith("serve.latency.lat-sane.client.")
    ]
    assert len(hits) == 1
    assert '"' not in hits[0] and "\n" not in hits[0] and "{" not in hits[0]


def test_sharded_server_uses_its_own_latency_key(small_index):
    from randomprojection_tpu.serving import ShardedTopKServer

    rng = np.random.default_rng(3)
    srv = ShardedTopKServer([small_index], 3, max_delay_s=0.0,
                            name="lat-shard")
    try:
        srv.query(rng.integers(0, 256, size=(2, 8), dtype=np.uint8),
                  label="a")
        st = srv.stats()
    finally:
        srv.close()
    assert st["latency"]["count"] >= 1
    assert telemetry.registry().hist_quantiles(
        "serve.latency.lat-shard.client.a"
    )["count"] == 1


def test_topk_server_rejects_bad_name(small_index):
    with pytest.raises(ValueError):
        TopKServer(small_index, 3, name="", start=False)


# -- doctor: latency section + --live ----------------------------------------


def test_trace_report_latency_and_loadgen_sections(tmp_path):
    from randomprojection_tpu.utils.trace_report import (
        DEGRADED_EVENTS,
        build_report,
        render_report,
    )

    assert EVENTS.TELEMETRY_SUBSCRIBER_DROPPED in DEGRADED_EVENTS
    path = str(tmp_path / "doc.jsonl")
    telemetry.configure(path)
    try:
        for i in range(20):
            telemetry.emit(
                EVENTS.SERVE_LATENCY_REQUEST, server="s1",
                label="a" if i % 2 else "b", rows=4,
                queue_wait_s=0.001, serve_s=0.002,
                total_s=0.004 * (1 + i % 3),
            )
        telemetry.emit(
            EVENTS.LOADGEN_RUN, requests=20, rows=80, rejects=1,
            errors=0, elapsed_s=0.5, max_lag_s=0.0,
            schedule_sha256="abc123",
        )
    finally:
        telemetry.shutdown()
    rep = build_report(path)
    assert set(rep["latency"]) == {"s1", "s1[a]", "s1[b]"}
    assert rep["latency"]["s1"]["count"] == 20
    assert rep["latency"]["s1[a]"]["count"] == 10
    assert rep["latency"]["s1"]["p50"] is not None
    assert rep["loadgen"][0]["schedule_sha256"] == "abc123"
    text = render_report(rep)
    assert "serve latency" in text and "loadgen (open-loop)" in text


def test_doctor_live_polls_a_real_endpoint(capsys):
    telemetry.registry().observe("serve.latency.live-doc", 0.004)
    agg = LiveAggregator()
    agg({"v": 2, "ts": time.time(), "event": "span_end",
         "name": "dispatch", "dur_s": 0.5})
    with metrics_server.MetricsServer(port=0, aggregator=agg) as ms:
        rv = cli.main([
            "doctor", "--live", f"127.0.0.1:{ms.port}",
            "--iterations", "2", "--interval", "0.05",
        ])
        assert rv == 0
        out = capsys.readouterr().out
        assert "live doctor" in out and "poll #2" in out
        assert "dispatch" in out  # the live span window rendered
        # JSON mode: one parseable object per poll
        cli.main([
            "doctor", "--live", f"127.0.0.1:{ms.port}",
            "--iterations", "1", "--json",
        ])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        parsed = json.loads(line)
        assert "metrics" in parsed and "labeled" in parsed


def test_doctor_live_tolerates_transient_scrape_failures(
    monkeypatch, capsys
):
    """Review regression: one timed-out scrape after a healthy first
    poll must NOT kill the dashboard — only a first-poll failure or 5
    consecutive failures abort."""
    calls = {"n": 0}
    real_exposition = telemetry.to_openmetrics(
        telemetry.registry().snapshot()
    )

    def flaky(host, port, timeout=5.0):
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("timed out")
        return real_exposition

    monkeypatch.setattr(metrics_server, "fetch_metrics", flaky)
    rv = cli.main([
        "doctor", "--live", "127.0.0.1:9", "--iterations", "3",
        "--interval", "0.01",
    ])
    assert rv == 0 and calls["n"] == 3
    err = capsys.readouterr().err
    assert "poll #2 failed" in err


def test_loadgen_offered_qps_excludes_drain_time(small_index):
    """Review regression: offered_qps is computed over the SUBMIT
    window, not completion — a slow drain must not make the record
    claim a lighter offered load than the schedule delivered."""
    from randomprojection_tpu.serving import ShardedTopKServer

    srv = ShardedTopKServer([small_index], 4, max_delay_s=0.001,
                            name="lg-offered")
    try:
        sched = loadgen.build_schedule(
            seed=1, duration_s=0.3, rate_qps=60, request_rows=(2,),
            labels=("a",),
        )
        rec = loadgen.run(srv, sched, code_bytes=8, warmup_rows=2)
    finally:
        srv.close()
    assert rec["submit_elapsed_s"] <= rec["elapsed_s"]
    assert rec["offered_qps"] == pytest.approx(
        len(sched) / rec["submit_elapsed_s"], rel=0.05
    )


def test_doctor_live_refuses_bad_target_and_unreachable():
    with pytest.raises(SystemExit):
        cli.main(["doctor", "--live", "nonsense"])
    with pytest.raises(SystemExit):
        cli.main(["doctor", "--live", "127.0.0.1:1", "--iterations", "1"])
    with pytest.raises(SystemExit):
        cli.main(["doctor"])  # neither file nor --live


# -- loadgen -----------------------------------------------------------------


def test_schedule_identical_seed_identical_schedule():
    """THE determinism acceptance pin: same seed+params ⇒ the exact same
    arrival schedule (times, labels, sizes) and digest; different seed ⇒
    different digest."""
    kw = dict(duration_s=3.0, rate_qps=40, arrival="poisson",
              request_rows=(16, 64), labels=("a", "b", "c"))
    s1 = loadgen.build_schedule(seed=42, **kw)
    s2 = loadgen.build_schedule(seed=42, **kw)
    assert s1 == s2
    assert loadgen.schedule_digest(s1) == loadgen.schedule_digest(s2)
    s3 = loadgen.build_schedule(seed=43, **kw)
    assert loadgen.schedule_digest(s3) != loadgen.schedule_digest(s1)
    assert all(0 <= r.t < 3.0 for r in s1)
    assert {r.label for r in s1} <= {"a", "b", "c"}
    assert {r.rows for r in s1} <= {16, 64}


def test_schedule_bursty_confines_arrivals_to_the_on_window():
    s = loadgen.build_schedule(
        seed=5, duration_s=4.0, rate_qps=50, arrival="bursty",
        burst_factor=8.0, burst_fraction=0.125, burst_period_s=1.0,
    )
    # factor*fraction == 1: ALL arrivals inside the 125 ms on-phase
    assert s and all((r.t % 1.0) < 0.125 for r in s)
    # mean rate stays ~rate_qps (Poisson noise around 200 arrivals)
    assert 120 < len(s) < 300


def test_schedule_validation():
    with pytest.raises(ValueError):
        loadgen.build_schedule(seed=0, duration_s=1, rate_qps=10,
                               arrival="diurnal")
    with pytest.raises(ValueError):
        loadgen.build_schedule(seed=0, duration_s=0, rate_qps=10)
    with pytest.raises(ValueError):
        loadgen.build_schedule(seed=0, duration_s=1, rate_qps=10,
                               labels=())
    with pytest.raises(ValueError):
        loadgen.build_schedule(seed=0, duration_s=1, rate_qps=10,
                               request_rows=(0,))
    with pytest.raises(ValueError):
        loadgen.build_schedule(
            seed=0, duration_s=1, rate_qps=10, arrival="bursty",
            burst_factor=10.0, burst_fraction=0.2,
        )


def test_loadgen_run_record_shape(small_index, tmp_path):
    from randomprojection_tpu.serving import ShardedTopKServer

    path = str(tmp_path / "lg.jsonl")
    telemetry.configure(path)
    srv = ShardedTopKServer([small_index], 4, max_delay_s=0.001,
                            name="lg-test")
    try:
        sched = loadgen.build_schedule(
            seed=9, duration_s=0.4, rate_qps=50,
            request_rows=(2, 4), labels=("a", "b"),
        )
        rec = loadgen.run(srv, sched, code_bytes=8, warmup_rows=2)
    finally:
        srv.close()
        telemetry.shutdown()
    assert rec["metric"] == "topk_slo"
    assert rec["requests"] == len(sched)
    assert rec["schedule_sha256"] == loadgen.schedule_digest(sched)
    assert rec["rejects"] == 0 and rec["errors"] == 0
    for table in list(rec["labels"].values()) + [rec["total"]]:
        assert {"count", "rows", "rejects", "p50_ms", "p90_ms",
                "p99_ms", "p99.9_ms", "mean_ms", "max_ms"} <= set(table)
    assert sum(t["count"] for t in rec["labels"].values()) == len(sched)
    assert rec["total"]["count"] == len(sched)
    # quantile tables are exact order statistics: monotone by construction
    for t in rec["labels"].values():
        if t["count"]:
            assert t["p50_ms"] <= t["p90_ms"] <= t["p99_ms"] \
                <= t["p99.9_ms"] <= t["max_ms"]
    runs = [
        e for e in telemetry.read_events(path)
        if e["event"] == EVENTS.LOADGEN_RUN
    ]
    assert len(runs) == 1
    assert runs[0]["schedule_sha256"] == rec["schedule_sha256"]


def test_cli_loadgen_identical_seed_identical_schedule(capsys, tmp_path):
    """Acceptance pin through the REAL CLI: two runs with the identical
    seed commit topk_slo records whose schedule digests match (and carry
    per-label quantile tables); a different seed diverges."""
    out_path = str(tmp_path / "slo.json")
    args = [
        "loadgen", "--index-codes", "256", "--code-bytes", "8",
        "--m", "4", "--rate", "40", "--duration", "0.3",
        "--request-rows", "2,4", "--labels", "x,y", "--shards", "2",
    ]
    digests = []
    for seed in ("7", "7", "8"):
        cli.main(args + ["--seed", seed, "--out", out_path])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(line)
        assert rec["metric"] == "topk_slo"
        assert rec == json.load(open(out_path))
        for t in rec["labels"].values():
            assert {"p50_ms", "p90_ms", "p99_ms", "p99.9_ms"} <= set(t)
        digests.append(rec["schedule_sha256"])
    assert digests[0] == digests[1]
    assert digests[2] != digests[0]


def test_cli_loadgen_rejects_bad_flag_combos():
    with pytest.raises(SystemExit):
        cli.main(["loadgen", "--request-rows", "abc"])
    with pytest.raises(SystemExit):
        cli.main(["loadgen", "--rate", "0.001", "--duration", "0.1"])


# -- live smoke (the make verify / CI gate, in-process) ----------------------


def test_live_smoke_passes(capsys):
    """stream-bench with --metrics-port, scraped over real HTTP
    mid-run: valid OpenMetrics with quantile lines and a nonzero
    span-derived gauge — the end-to-end acceptance path."""
    from randomprojection_tpu.utils import live_smoke

    assert live_smoke.main() == 0
    assert "live-smoke OK" in capsys.readouterr().out
