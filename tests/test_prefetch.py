"""Overlapped-ingest pipeline tests: ``PrefetchSource`` ordering /
cursor-resume / shutdown semantics, multi-threaded hashing determinism,
early-H2D prepared batches, and the per-stage observability (ISSUE r6)."""

import threading

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import GaussianRandomProjection
from randomprojection_tpu.models.sketch import CountSketch, DeviceBatch
from randomprojection_tpu.ops.hashing import hash_threads_override
from randomprojection_tpu.streaming import (
    ArraySource,
    FaultInjectionSource,
    PrefetchSource,
    StreamCursor,
    TokenSource,
    stream_transform,
)
from randomprojection_tpu.utils.observability import StreamStats, batch_nbytes


def prefetch_threads():
    return [
        t for t in threading.enumerate() if t.name.startswith("rp-prefetch")
    ]


@pytest.fixture
def X():
    return np.random.default_rng(0).normal(size=(1000, 128)).astype(np.float32)


def make_est(backend="numpy", k=16):
    return GaussianRandomProjection(
        n_components=k, random_state=0, backend=backend
    )


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("depth", [1, 2, 4])
def test_prefetch_matches_serial(X, backend, depth):
    """Prefetching must change WHEN batches are produced, never their
    order or values."""
    est = make_est(backend).fit(X)
    ref = list(est.transform_stream(ArraySource(X, 128)))
    got = list(
        est.transform_stream(
            PrefetchSource(
                ArraySource(X, 128), depth=depth, prepare=est.prepare_batch
            )
        )
    )
    assert [lo for lo, _ in got] == [lo for lo, _ in ref]
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in got]),
        np.concatenate([y for _, y in ref]),
    )
    assert not prefetch_threads()


def test_prefetch_depth_validation(X):
    with pytest.raises(ValueError, match="depth"):
        PrefetchSource(ArraySource(X, 128), depth=0)


def test_prefetch_fault_resume_bit_identical(X, tmp_path):
    """A worker-thread failure (fault-injected source) must propagate to
    the consumer after the batches produced before it — no hang, no leaked
    thread — and the checkpoint resume must be bit-identical, exactly as
    the serial source behaves."""
    ckpt = str(tmp_path / "cursor.json")
    est = make_est().fit(X)
    Y_ref = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )

    inner = FaultInjectionSource(ArraySource(X, 128), fail_after_batches=3)
    src = PrefetchSource(inner, depth=2)
    got = []
    with pytest.raises(FaultInjectionSource.InjectedFault):
        for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
            got.append((lo, y))
    assert not prefetch_threads(), "worker must be joined after the failure"
    committed = StreamCursor.load(ckpt).rows_done
    assert committed == sum(y.shape[0] for _, y in got)
    assert 0 < committed < 1000

    inner.disarm()
    for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
        assert lo == committed, "resume must continue at the cursor"
        committed += y.shape[0]
        got.append((lo, y))
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in got]), Y_ref
    )
    assert not prefetch_threads()


def test_prefetch_worker_exception_in_prepare_propagates(X):
    """A failure in the prepare step (worker thread) must surface in the
    consumer, not hang the stream."""

    class PrepareBoom(RuntimeError):
        pass

    def bad_prepare(batch):
        raise PrepareBoom("prepare failed")

    est = make_est().fit(X)
    with pytest.raises(PrepareBoom):
        list(
            est.transform_stream(
                PrefetchSource(ArraySource(X, 128), depth=2,
                               prepare=bad_prepare)
            )
        )
    assert not prefetch_threads()


def test_prefetch_consumer_break_joins_worker(X):
    """Abandoning the stream mid-flight (break) must stop and join the
    worker thread — no thread outlives the iteration."""
    est = make_est().fit(X)
    for i, (lo, y) in enumerate(
        est.transform_stream(PrefetchSource(ArraySource(X, 128), depth=2))
    ):
        if i == 1:
            break
    assert not prefetch_threads()


def test_prefetch_consumer_crash_does_not_commit_inflight(X, tmp_path):
    """Ack-after-yield survives prefetching: batches hashed/produced ahead
    by the worker are NOT committed until the consumer has processed them
    — a crash inside the consumer's write leaves the in-flight batch
    uncommitted, so resume re-yields it."""
    ckpt = str(tmp_path / "cursor.json")
    est = make_est().fit(X)
    Y_ref = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )

    class ConsumerCrash(RuntimeError):
        pass

    written = {}
    with pytest.raises(ConsumerCrash):
        for lo, y in est.transform_stream(
            PrefetchSource(ArraySource(X, 128), depth=4),
            checkpoint_path=ckpt,
        ):
            if lo == 256:
                raise ConsumerCrash("crash before persisting this batch")
            written[lo] = y
    assert not prefetch_threads()
    assert StreamCursor.load(ckpt).rows_done == 256, (
        "the worker had prefetched past row 256, but only consumer-acked "
        "batches may commit"
    )
    for lo, y in est.transform_stream(
        PrefetchSource(ArraySource(X, 128), depth=4), checkpoint_path=ckpt
    ):
        written[lo] = y
    np.testing.assert_array_equal(
        np.concatenate([written[lo] for lo in sorted(written)]), Y_ref
    )


def test_prefetch_schema_delegates(X):
    src = PrefetchSource(ArraySource(X, 128), depth=2)
    assert src.schema() == ArraySource(X, 128).schema()
    assert src.batch_rows == 128


def test_hash_threads_bit_identical():
    """The C++ batch hasher must be bit-identical at any worker count
    (token i's outputs depend only on token i).  Uses >= 2^18 tokens so
    the threaded path actually engages (native/murmur3.cpp gate)."""
    from randomprojection_tpu.native.build import load_murmur3
    from randomprojection_tpu.ops.hashing import hash_tokens

    if load_murmur3() is None:  # pragma: no cover - no-compiler envs
        pytest.skip("native murmur3 unavailable; only the serial path exists")
    words = np.asarray([f"tok{i}" for i in range(50_000)])
    toks = words[
        np.random.default_rng(7).integers(0, len(words), size=1 << 18)
    ]
    with hash_threads_override(1):
        idx1, sign1 = hash_tokens(toks, 1 << 20)
    with hash_threads_override(4):
        idx4, sign4 = hash_tokens(toks, 1 << 20)
    np.testing.assert_array_equal(idx1, idx4)
    np.testing.assert_array_equal(sign1, sign4)


def test_hash_threads_override_scoping(monkeypatch):
    """With the explicit-thread ABI the override is THREAD-LOCAL (no env
    mutation — concurrent streams can't leak into each other); a legacy
    .so falls back to a locked env override that always restores."""
    import os

    from randomprojection_tpu.native.build import load_murmur3
    from randomprojection_tpu.ops import hashing as h

    lib = load_murmur3()
    if lib is not None and getattr(lib, "has_explicit_threads", False):
        monkeypatch.setenv("RP_HASH_THREADS", "1")
        with hash_threads_override(3):
            assert os.environ["RP_HASH_THREADS"] == "1", "env must not move"
            assert h._requested_threads(None) == 3
        assert h._requested_threads(None) == 0
        # a sibling thread must not see this thread's override
        seen = {}
        with hash_threads_override(3):
            t = threading.Thread(
                target=lambda: seen.setdefault(
                    "n", h._requested_threads(None)
                )
            )
            t.start()
            t.join()
        assert seen["n"] == 0

    # legacy path (forced): env override, set and restored
    monkeypatch.setattr(h, "_explicit_threads_supported", lambda: False)
    monkeypatch.setenv("RP_HASH_THREADS", "1")
    with hash_threads_override(3):
        assert os.environ["RP_HASH_THREADS"] == "3"
    assert os.environ["RP_HASH_THREADS"] == "1"
    monkeypatch.delenv("RP_HASH_THREADS")
    with hash_threads_override(2):
        assert os.environ["RP_HASH_THREADS"] == "2"
    assert "RP_HASH_THREADS" not in os.environ
    with pytest.raises(ValueError):
        hash_threads_override(0).__enter__()


def test_token_source_hash_threads_param():
    from randomprojection_tpu.ops.hashing import FeatureHasher

    fh = FeatureHasher(1 << 10, input_type="string", dtype=np.float32)

    def read_tokens(lo, hi):
        return (
            np.asarray([f"t{i}" for i in range(lo, hi)]),
            np.arange(0, hi - lo + 1),
        )

    with pytest.raises(ValueError, match="hash_threads"):
        TokenSource(read_tokens, 8, fh, batch_rows=4, hash_threads=0)
    ref = [b for _, b in TokenSource(read_tokens, 8, fh, 4).iter_batches()]
    got = [
        b
        for _, b in TokenSource(
            read_tokens, 8, fh, 4, hash_threads=2
        ).iter_batches()
    ]
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r.toarray(), g.toarray())


def test_countsketch_prepare_batch_device_path():
    """prepare_batch must route exactly like _transform_csr_jax (doc-major
    for low-skew, flat for skewed), return device-resident batches, and
    the dispatched results must match the unprepared path bit-for-bit."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(100, 400)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)
    cs = CountSketch(32, random_state=0, backend="jax").fit_schema(
        *Xs.shape, np.float32
    )
    b = cs.prepare_batch(Xs)
    assert isinstance(b, DeviceBatch) and b.kind == "docmajor"
    assert b.shape == Xs.shape and b.nbytes == batch_nbytes(Xs)
    ref = np.asarray(cs._transform_csr_jax(Xs))
    np.testing.assert_array_equal(
        np.asarray(cs._transform_async(b)), ref
    )

    # a single huge row forces the flat kernel on both paths
    wide = sp.csr_array(
        (
            np.ones(4096, np.float32),
            rng.integers(0, 400, 4096),
            np.asarray([0, 4096] + [4096] * 7),
        ),
        shape=(8, 400),
    )
    bw = cs.prepare_batch(wide)
    assert isinstance(bw, DeviceBatch) and bw.kind == "flat"
    np.testing.assert_array_equal(
        np.asarray(cs._transform_async(bw)),
        np.asarray(cs._transform_csr_jax(wide)),
    )

    # host-path batches pass through unchanged
    assert cs.prepare_batch(Xs.astype(np.float64)) is not b
    assert not isinstance(
        cs.prepare_batch(Xs.astype(np.float64)), DeviceBatch
    )
    cs_np = CountSketch(32, random_state=0, backend="numpy").fit_schema(
        *Xs.shape, np.float32
    )
    assert not isinstance(cs_np.prepare_batch(Xs), DeviceBatch)


def test_countsketch_prefetch_stream_matches_numpy_reference():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(300, 256)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)
    cs = CountSketch(16, random_state=0, backend="jax").fit_schema(
        *Xs.shape, np.float32
    )
    got = np.concatenate(
        [
            np.asarray(y)
            for _, y in stream_transform(
                cs,
                PrefetchSource(
                    ArraySource(Xs, 64), depth=2, prepare=cs.prepare_batch
                ),
            )
        ]
    )
    ref = (
        CountSketch(16, random_state=0, backend="numpy")
        .fit(Xs)
        .transform(Xs.astype(np.float64))
    )
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_jl_prepare_batch_uploads_device_array(X):
    est = make_est("jax").fit(X)
    prepared = est.prepare_batch(X[:128])
    import jax

    assert isinstance(prepared, jax.Array)
    np.testing.assert_array_equal(
        np.asarray(est._transform_async(prepared)),
        np.asarray(est.transform(X[:128])),
    )
    # numpy backend: no-op hook
    est_np = make_est("numpy").fit(X)
    assert est_np.prepare_batch(X[:128]) is X[:128] or isinstance(
        est_np.prepare_batch(X[:128]), np.ndarray
    )


def test_stream_stats_stage_attribution_and_queue_gauge():
    """The token pipeline under PrefetchSource must attribute wall to the
    hash/h2d/dispatch/d2h stages, sample queue occupancy, and report a
    clamped overlap ratio."""
    from randomprojection_tpu.ops.hashing import FeatureHasher

    words = np.asarray([f"w{i}" for i in range(2000)])

    def read_tokens(lo, hi):
        rngs = [np.random.default_rng(500 + i) for i in range(lo, hi)]
        toks = np.concatenate(
            [words[r.integers(0, len(words), size=10)] for r in rngs]
        )
        return toks, np.arange(0, (hi - lo) * 10 + 1, 10)

    fh = FeatureHasher(1 << 14, input_type="string", dtype=np.float32)
    stats = StreamStats()
    source = PrefetchSource(
        TokenSource(
            read_tokens, 128, fh, batch_rows=32, hash_threads=2, stats=stats
        ),
        depth=2, stats=stats,
    )
    cs = CountSketch(16, random_state=0, backend="jax").fit_source(source)
    rows = 0
    for _, y in stream_transform(cs, source, stats=stats):
        rows += y.shape[0]
    assert rows == 128
    assert {"hash", "dispatch", "d2h"} <= set(stats.stage_wall)
    assert all(v >= 0 for v in stats.stage_wall.values())
    assert 0.0 <= stats.overlap_ratio() < 1.0
    # one producer-side occupancy sample per delivered batch
    assert stats.registry.gauge("stream.queue_depth")["n"] == 4
    assert 0 <= stats.queue_depth_max <= 2
    s = stats.summary()
    assert "stage_wall_s" in s and "pipeline_overlap_ratio" in s
    assert s["queue_depth_max"] == stats.queue_depth_max


def test_batch_nbytes_lil_dok_regression():
    """ADVICE r5: LIL's object-dtype .data counted 8 pointer bytes per row
    and DOK counted 0 — both must report a real payload estimate now."""
    dense = np.zeros((64, 32), dtype=np.float32)
    dense[::2, ::4] = 1.5
    lil = sp.lil_array(dense)
    dok = sp.dok_array(dense)
    # COO-equivalent estimate: value + (row, col) intp pair per element
    want = int(dense.astype(bool).sum()) * (
        np.dtype(np.float32).itemsize + 2 * np.dtype(np.intp).itemsize
    )
    assert batch_nbytes(lil) == want
    assert batch_nbytes(dok) == want
    # CSR stays the exact component count, dense the ndarray nbytes
    csr = sp.csr_array(dense)
    assert batch_nbytes(csr) == (
        csr.data.nbytes + csr.indices.nbytes + csr.indptr.nbytes
    )
    assert batch_nbytes(dense) == dense.nbytes
