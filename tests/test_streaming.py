"""Streaming-layer tests: batching, checkpoint/resume, fault injection
(SURVEY.md §6 failure detection / §8 step 5)."""

import os

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import GaussianRandomProjection, SparseRandomProjection
from randomprojection_tpu.streaming import (
    ArraySource,
    CallableSource,
    FaultInjectionSource,
    StreamCursor,
    stream_transform,
)


def make_est(backend="numpy", k=16, **kw):
    return GaussianRandomProjection(
        n_components=k, random_state=0, backend=backend, **kw
    )


@pytest.fixture
def X():
    return np.random.default_rng(0).normal(size=(1000, 128)).astype(np.float32)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("batch_rows", [128, 256, 1000])
def test_stream_matches_oneshot(X, backend, batch_rows):
    est = make_est(backend).fit_source(ArraySource(X, batch_rows))
    Y_full = np.asarray(est.transform(X))
    chunks = list(est.transform_stream(ArraySource(X, batch_rows)))
    assert [lo for lo, _ in chunks] == list(range(0, 1000, batch_rows))
    Y_stream = np.concatenate([y for _, y in chunks])
    np.testing.assert_array_equal(Y_stream, Y_full)


def test_stream_batch_size_invariance(X):
    """The projection must not depend on how the stream is chopped."""
    est = make_est("jax").fit(X)
    ys = {
        b: np.concatenate([y for _, y in est.transform_stream(ArraySource(X, b))])
        for b in (100, 250, 1000)
    }
    np.testing.assert_array_equal(ys[100], ys[250])
    np.testing.assert_array_equal(ys[100], ys[1000])


def test_callable_source_out_of_core(X):
    reads = []

    def read(lo, hi):
        reads.append((lo, hi))
        return X[lo:hi]

    src = CallableSource(read, n_rows=1000, n_features=128, dtype=X.dtype,
                         batch_rows=300)
    est = make_est().fit_source(src)
    Y = np.concatenate([y for _, y in est.transform_stream(src)])
    np.testing.assert_array_equal(Y, np.asarray(est.transform(X)))
    assert reads == [(0, 300), (300, 600), (600, 900), (900, 1000)]


def test_fit_source_touches_no_rows():
    def read(lo, hi):
        raise AssertionError("fit must not read rows")

    src = CallableSource(read, n_rows=500, n_features=64, batch_rows=100)
    est = make_est().fit_source(src)
    assert est.n_components_ == 16 and est.n_features_in_ == 64


def test_cursor_roundtrip(tmp_path):
    p = str(tmp_path / "cursor.json")
    StreamCursor(rows_done=768).save(p)
    assert StreamCursor.load(p).rows_done == 768


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_fault_injection_resume_bit_identical(X, backend, tmp_path):
    """Crash mid-stream, resume from the checkpoint → bit-identical output."""
    ckpt = str(tmp_path / "cursor.json")
    est = make_est(backend).fit(X)
    Y_ref = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )

    src = FaultInjectionSource(ArraySource(X, 128), fail_after_batches=3)
    got = []
    with pytest.raises(FaultInjectionSource.InjectedFault):
        for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
            got.append((lo, y))
    committed_rows = StreamCursor.load(ckpt).rows_done
    assert committed_rows == sum(y.shape[0] for _, y in got)
    assert 0 < committed_rows < 1000

    src.disarm()
    for lo, y in est.transform_stream(src, checkpoint_path=ckpt):
        assert lo == committed_rows, "resume must continue at the cursor"
        committed_rows += y.shape[0]
        got.append((lo, y))

    Y_resumed = np.concatenate([y for _, y in got])
    np.testing.assert_array_equal(Y_resumed, Y_ref)


def test_consumer_crash_does_not_commit_inflight_batch(X, tmp_path):
    """The canonical usage writes output AFTER the yield; a crash inside the
    consumer's write must leave the in-flight batch uncommitted so resume
    re-yields it (the cursor may never claim rows the consumer didn't see
    through)."""
    ckpt = str(tmp_path / "cursor.json")
    est = make_est().fit(X)
    Y_ref = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )

    class ConsumerCrash(RuntimeError):
        pass

    written = {}
    with pytest.raises(ConsumerCrash):
        for lo, y in est.transform_stream(
            ArraySource(X, 128), checkpoint_path=ckpt
        ):
            if lo == 256:
                raise ConsumerCrash("crash before persisting this batch")
            written[lo] = y  # the durable write
    assert StreamCursor.load(ckpt).rows_done == 256, (
        "batch [256, 384) was yielded but never persisted by the consumer; "
        "it must not be committed"
    )

    for lo, y in est.transform_stream(ArraySource(X, 128), checkpoint_path=ckpt):
        written[lo] = y
    Y = np.concatenate([written[lo] for lo in sorted(written)])
    np.testing.assert_array_equal(Y, Y_ref)


def test_stream_to_memmap_crash_resume(X, tmp_path):
    """Library-level durable memmap streaming: crash mid-run (injected
    fault), resume into the same file, result identical to one-shot."""
    from randomprojection_tpu.streaming import stream_to_memmap

    est = make_est().fit(X)
    Y_ref = np.asarray(est.transform(X))
    out_path = str(tmp_path / "y.npy")
    ckpt = str(tmp_path / "c.json")

    src = FaultInjectionSource(ArraySource(X, 128), fail_after_batches=3)
    with pytest.raises(FaultInjectionSource.InjectedFault):
        stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)
    committed = StreamCursor.load(ckpt).rows_done
    assert 0 < committed < 1000
    # committed rows are durable on disk already
    partial = np.lib.format.open_memmap(out_path, mode="r")
    np.testing.assert_array_equal(partial[:committed], Y_ref[:committed])
    del partial

    src.disarm()
    out = stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)
    np.testing.assert_array_equal(np.asarray(out), Y_ref)
    # completed rerun: no-op, same contents
    out2 = stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)
    np.testing.assert_array_equal(np.asarray(out2), Y_ref)

    # a resume whose memmap vanished is refused
    StreamCursor(rows_done=128).save(ckpt)
    os.remove(out_path)
    with pytest.raises(ValueError, match="does not exist"):
        stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)


def test_stream_sparse_input_sparse_output():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 96))
    X[X < 1.0] = 0
    Xs = sp.csr_array(X)
    est = SparseRandomProjection(
        n_components=8, random_state=0, backend="numpy", dense_output=False
    ).fit(Xs)
    chunks = [y for _, y in est.transform_stream(ArraySource(Xs, 150))]
    assert all(sp.issparse(y) for y in chunks)
    ref = est.transform(Xs)
    np.testing.assert_allclose(
        sp.vstack(chunks).toarray(), ref.toarray(), rtol=1e-12
    )


def test_transform_stream_requires_fit(X):
    from randomprojection_tpu import NotFittedError

    with pytest.raises(NotFittedError):
        list(make_est().transform_stream(ArraySource(X, 100)))


def test_misaligned_resume_rejected(X):
    est = make_est().fit(X)
    src = ArraySource(X, 128)
    with pytest.raises(ValueError, match="multiple of batch_rows"):
        list(stream_transform(est, src, cursor=StreamCursor(rows_done=100)))


def test_rerun_of_completed_stream_yields_nothing(X, tmp_path):
    """A finished stream's cursor is n_rows (not a batch multiple when the
    tail is ragged); re-running with it must be a clean no-op."""
    ckpt = str(tmp_path / "cur.json")
    est = make_est().fit(X)
    src = ArraySource(X, 128)  # 1000 % 128 != 0 → ragged tail
    n = sum(y.shape[0] for _, y in est.transform_stream(src, checkpoint_path=ckpt))
    assert n == 1000
    assert StreamCursor.load(ckpt).rows_done == 1000
    assert list(est.transform_stream(src, checkpoint_path=ckpt)) == []


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_stream_sign_rp_yields_packed_codes(X, backend):
    """Streaming must route through SignRandomProjection's override: packed
    uint8 codes, identical to the one-shot transform."""
    from randomprojection_tpu import SignRandomProjection

    est = SignRandomProjection(
        n_components=64, random_state=0, backend=backend
    ).fit(X)
    C_ref = np.asarray(est.transform(X))
    chunks = [y for _, y in est.transform_stream(ArraySource(X, 256))]
    assert all(y.dtype == np.uint8 for y in chunks)
    np.testing.assert_array_equal(np.concatenate(chunks), C_ref)


def test_stream_countsketch(X):
    from randomprojection_tpu import CountSketch

    cs = CountSketch(32, random_state=0, backend="numpy").fit_source(
        ArraySource(X, 256)
    )
    Y_ref = cs.transform(X)
    chunks = [y for _, y in cs.transform_stream(ArraySource(X, 256))]
    np.testing.assert_allclose(np.concatenate(chunks), Y_ref, rtol=1e-6)


def test_memmap_resume_rejects_different_estimator_shape(tmp_path):
    """Resuming into a memmap written by a different-width/dtype estimator
    must refuse at the library level (ADVICE r2: it used to fail only as a
    broadcast error mid-write — or silently mix projections when shapes
    happened to match)."""
    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.streaming import (
        ArraySource,
        StreamCursor,
        stream_to_memmap,
    )

    X = np.random.default_rng(0).normal(size=(300, 64)).astype(np.float32)
    src = ArraySource(X, batch_rows=100)
    out_path = str(tmp_path / "y.npy")
    ckpt = str(tmp_path / "cur.json")
    est16 = GaussianRandomProjection(16, random_state=0, backend="numpy").fit(X)
    stream_to_memmap(est16, src, out_path, checkpoint_path=ckpt)

    # rewind the cursor, then try to resume with a different estimator
    StreamCursor(rows_done=100).save(ckpt)
    est8 = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    with pytest.raises(ValueError, match="mix two projections"):
        stream_to_memmap(est8, src, out_path, checkpoint_path=ckpt)

    # same width, different dtype: also refused
    est16_64 = GaussianRandomProjection(16, random_state=1, backend="numpy").fit(
        X.astype(np.float64)
    )
    with pytest.raises(ValueError, match="mix two projections"):
        stream_to_memmap(est16_64, src, out_path, checkpoint_path=ckpt)


def test_memmap_resume_bf16(tmp_path):
    """bf16 streams write .npy files whose header degrades to raw void
    ('|V2'); resume must restore the typed view and produce bit-identical
    output, not refuse (same-width different-dtype estimators still
    refuse)."""
    import ml_dtypes

    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.streaming import (
        ArraySource,
        StreamCursor,
        stream_to_memmap,
    )

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X = np.random.default_rng(0).normal(size=(300, 64)).astype(bf16)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    src = ArraySource(X, 100)
    out_path = str(tmp_path / "y.npy")
    ckpt = str(tmp_path / "c.json")
    ref = np.asarray(
        stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)
    ).copy()
    assert ref.dtype == bf16

    StreamCursor(rows_done=100).save(ckpt)
    out = stream_to_memmap(est, src, out_path, checkpoint_path=ckpt)
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint16), ref.view(np.uint16)
    )

    # an f32 estimator of the same width must still refuse (4-byte vs
    # 2-byte itemsize; genuinely different projection)
    StreamCursor(rows_done=100).save(ckpt)
    est32 = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(
        np.asarray(X, dtype=np.float32)
    )
    with pytest.raises(ValueError, match="mix two projections"):
        stream_to_memmap(est32, src, out_path, checkpoint_path=ckpt)


def test_token_source_end_to_end_pipeline(tmp_path):
    """Config-5 pipeline: raw tokens -> murmur3 CSR -> CountSketch, one
    stream with checkpoint/resume.  The streamed sketch must equal the
    all-at-once hash+sketch, and a crash/resume must be bit-identical."""
    from randomprojection_tpu.models.sketch import CountSketch
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.streaming import TokenSource, stream_to_array

    n_docs, tok_per_doc = 257, 20
    words = np.asarray([f"w{i}" for i in range(5000)])

    def read_tokens(lo, hi):
        # deterministic in (lo, hi): each doc's tokens derive from its id
        rngs = [np.random.default_rng(1000 + i) for i in range(lo, hi)]
        toks = np.concatenate(
            [words[r.integers(0, len(words), size=tok_per_doc)] for r in rngs]
        )
        indptr = np.arange(0, (hi - lo) * tok_per_doc + 1, tok_per_doc)
        return toks, indptr

    hasher = FeatureHasher(1 << 16, input_type="string", dtype=np.float32)
    source = TokenSource(read_tokens, n_docs, hasher, batch_rows=64)
    cs = CountSketch(32, random_state=0, backend="jax").fit_source(source)
    assert cs.n_features_in_ == 1 << 16
    Y = stream_to_array(cs, source)
    assert Y.shape == (n_docs, 32) and Y.dtype == np.float32

    toks, indptr = read_tokens(0, n_docs)
    ref = cs.transform(hasher.transform_tokens(toks, indptr))
    np.testing.assert_allclose(Y, ref, rtol=2e-5, atol=2e-5)

    # crash after 2 batches, resume from cursor: bit-identical
    ckpt = str(tmp_path / "cursor.json")
    src_fail = FaultInjectionSource(
        TokenSource(read_tokens, n_docs, hasher, batch_rows=64), 2
    )
    got = []
    with pytest.raises(FaultInjectionSource.InjectedFault):
        for lo, y in stream_transform(cs, src_fail, checkpoint_path=ckpt):
            got.append((lo, y))
    committed = StreamCursor.load(ckpt).rows_done
    assert committed == sum(y.shape[0] for _, y in got)
    src_fail.disarm()
    for lo, y in stream_transform(cs, src_fail, checkpoint_path=ckpt):
        assert lo == committed, "resume must continue at the cursor"
        committed += y.shape[0]
        got.append((lo, y))
    Y2 = np.concatenate([y for _, y in got])
    np.testing.assert_array_equal(Y2, Y)


def test_token_source_validation_and_values():
    """A reader returning mis-shaped batches must fail loudly (a silent
    local/global indptr mix-up would mis-assign rows); weighted tokens
    (TF-IDF values) flow through to the CSR."""
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.streaming import TokenSource

    fh = FeatureHasher(1 << 10, input_type="string", dtype=np.float32)

    def bad_reader(lo, hi):
        # GLOBAL indptr (the classic mistake): indptr[0] == lo != 0, so
        # transform_tokens refuses (only bit-identical to a local indptr
        # for the lo == 0 batch — hence n_rows > batch_rows here)
        toks = np.asarray(["a"] * (hi - lo))
        return toks, np.arange(lo, hi + 1)

    src = TokenSource(bad_reader, 8, fh, batch_rows=4)
    with pytest.raises(ValueError):
        list(src.iter_batches())

    def weighted_reader(lo, hi):
        toks = np.asarray(["w"] * (hi - lo))
        indptr = np.arange(0, hi - lo + 1)
        values = np.full(hi - lo, 2.5)
        return toks, indptr, values

    src = TokenSource(weighted_reader, 4, fh, batch_rows=4)
    (lo, batch), = src.iter_batches()
    assert batch.shape == (4, 1 << 10) and batch.dtype == np.float32
    assert set(np.abs(batch.data)) == {2.5}  # weights survived hashing


def test_stream_stats_counts_sparse_input_bytes():
    """ADVICE r4: scipy CSR has no ``.nbytes``, so the old
    ``getattr(batch, 'nbytes', 0)`` recorded ``bytes_in=0`` for every
    sparse stream; the payload is data+indices+indptr."""
    from randomprojection_tpu.models.sketch import CountSketch
    from randomprojection_tpu.streaming import RowBatchSource
    from randomprojection_tpu.utils.observability import (
        StreamStats,
        batch_nbytes as _batch_nbytes,
    )

    rng = np.random.default_rng(0)
    X = sp.random(64, 128, density=0.1, random_state=0,
                  dtype=np.float32, format="csr")
    assert _batch_nbytes(X) == (
        X.data.nbytes + X.indices.nbytes + X.indptr.nbytes
    )
    coo = X.tocoo()
    assert _batch_nbytes(coo) >= coo.data.nbytes + 2 * coo.row.nbytes
    dense = rng.normal(size=(4, 4)).astype(np.float32)
    assert _batch_nbytes(dense) == dense.nbytes

    class CsrSource(RowBatchSource):
        def schema(self):
            return X.shape[0], X.shape[1], X.dtype

        def iter_batches(self, start_row=0):
            for lo in range(start_row, X.shape[0], 32):
                yield lo, X[lo : lo + 32]

    cs = CountSketch(16, random_state=0, backend="jax").fit_schema(
        *X.shape, np.float32
    )
    stats = StreamStats()
    for _ in stream_transform(cs, CsrSource(), stats=stats):
        pass
    assert stats.bytes_in > 0
    assert stats.rows == X.shape[0]


def test_countsketch_stream_through_docmajor_kernel(monkeypatch):
    """The streaming lazy-handle path must route through the doc-major
    compare-reduce kernel when eligible (r5) and still commit correct,
    in-order batches."""
    from randomprojection_tpu.models.sketch import CountSketch

    monkeypatch.setattr(CountSketch, "_DOCMAJOR_MAX_INFLATION", 1e9)
    rng = np.random.default_rng(30)
    X = rng.normal(size=(200, 400)).astype(np.float32)
    X[np.abs(X) < 1.0] = 0.0
    Xs = sp.csr_array(X)

    cs = CountSketch(32, random_state=0, backend="jax").fit_schema(
        *Xs.shape, np.float32
    )
    got = []
    for lo, y in stream_transform(cs, ArraySource(Xs, 64)):
        got.append((lo, np.asarray(y)))
    assert [lo for lo, _ in got] == [0, 64, 128, 192]
    assert any(k[0] == "docmajor" for k in cs._csr_fns), list(cs._csr_fns)
    Y = np.concatenate([y for _, y in got])
    ref = CountSketch(32, random_state=0, backend="numpy").fit(Xs).transform(
        Xs.astype(np.float64)
    )
    np.testing.assert_allclose(Y, ref, rtol=2e-5, atol=2e-5)


def test_batch_rows_helper_tolerates_prepared_operands():
    """ISSUE r9 satellite: the stream.dispatch rows field must survive
    prepared operands without a plain ``.shape`` (DeviceBatch-style
    carriers expose ``.n``), odd shapes, and unknown objects."""
    from randomprojection_tpu.streaming import _batch_rows

    assert _batch_rows(np.zeros((7, 3))) == 7

    class Carrier:  # DeviceBatch-style: .n, no .shape
        n = 42

    assert _batch_rows(Carrier()) == 42

    class ZeroD:  # 0-d shape: shape[0] raises IndexError
        shape = ()
        n = 5

    assert _batch_rows(ZeroD()) == 5

    class Opaque:
        pass

    assert _batch_rows(Opaque()) is None
    assert _batch_rows(Opaque(), 0) == 0

    class BadN:  # non-integral .n must not be trusted
        n = "nope"

    assert _batch_rows(BadN()) is None


def test_stream_dispatch_rows_truthful_for_shapeless_prepared_batch(
    X, tmp_path
):
    """A prepare hook that replaces batches with a shape-less carrier must
    not crash the stream or fake the telemetry row counts: stream.dispatch
    events and cursor commits keep the true per-batch rows (the doctor
    treats both as truth)."""
    from randomprojection_tpu.streaming import PrefetchSource
    from randomprojection_tpu.utils import telemetry

    class Carrier:
        __slots__ = ("arr", "n", "nbytes")

        def __init__(self, arr):
            self.arr = arr
            self.n = arr.shape[0]
            self.nbytes = arr.nbytes

    class StubEst:
        def _check_is_fitted(self):
            pass

        def _stream_out_dtype(self):
            return None

        def _stream_out_width(self):
            return X.shape[1]

        def _transform_async(self, b):
            assert isinstance(b, Carrier), "prepared carrier must arrive"
            return b.arr * 2.0

    path = str(tmp_path / "events.jsonl")
    ckpt = str(tmp_path / "cursor.json")
    telemetry.configure(path)
    try:
        got = list(
            stream_transform(
                StubEst(),
                PrefetchSource(
                    ArraySource(X, 128), depth=2, prepare=Carrier
                ),
                checkpoint_path=ckpt,
            )
        )
    finally:
        telemetry.shutdown()
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in got]), X * 2.0
    )
    assert StreamCursor.load(ckpt).rows_done == X.shape[0]
    dispatches = [
        e for e in telemetry.read_events(path)
        if e["event"] == "stream.dispatch"
    ]
    assert len(dispatches) == 8
    assert [e["rows"] for e in dispatches] == [128] * 7 + [104]
