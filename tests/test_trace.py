"""Tracing spans + run doctor (ISSUE r8): span API, cross-thread
propagation through ``PrefetchSource``, the critical-path report on
clean AND torn/orphaned files, the ``cli doctor`` end-to-end contract,
the OpenMetrics exposition, schema v1/v2 compatibility, and
teardown-safety of ``emit``/spans."""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import (
    parse_event,
    read_events,
    to_openmetrics,
)
from randomprojection_tpu.utils.trace_report import build_report, render_report

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _no_global_sink():
    yield
    telemetry.shutdown()


# -- span API ----------------------------------------------------------------


def test_span_pairing_nesting_and_ids(tmp_path):
    p = str(tmp_path / "t.jsonl")
    telemetry.configure(p)
    with telemetry.span("batch", new_trace=True, row=7) as root:
        assert telemetry.current_span() is root
        with telemetry.span("hash") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    assert telemetry.current_span() is None
    telemetry.shutdown()
    evs = list(read_events(p))
    assert [e["event"] for e in evs] == [
        "span_start", "span_start", "span_end", "span_end",
    ]
    assert all(e["v"] == telemetry.SCHEMA_VERSION for e in evs)
    start_root, start_child, end_child, end_root = evs
    assert start_root["parent_id"] is None
    assert start_root["trace_id"] == start_root["span_id"]
    assert start_root["row"] == 7
    assert start_child["parent_id"] == start_root["span_id"]
    assert end_child["span_id"] == start_child["span_id"]
    assert end_child["dur_s"] >= 0
    assert end_root["name"] == "batch"


def test_span_noop_without_sink():
    telemetry.shutdown()
    assert telemetry.start_span("x") is None
    telemetry.end_span(None)  # must not raise
    with telemetry.span("y") as s:
        assert s is None
    assert telemetry.trace_fields() == {}


def test_span_require_parent(tmp_path):
    """Instrumented stages must not open orphan traces when no batch
    trace is active — and must nest when one is."""
    p = str(tmp_path / "t.jsonl")
    telemetry.configure(p)
    with telemetry.span("dispatch", require_parent=True) as s:
        assert s is None  # no parent in scope: skipped entirely
    with telemetry.span("batch", new_trace=True) as root:
        with telemetry.span("dispatch", require_parent=True) as s:
            assert s is not None and s.parent_id == root.span_id
    telemetry.shutdown()
    starts = [e for e in read_events(p) if e["event"] == "span_start"]
    assert [e["name"] for e in starts] == ["batch", "dispatch"]


def test_activate_span_cross_thread_adoption(tmp_path):
    """The explicit propagation primitive: a root created on one thread,
    adopted on another — the child parents to the foreign root."""
    import threading

    p = str(tmp_path / "t.jsonl")
    telemetry.configure(p)
    root = telemetry.start_span("batch", new_trace=True)

    def consumer():
        with telemetry.activate_span(root):
            with telemetry.span("d2h"):
                pass
        assert telemetry.current_span() is None

    t = threading.Thread(target=consumer)
    t.start()
    t.join()
    telemetry.end_span(root, row=0)
    telemetry.shutdown()
    starts = {e["name"]: e for e in read_events(p)
              if e["event"] == "span_start"}
    assert starts["d2h"]["parent_id"] == root.span_id
    assert starts["d2h"]["trace_id"] == root.trace_id


# -- schema compatibility (satellite) ----------------------------------------

# FROZEN v1 fixture line — byte-for-byte what an r7 TelemetryLog wrote.
# Do not regenerate from code: the point is that committed v1 files keep
# parsing after the v2 (span) bump.
_V1_FIXTURE = (
    '{"v":1,"ts":1722700000.123456,"event":"stream.commit",'
    '"row":4096,"rows":4096,"bytes_in":1048576,"bytes_out":262144}'
)


def test_v1_fixture_line_still_parses():
    rec = parse_event(_V1_FIXTURE)
    assert rec["v"] == 1 and rec["event"] == "stream.commit"
    assert rec["rows"] == 4096


def test_v1_and_v2_lines_coexist_in_one_file(tmp_path):
    """A file a v1 run appended to and a v2 run continued must read end
    to end — the real multi-run telemetry-file shape."""
    p = tmp_path / "mixed.jsonl"
    p.write_text(_V1_FIXTURE + "\n")
    telemetry.configure(str(p))
    with telemetry.span("batch", new_trace=True):
        pass
    telemetry.emit("stream.commit", row=0, rows=1)
    telemetry.shutdown()
    evs = list(read_events(str(p)))
    assert [e["v"] for e in evs] == [1, 2, 2, 2]
    assert evs[0]["event"] == "stream.commit"
    assert {e["event"] for e in evs[1:]} == {
        "span_start", "span_end", "stream.commit",
    }


def test_unsupported_version_still_rejected():
    with pytest.raises(ValueError, match="version"):
        parse_event(json.dumps({"v": 3, "ts": 0.0, "event": "x"}))


# -- propagation through PrefetchSource (satellite) --------------------------


def _run_token_pipeline(tel_path, n_docs=96, batch_rows=32):
    from randomprojection_tpu.models.sketch import CountSketch
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.streaming import (
        PrefetchSource,
        TokenSource,
        stream_transform,
    )
    from randomprojection_tpu.utils.observability import StreamStats

    telemetry.configure(tel_path)
    words = np.asarray([f"w{i}" for i in range(500)])

    def read_tokens(lo, hi):
        rng = np.random.default_rng(lo + 1)
        toks = words[rng.integers(0, len(words), size=(hi - lo) * 8)]
        return toks, np.arange(0, (hi - lo) * 8 + 1, 8)

    fh = FeatureHasher(1 << 12, input_type="string", dtype=np.float32)
    stats = StreamStats()
    source = PrefetchSource(
        TokenSource(read_tokens, n_docs, fh, batch_rows=batch_rows,
                    stats=stats),
        depth=2, stats=stats,
    )
    cs = CountSketch(16, random_state=0, backend="numpy").fit_source(source)
    rows = sum(
        y.shape[0] for _, y in stream_transform(cs, source, stats=stats)
    )
    telemetry.shutdown()
    assert rows == n_docs
    return stats


def test_prefetch_span_propagation_and_no_leakage(tmp_path):
    """Every batch gets ONE trace whose children cover the producer-side
    stages (hash on the worker thread, enqueue-wait) AND the consumer-
    side stages (dispatch, d2h) — correct parent linkage across the
    thread boundary, and no child ever lands in another batch's trace."""
    tel = str(tmp_path / "ev.jsonl")
    _run_token_pipeline(tel)
    evs = list(read_events(tel))
    starts = {e["span_id"]: e for e in evs if e["event"] == "span_start"}
    ends = {e["span_id"]: e for e in evs if e["event"] == "span_end"}
    assert set(starts) == set(ends), "clean run must orphan no spans"

    roots = [e for e in starts.values() if e["parent_id"] is None]
    assert all(e["name"] == "batch" for e in roots)
    committed = [
        ends[r["span_id"]] for r in roots
        if "row" in ends[r["span_id"]]
    ]
    assert len(committed) == 3  # 96 docs / 32 per batch
    assert sorted(e["row"] for e in committed) == [0, 32, 64]

    # per-trace child sets: production + queue + consumer stages, each
    # parented to THAT trace's root
    by_trace = {}
    for e in starts.values():
        if e["parent_id"] is not None:
            assert starts[e["parent_id"]]["name"] == "batch"
            assert starts[e["parent_id"]]["trace_id"] == e["trace_id"]
            by_trace.setdefault(e["trace_id"], []).append(e["name"])
    committed_traces = {e["trace_id"] for e in committed}
    assert set(by_trace) == committed_traces
    for names in by_trace.values():
        assert set(names) == {"hash", "enqueue_wait", "dispatch", "d2h"}
        assert len(names) == 4, "exactly one span per stage per batch"

    # cross-batch leakage check via the flat events: the commit/dispatch
    # events carry their trace id, and the row they record must match the
    # row the trace's ROOT committed
    root_rows = {e["trace_id"]: e["row"] for e in committed}
    for e in evs:
        if e["event"] in ("stream.commit", "stream.dispatch") \
                and "trace_id" in e:
            assert root_rows[e["trace_id"]] == e["row"]
    # hash batches correlate with the trace that hashed them
    hash_evs = [e for e in evs if e["event"] == "hash.batch"]
    assert all("trace_id" in e for e in hash_evs)
    assert {e["trace_id"] for e in hash_evs} <= set(root_rows) | {
        r["trace_id"] for r in roots
    }


def test_report_on_clean_run_sums_to_batch_wall(tmp_path):
    tel = str(tmp_path / "ev.jsonl")
    stats = _run_token_pipeline(tel)
    report = build_report(tel)
    assert report["traces"]["batches"] == 3
    assert report["spans"]["orphan_starts"] == 0
    stages = report["batch"]["stages"]
    assert {"hash", "dispatch", "d2h"} <= set(stages)
    total_pct = sum(d["pct"] for d in stages.values())
    total_pct += report["batch"]["bubble"]["pct"]
    assert total_pct == pytest.approx(100.0, abs=0.5)
    # stage walls in the report agree with StreamStats' own attribution
    # (same regions, measured independently) to within clock noise
    for name in ("hash", "dispatch", "d2h"):
        assert stages[name]["wall_s"] == pytest.approx(
            stats.stage_wall[name], rel=0.5, abs=0.05
        )
    assert 0.0 <= report["pipeline"]["overlap_ratio_est"] < 1.0
    assert report["queue_depth"]["samples"] == 3
    assert report["degraded"]["backend.vmem_oom_retry"] == 0
    # renders without error and names every section
    text = render_report(report)
    assert "critical path" in text and "degraded-event audit" in text


def test_report_tolerates_torn_tail_and_orphans(tmp_path):
    """The doctor must work on the file a CRASHED run left behind: a torn
    final line plus span_starts whose ends never made it."""
    tel = str(tmp_path / "ev.jsonl")
    _run_token_pipeline(tel)
    raw = open(tel).read().rstrip("\n").splitlines()
    # a batch that died mid-flight: start with no end, two of them
    orphan1 = json.dumps({
        "v": 2, "ts": 9e9, "event": "span_start", "name": "batch",
        "trace_id": "dead-1", "span_id": "dead-1", "parent_id": None,
    })
    orphan2 = json.dumps({
        "v": 2, "ts": 9e9, "event": "span_start", "name": "hash",
        "trace_id": "dead-1", "span_id": "dead-2", "parent_id": "dead-1",
    })
    torn = raw[-1][: len(raw[-1]) // 2]  # crash mid-write of the last event
    open(tel, "w").write("\n".join(raw[:-1] + [orphan1, orphan2, torn]))
    report = build_report(tel)
    # 2 injected orphans + the span whose end was on the torn final line
    # (a clean run's last event is the final batch root's span_end)
    assert report["spans"]["orphan_starts"] == 3
    # the healthy batches still attribute; percentages still close
    assert report["traces"]["batches"] == 2
    total = sum(d["pct"] for d in report["batch"]["stages"].values())
    total += report["batch"]["bubble"]["pct"]
    assert total == pytest.approx(100.0, abs=0.5)
    text = render_report(report)
    assert "orphaned span" in text


def test_clean_break_leaves_no_orphans_and_healthy_runs_no_incomplete(
    tmp_path,
):
    """A consumer `break` is a deliberate abandon, not a crash: every
    in-flight trace (mid-yield, pending, queued ahead by the worker) is
    CLOSED as abandoned — the doctor must not show orphaned spans for
    it.  And a fully-healthy run reports zero incomplete traces (the
    end-of-stream production probe is counted as `empty`, separately)."""
    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.streaming import (
        ArraySource,
        PrefetchSource,
        stream_transform,
    )

    X = np.random.default_rng(0).normal(size=(1000, 128)).astype(np.float32)
    est = GaussianRandomProjection(16, random_state=0, backend="numpy").fit(X)

    tel = str(tmp_path / "break.jsonl")
    telemetry.configure(tel)
    for i, _ in enumerate(
        stream_transform(est, PrefetchSource(ArraySource(X, 128), depth=4))
    ):
        if i == 1:
            break
    telemetry.shutdown()
    r = build_report(tel)
    assert r["spans"]["orphan_starts"] == 0
    assert r["traces"]["batches"] >= 1
    assert r["traces"]["incomplete"] >= 1  # the abandoned in-flight batches

    tel2 = str(tmp_path / "healthy.jsonl")
    telemetry.configure(tel2)
    for _ in stream_transform(est, ArraySource(X, 128)):
        pass
    telemetry.shutdown()
    r2 = build_report(tel2)
    assert r2["traces"] == {"batches": 8, "incomplete": 0, "empty": 1}
    assert "incomplete" not in render_report(r2).splitlines()[0]


def test_report_skips_malformed_span_events(tmp_path):
    """Span events missing their ids (foreign tooling, hand edits) are
    counted as malformed and skipped — never a KeyError out of the
    doctor."""
    p = tmp_path / "weird.jsonl"
    p.write_text(
        json.dumps({"v": 2, "ts": 1.0, "event": "span_start",
                    "name": "batch"}) + "\n"
        + json.dumps({"v": 2, "ts": 2.0, "event": "span_end",
                      "name": "batch"}) + "\n"
    )
    r = build_report(str(p))
    assert r["spans"]["malformed"] == 2
    assert r["traces"]["batches"] == 0


def test_report_on_flat_v1_log(tmp_path):
    """A spanless (v1-era) file must produce an audit-only report, not a
    crash."""
    p = tmp_path / "v1.jsonl"
    p.write_text(
        _V1_FIXTURE + "\n" + json.dumps({
            "v": 1, "ts": 1.0, "event": "backend.vmem_oom_retry",
            "shape": [128, 4096], "mxu_mode": "split2",
        }) + "\n"
    )
    report = build_report(str(p))
    assert report["traces"]["batches"] == 0
    assert report["degraded"]["backend.vmem_oom_retry"] == 1
    text = render_report(report)
    assert "no complete batch traces" in text
    assert "DEGRADED paths taken: backend.vmem_oom_retry" in text


# -- cli doctor end-to-end (the acceptance contract) -------------------------


def test_cli_doctor_on_real_stream_bench_run(tmp_path, capsys):
    """Acceptance: `cli doctor` on a fresh `stream-bench --telemetry-jsonl`
    run prints per-stage critical-path percentages summing to ~100% of
    batch wall, a bubble total consistent with the run's own
    pipeline_overlap_ratio accounting, and the degraded-event audit."""
    from randomprojection_tpu import cli

    tel = str(tmp_path / "ev.jsonl")
    cli.main([
        "stream-bench", "--rows", "512", "--batch-rows", "128",
        "--d", "64", "--k", "16", "--backend", "numpy",
        "--prefetch-batches", "2", "--telemetry-jsonl", tel,
    ])
    telemetry.shutdown()  # release the sink the CLI installed
    bench_line = json.loads(capsys.readouterr().out.splitlines()[-1])

    cli.main(["doctor", tel, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert report["traces"]["batches"] == 4  # 512 rows / 128
    stages = report["batch"]["stages"]
    assert {"dispatch", "d2h"} <= set(stages)
    total_pct = sum(d["pct"] for d in stages.values())
    total_pct += report["batch"]["bubble"]["pct"]
    assert total_pct == pytest.approx(100.0, abs=0.5)
    # bubble consistency with the run's own overlap accounting: covered
    # stage time cannot exceed the summed stage walls the bench reported,
    # and bubble = batch wall − covered, all non-negative
    covered = sum(d["wall_s"] for d in stages.values())
    bubble = report["batch"]["bubble"]["wall_s"]
    wall = report["batch"]["wall_s"]
    # each field is independently rounded to 6 decimals in the report
    assert covered + bubble == pytest.approx(wall, abs=1e-4)
    reported_stage_sum = sum(bench_line["stage_wall_s"].values())
    assert covered <= reported_stage_sum * 1.5 + 0.05
    assert 0.0 <= report["pipeline"]["overlap_ratio_est"] < 1.0
    assert "degraded" in report and "tripwire" in report

    # the human rendering carries the waterfall + audit + tripwire
    cli.main(["report", tel])  # alias must resolve too
    text = capsys.readouterr().out
    assert "critical path" in text
    assert "(bubble)" in text
    assert "degraded-event audit:" in text
    assert "regression tripwire" in text


def test_tripwire_rendering_distinguishes_no_verdict_from_clean(tmp_path):
    """A baseline record that predates the tripwire (no regressions key)
    must render as 'no verdict recorded' — never as a clean comparison
    that was never computed; a record whose tripwire RAN and found
    nothing names its baseline."""
    base = {"file": "x", "events": 0, "event_counts": {},
            "spans": {"complete": 0, "orphan_starts": 0, "orphan_ends": 0,
                      "malformed": 0},
            "traces": {"batches": 0, "incomplete": 0, "empty": 0},
            "batch": {"wall_s": 0, "stages": {},
                      "bubble": {"wall_s": 0, "pct": 0}},
            "pipeline": {"elapsed_s": 0, "stage_wall_s": 0,
                         "overlap_ratio_est": 0},
            "queue_depth": None,
            "degraded": {}}
    pre = dict(base, tripwire={"baseline": "BENCH_r05.json",
                               "regressions": None, "regressions_vs": None,
                               "regressions_skipped": None})
    assert "no verdict recorded" in render_report(pre)
    clean = dict(base, tripwire={"baseline": "BENCH_r06.json",
                                 "regressions": [],
                                 "regressions_vs": "BENCH_r05.json",
                                 "regressions_skipped": None})
    text = render_report(clean)
    assert "no >10% drops recorded vs BENCH_r05.json" in text


def test_cli_doctor_missing_and_corrupt_files(tmp_path):
    from randomprojection_tpu import cli

    with pytest.raises(SystemExit, match="no such telemetry file"):
        cli.main(["doctor", str(tmp_path / "nope.jsonl")])
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v":2,"ts":1.0,"eve\n'
                   '{"v":2,"ts":2.0,"event":"x"}\n')
    with pytest.raises(SystemExit, match="corrupt"):
        cli.main(["doctor", str(bad)])


# -- OpenMetrics exposition (acceptance) -------------------------------------

_OM_SAMPLE = __import__("re").compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*'
    r'(\{le="[^"]+"\}|\{quantile="[^"]+"\})? -?[0-9][0-9eE.+-]*$'
)
_OM_TYPE = __import__("re").compile(
    r"^# TYPE [a-zA-Z_][a-zA-Z0-9_]* (counter|gauge|histogram|summary)$"
)


def _assert_openmetrics_wellformed(text):
    lines = text.rstrip("\n").splitlines()
    assert lines[-1] == "# EOF"
    typed = set()
    for line in lines[:-1]:
        if line.startswith("# TYPE"):
            assert _OM_TYPE.match(line), line
            typed.add(line.split()[2])
        else:
            assert _OM_SAMPLE.match(line), line
            base = line.split("{")[0].split(" ")[0]
            stripped = base
            for suf in ("_total", "_bucket", "_sum", "_count"):
                if stripped.endswith(suf):
                    stripped = stripped[: -len(suf)]
            assert stripped in typed or base in typed, line
    return lines


def test_openmetrics_exposition_parses():
    r = telemetry.MetricsRegistry()
    r.counter_inc("backend.dispatches", 3)
    r.gauge_set("stream.queue_depth", 1)
    r.gauge_set("stream.queue_depth", 2)
    r.observe("stage.hash", 1.5e-6)
    r.observe("stage.hash", 3.0e-6)
    r.observe("stage.hash", 1.5)
    text = to_openmetrics(r.snapshot())
    lines = _assert_openmetrics_wellformed(text)
    assert "rp_backend_dispatches_total 3" in lines
    assert "rp_stream_queue_depth 2" in lines
    assert "rp_stream_queue_depth_max 2" in lines
    # histogram: cumulative buckets at the fixed log2 upper edges, exact
    # sum/count riding along
    assert 'rp_stage_hash_seconds_bucket{le="+Inf"} 3' in lines
    # r17: the sibling quantile summary rides beside every histogram
    assert "# TYPE rp_stage_hash_seconds_quantile summary" in lines
    assert any(
        line.startswith('rp_stage_hash_seconds_quantile{quantile="0.5"}')
        for line in lines
    )
    assert "rp_stage_hash_seconds_quantile_count 3" in lines
    bucket_lines = [ln for ln in lines if "_bucket{" in ln]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert any(ln.startswith("rp_stage_hash_seconds_sum") for ln in lines)
    assert "rp_stage_hash_seconds_count 3" in lines


def test_openmetrics_merges_stream_registry_via_cli(tmp_path, capsys):
    """--openmetrics on a workload command writes one exposition carrying
    BOTH the process registry and the run's StreamStats registry."""
    from randomprojection_tpu import cli

    X = np.random.default_rng(0).normal(size=(300, 64)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    np.save(xin, X)
    om = str(tmp_path / "metrics.om")
    cli.main([
        "project", "--input", xin, "--output", str(tmp_path / "y.npy"),
        "--kind", "gaussian", "--n-components", "8",
        "--backend", "numpy", "--batch-rows", "100",
        "--openmetrics", om,
    ])
    capsys.readouterr()
    text = open(om).read()
    lines = _assert_openmetrics_wellformed(text)
    assert "rp_stream_rows_total 300" in lines  # StreamStats registry
    assert any(
        ln.startswith("rp_stage_dispatch_seconds_count") for ln in lines
    )


# -- teardown / unconfigured safety (satellite, subprocess-asserted) ---------

_TEARDOWN_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from randomprojection_tpu.utils import telemetry

# 1) never configured: everything is a no-op, nothing raises
telemetry.emit("unconfigured", x=1)
s = telemetry.start_span("s")
assert s is None
telemetry.end_span(s)
with telemetry.span("t") as t:
    assert t is None

# 2) configured: leave a span OPEN and schedule emits/spans for
# interpreter teardown (module-level __del__); the guards must drop
# them silently — no traceback, no "Exception ignored" noise
telemetry.configure({path!r})
telemetry.emit("alive", x=1)
open_span = telemetry.start_span("left_open", new_trace=True)

class AtTeardown:
    def __del__(self):
        telemetry.emit("late.emit")
        s2 = telemetry.start_span("late_span", new_trace=True)
        telemetry.end_span(s2)
        telemetry.end_span(open_span)

keep = AtTeardown()
print("READY")
"""


def test_emit_and_spans_safe_at_teardown_and_unconfigured(tmp_path):
    tel = str(tmp_path / "teardown.jsonl")
    script = _TEARDOWN_SCRIPT.format(repo=str(REPO), path=tel)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "READY" in proc.stdout
    assert "Traceback" not in proc.stderr, proc.stderr
    assert "Exception ignored" not in proc.stderr, proc.stderr
    # whatever subset of the late events landed, the file must stay
    # readable end to end (the torn-tail contract)
    events = [e["event"] for e in read_events(tel)]
    assert "alive" in events


# -- bench trajectory (acceptance) -------------------------------------------


def test_bench_trajectory_covers_every_committed_record():
    from randomprojection_tpu import benchmark

    rows = benchmark.bench_trajectory(str(REPO))
    files = sorted(
        p.name for p in REPO.glob("BENCH_r*.json")
    )
    assert files, "no committed BENCH_r*.json"
    assert [r["file"] for r in rows] == files
    for r in rows:
        assert "error" in r or r["rates"], r


def test_trajectory_table_renders_all_rounds():
    sys.path.insert(0, str(REPO / "docs"))
    try:
        import gen_bench_tables as g
    finally:
        sys.path.pop(0)
    lines = g.render_trajectory()
    text = "\n".join(lines)
    for p in sorted(REPO.glob("BENCH_r*.json")):
        rnd = p.name.replace("BENCH_", "").replace(".json", "")
        assert f"`{rnd}`" in text
    # and it is part of the generated BASELINE block
    block = g.render(g.latest_bench_path())
    assert "Bench trajectory" in block
