"""Multi-host bring-up tests (SURVEY.md §3.4).

The two-process integration test actually EXECUTES the multi-host path on
this machine: two subprocesses join one ``jax.distributed`` runtime over a
localhost coordinator (CPU backend), each transforms its own
``host_row_range`` slice of a shared source, and the concatenation must
equal the single-process result — the Spark partition-map contract
(VERDICT r2 missing #2: the module previously had zero execution coverage).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# host_row_range: pure-function unit tests
# ---------------------------------------------------------------------------


def test_host_row_range_partitions_exactly():
    from randomprojection_tpu.parallel.distributed import host_row_range

    for n_rows in (0, 1, 7, 100, 101, 1023):
        for n_p in (1, 2, 3, 8):
            ranges = [
                host_row_range(n_rows, process_id=p, process_count=n_p)
                for p in range(n_p)
            ]
            # contiguous, ordered, covering exactly [0, n_rows)
            assert ranges[0][0] == 0 and ranges[-1][1] == n_rows
            for (alo, ahi), (blo, bhi) in zip(ranges, ranges[1:]):
                assert ahi == blo
            # balanced to within one row
            sizes = [hi - lo for lo, hi in ranges]
            assert max(sizes) - min(sizes) <= 1


def test_host_row_range_validates():
    from randomprojection_tpu.parallel.distributed import host_row_range

    with pytest.raises(ValueError, match="n_rows"):
        host_row_range(-1, process_id=0, process_count=1)
    with pytest.raises(ValueError, match="out of range"):
        host_row_range(10, process_id=2, process_count=2)


def test_host_row_range_uses_runtime_by_default():
    from randomprojection_tpu.parallel.distributed import host_row_range

    # single-process runtime: the whole range
    assert host_row_range(100) == (0, 100)


# ---------------------------------------------------------------------------
# initialize(): failure policy
# ---------------------------------------------------------------------------


@pytest.fixture
def _fresh_initialize(monkeypatch):
    """Reset the idempotence latch and make the underlying jax call fail
    fast (a real unreachable coordinator would retry for minutes)."""
    import jax

    from randomprojection_tpu.parallel import distributed

    if hasattr(distributed.initialize, "_done"):
        del distributed.initialize._done

    def boom(**kwargs):
        raise RuntimeError("simulated coordinator failure")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    yield distributed
    if hasattr(distributed.initialize, "_done"):
        del distributed.initialize._done


def test_initialize_raises_on_explicit_args_failure(_fresh_initialize):
    """Explicit distributed arguments that cannot be satisfied must raise,
    never silently degrade to single-process (VERDICT r2 weak #5)."""
    distributed = _fresh_initialize
    with pytest.raises(RuntimeError, match="refusing to silently degrade"):
        distributed.initialize(
            coordinator_address="localhost:1", num_processes=2, process_id=1
        )
    # the latch must NOT be set after a failure
    assert not getattr(distributed.initialize, "_done", False)


def test_initialize_raises_when_env_marks_distributed(
    _fresh_initialize, monkeypatch
):
    """Auto-detection failure inside a distributed launch (env markers
    present) is a misconfiguration, not a single-machine run."""
    distributed = _fresh_initialize
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "localhost:1")
    with pytest.raises(RuntimeError, match="refusing to silently degrade"):
        distributed.initialize()


def test_initialize_degrades_quietly_on_plain_single_machine(
    _fresh_initialize, monkeypatch
):
    """No args, no env markers: the ordinary laptop case stays a no-op."""
    distributed = _fresh_initialize
    for v in distributed._DISTRIBUTED_ENV_MARKERS:
        monkeypatch.delenv(v, raising=False)
    distributed.initialize()  # must not raise
    assert distributed.initialize._done


# ---------------------------------------------------------------------------
# two-process integration
# ---------------------------------------------------------------------------

_WORKER = r"""
import json, os, sys
sys.path.insert(0, "@REPO@")
import numpy as np

from randomprojection_tpu.parallel import distributed

pid = int(sys.argv[1])
distributed.initialize(
    coordinator_address="@COORD@", num_processes=2, process_id=pid
)
import jax

assert jax.process_count() == 2, jax.process_count()
assert distributed.is_multi_process()

from randomprojection_tpu import GaussianRandomProjection

X = np.random.default_rng(0).normal(size=(301, 64)).astype(np.float32)
lo, hi = distributed.host_row_range(X.shape[0])
est = GaussianRandomProjection(16, random_state=7, backend="jax")
est.fit_schema(*X.shape, dtype=X.dtype)  # fit-from-schema: no data needed
Y = np.asarray(est.transform(X[lo:hi]))
np.save(sys.argv[2], Y)
print(json.dumps({"pid": pid, "lo": lo, "hi": hi, "shape": list(Y.shape)}))
"""


def test_two_process_transform_matches_single(tmp_path):
    port = _free_port()
    coord = f"localhost:{port}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # each process is a plain 1-device CPU host: drop the suite's
        # virtual 8-device flag so the two runtimes agree on topology
        "XLA_FLAGS": "",
        "PYTHONPATH": REPO_ROOT,
    }
    script = _WORKER.replace("@REPO@", REPO_ROOT).replace("@COORD@", coord)
    procs = []
    outs = [str(tmp_path / f"y{p}.npy") for p in range(2)]
    for p in range(2):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script, str(p), outs[p]],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = [pr.communicate(timeout=240) for pr in procs]
    for pr, (so, se) in zip(procs, results):
        assert pr.returncode == 0, f"worker failed:\n{so}\n{se}"

    metas = [json.loads(so.splitlines()[-1]) for so, _ in results]
    assert metas[0]["lo"] == 0 and metas[1]["hi"] == 301
    assert metas[0]["hi"] == metas[1]["lo"]

    # single-process reference: same seed => same matrix => same output.
    # Workers always run on CPU; under RP_TEST_TPU=1 this reference runs on
    # the real chip, whose f32 'high' mode (3-pass bf16) differs from true
    # CPU f32 at ~1e-4 relative — the assertion checks partitioning and
    # matrix identity, so distortion-level tolerance is the contract
    # (wrong partitioning would be off by O(1), not O(1e-4)).
    from randomprojection_tpu import GaussianRandomProjection

    X = np.random.default_rng(0).normal(size=(301, 64)).astype(np.float32)
    est = GaussianRandomProjection(16, random_state=7, backend="jax")
    est.fit_schema(*X.shape, dtype=X.dtype)
    ref = np.asarray(est.transform(X))
    got = np.concatenate([np.load(o) for o in outs])
    if os.environ.get("RP_TEST_TPU", "") not in ("", "0"):
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)
    else:
        # all-CPU: both sides are true f32 — keep the tight contract so a
        # numerics regression in either path cannot hide
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


_POD_WORKER = r"""
import json, os, sys
sys.path.insert(0, "@REPO@")
import numpy as np

from randomprojection_tpu.parallel import distributed

pid = int(sys.argv[1])
distributed.initialize(
    coordinator_address="@COORD@", num_processes=2, process_id=pid
)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert len(jax.local_devices()) == 4, jax.local_devices()
assert len(jax.devices()) == 8, jax.devices()

from randomprojection_tpu.parallel import make_mesh
from randomprojection_tpu.parallel.sharded import make_sharded_projector

n, d, k = 320, 64, 16
X = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
R = np.random.default_rng(1).normal(size=(k, d)).astype(np.float32)
out = {}

# --- DP over the GLOBAL 8-device mesh (4 devices on each of 2 hosts) ---
mesh = make_mesh({"data": 8})
Xg = jax.make_array_from_callback(
    X.shape, NamedSharding(mesh, P("data", None)), lambda i: X[i]
)
Rg = jax.make_array_from_callback(
    R.shape, NamedSharding(mesh, P()), lambda i: R[i]
)
y = make_sharded_projector(mesh)(Xg, Rg)
shards = {s.index[0].start or 0: np.asarray(s.data) for s in y.addressable_shards}
out["dp_lo"] = min(shards)
out["dp_rows"] = np.concatenate([shards[s] for s in sorted(shards)])

# --- DP x TP: 'feature' axis listed FIRST so its two groups live on
# DIFFERENT hosts -> the contraction psum crosses the process boundary
# (the DCN hop of a real pod) ---
mesh2 = make_mesh({"feature": 2, "data": 4})
Xg2 = jax.make_array_from_callback(
    X.shape, NamedSharding(mesh2, P("data", "feature")), lambda i: X[i]
)
Rg2 = jax.make_array_from_callback(
    R.shape, NamedSharding(mesh2, P(None, "feature")), lambda i: R[i]
)
y2 = make_sharded_projector(mesh2, feature_axis="feature")(Xg2, Rg2)
shards2 = {s.index[0].start or 0: np.asarray(s.data) for s in y2.addressable_shards}
out["tp_full"] = np.concatenate([shards2[s] for s in sorted(shards2)])
assert out["tp_full"].shape == (n, k)  # every host holds all rows (feature-replicated)

# --- ESTIMATOR over the global DPxTP mesh (VERDICT r4 #9): fit runs
# materialize_sharded across processes — the counter-based PRNG must
# derive each process's column shard of the SAME global matrix ---
from randomprojection_tpu import SparseRandomProjection

est_tp = SparseRandomProjection(
    k, random_state=11, density=0.25, backend="jax",
    backend_options={"mesh": mesh2, "feature_axis": "feature"},
)
est_tp.fit_schema(n, d, dtype=np.float32)
yg = est_tp.transform(Xg2)  # device-resident in -> device handle out
eshards = {s.index[0].start or 0: np.asarray(s.data) for s in yg.addressable_shards}
out["est_tp_full"] = np.concatenate([eshards[s] for s in sorted(eshards)])
assert out["est_tp_full"].shape == (n, k)

# --- deployment pattern: host_row_range over the stream, a LOCAL mesh of
# this host's 4 devices under the estimator ---
from randomprojection_tpu import GaussianRandomProjection
from randomprojection_tpu.streaming import ArraySource, stream_to_array

local_mesh = make_mesh({"data": 4}, devices=jax.local_devices())
lo, hi = distributed.host_row_range(n)
est = GaussianRandomProjection(
    k, random_state=7, backend="jax", backend_options={"mesh": local_mesh}
)
est.fit_schema(n, d, dtype=X.dtype)
out["stream_lo"] = lo
out["stream_rows"] = stream_to_array(est, ArraySource(X[lo:hi], batch_rows=64))

np.savez(sys.argv[2], **out)
print(json.dumps({"pid": pid, "ok": True}))
"""


@pytest.mark.mesh_env
def test_pod_topology_two_process_mesh(tmp_path):
    """The real pod shape (VERDICT r3 missing #4): 2 processes x 4 devices
    = one global 8-device mesh through jax.distributed.  DP rows, a TP
    whose psum crosses the process boundary, and the per-host
    host_row_range + local-mesh streaming pattern must all equal the
    single-process 8-device-mesh result computed by this (virtual-8) test
    process."""
    import jax

    if len(jax.devices()) != 8:
        pytest.skip("needs the suite's virtual 8-device CPU topology")
    port = _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO_ROOT,
    }
    script = _POD_WORKER.replace("@REPO@", REPO_ROOT).replace(
        "@COORD@", f"localhost:{port}"
    )
    outs = [str(tmp_path / f"pod{p}.npz") for p in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(p), outs[p]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for p in range(2)
    ]
    results = [pr.communicate(timeout=240) for pr in procs]
    for pr, (so, se) in zip(procs, results):
        assert pr.returncode == 0, f"pod worker failed:\n{so}\n{se}"
    w0, w1 = [np.load(o) for o in outs]

    # single-process reference on this test process's own 8 virtual devices
    from randomprojection_tpu.parallel import make_mesh
    from randomprojection_tpu.parallel.sharded import make_sharded_projector

    n, d, k = 320, 64, 16
    X = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
    R = np.random.default_rng(1).normal(size=(k, d)).astype(np.float32)
    ref_dp = np.asarray(make_sharded_projector(make_mesh({"data": 8}))(X, R))

    # DP: the two workers' row blocks tile [0, n)
    assert {int(w0["dp_lo"]), int(w1["dp_lo"])} == {0, n // 2}
    got_dp = np.concatenate(
        [w["dp_rows"] for w in sorted((w0, w1), key=lambda w: int(w["dp_lo"]))]
    )
    np.testing.assert_allclose(got_dp, ref_dp, rtol=1e-5, atol=1e-6)

    # TP (cross-host psum): both hosts hold the full feature-replicated Y
    mesh_tp = make_mesh({"feature": 2, "data": 4})
    ref_tp = np.asarray(
        make_sharded_projector(mesh_tp, feature_axis="feature")(X, R)
    )
    np.testing.assert_allclose(w0["tp_full"], ref_tp, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w1["tp_full"], ref_tp, rtol=1e-5, atol=1e-6)

    # estimator across processes (VERDICT r4 #9): the pod workers' fit ran
    # materialize_sharded over the multi-host mesh — the sharding-invariant
    # PRNG must yield the same matrix as this process's single-host fit of
    # the identical estimator on the identically-decomposed mesh
    from randomprojection_tpu import SparseRandomProjection

    est_ref = SparseRandomProjection(
        16, random_state=11, density=0.25, backend="jax",
        backend_options={"mesh": mesh_tp, "feature_axis": "feature"},
    )
    est_ref.fit_schema(n, d, dtype=np.float32)
    ref_est = np.asarray(est_ref.transform(X))
    np.testing.assert_allclose(w0["est_tp_full"], ref_est, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w1["est_tp_full"], ref_est, rtol=1e-5, atol=1e-6)
    # and the mesh must not have changed the MATRIX itself: the same seed
    # with no mesh at all agrees (same counter-based streams)
    est_plain = SparseRandomProjection(
        16, random_state=11, density=0.25, backend="jax"
    )
    est_plain.fit_schema(n, d, dtype=np.float32)
    np.testing.assert_allclose(
        ref_est, np.asarray(est_plain.transform(X)), rtol=1e-5, atol=1e-5
    )

    # streamed host_row_range + local mesh: concat equals the one-process
    # estimator (same seed => same matrix regardless of mesh/topology)
    from randomprojection_tpu import GaussianRandomProjection

    est = GaussianRandomProjection(k, random_state=7, backend="jax")
    est.fit_schema(n, d, dtype=X.dtype)
    ref_stream = np.asarray(est.transform(X))
    got_stream = np.concatenate(
        [w["stream_rows"]
         for w in sorted((w0, w1), key=lambda w: int(w["stream_lo"]))]
    )
    np.testing.assert_allclose(got_stream, ref_stream, rtol=1e-5, atol=1e-6)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_host_row_range_rejects_partial_pair():
    from randomprojection_tpu.parallel.distributed import host_row_range

    with pytest.raises(ValueError, match="together"):
        host_row_range(100, process_count=4)
    with pytest.raises(ValueError, match="together"):
        host_row_range(100, process_id=0)


# ---------------------------------------------------------------------------
# sharded serving tier over a mesh's devices (ISSUE 8)
# ---------------------------------------------------------------------------


def test_sharded_index_spans_mesh_devices():
    """The serving tier resolves its shard set from a jax Mesh's data
    axis and serves bit-identically to brute force across all 8 virtual
    devices.  Per-shard dispatch needs no shard_map, so this runs on
    any jax — unlike the shard_map path, it is NOT mesh_env-gated."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the suite's virtual 8-device CPU topology")
    from randomprojection_tpu.models import sketch as sk
    from randomprojection_tpu.parallel import make_mesh
    from randomprojection_tpu.serving import ShardedSimHashIndex

    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(42)
    codes = rng.integers(0, 256, size=(410, 4), dtype=np.uint8)
    queries = rng.integers(0, 256, size=(12, 4), dtype=np.uint8)
    idx = ShardedSimHashIndex(codes, mesh=mesh, topk_impl="scan")
    assert idx.n_shards == 8
    assert idx.devices == list(jax.devices()[:8])
    # every shard's chunk actually lives on its own device
    for shard, dev in zip(idx._shards, idx.devices):
        assert shard._chunks[0].b.devices() == {dev}
    d, i = idx.query_topk(queries, 6)
    rd, ri = sk.topk_bruteforce(queries, codes, 6)
    assert np.array_equal(d, rd)
    assert np.array_equal(i, ri.astype(np.int64))
