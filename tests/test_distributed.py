"""Multi-host bring-up tests (SURVEY.md §3.4).

The two-process integration test actually EXECUTES the multi-host path on
this machine: two subprocesses join one ``jax.distributed`` runtime over a
localhost coordinator (CPU backend), each transforms its own
``host_row_range`` slice of a shared source, and the concatenation must
equal the single-process result — the Spark partition-map contract
(VERDICT r2 missing #2: the module previously had zero execution coverage).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# host_row_range: pure-function unit tests
# ---------------------------------------------------------------------------


def test_host_row_range_partitions_exactly():
    from randomprojection_tpu.parallel.distributed import host_row_range

    for n_rows in (0, 1, 7, 100, 101, 1023):
        for n_p in (1, 2, 3, 8):
            ranges = [
                host_row_range(n_rows, process_id=p, process_count=n_p)
                for p in range(n_p)
            ]
            # contiguous, ordered, covering exactly [0, n_rows)
            assert ranges[0][0] == 0 and ranges[-1][1] == n_rows
            for (alo, ahi), (blo, bhi) in zip(ranges, ranges[1:]):
                assert ahi == blo
            # balanced to within one row
            sizes = [hi - lo for lo, hi in ranges]
            assert max(sizes) - min(sizes) <= 1


def test_host_row_range_validates():
    from randomprojection_tpu.parallel.distributed import host_row_range

    with pytest.raises(ValueError, match="n_rows"):
        host_row_range(-1, process_id=0, process_count=1)
    with pytest.raises(ValueError, match="out of range"):
        host_row_range(10, process_id=2, process_count=2)


def test_host_row_range_uses_runtime_by_default():
    from randomprojection_tpu.parallel.distributed import host_row_range

    # single-process runtime: the whole range
    assert host_row_range(100) == (0, 100)


# ---------------------------------------------------------------------------
# initialize(): failure policy
# ---------------------------------------------------------------------------


@pytest.fixture
def _fresh_initialize(monkeypatch):
    """Reset the idempotence latch and make the underlying jax call fail
    fast (a real unreachable coordinator would retry for minutes)."""
    import jax

    from randomprojection_tpu.parallel import distributed

    if hasattr(distributed.initialize, "_done"):
        del distributed.initialize._done

    def boom(**kwargs):
        raise RuntimeError("simulated coordinator failure")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    yield distributed
    if hasattr(distributed.initialize, "_done"):
        del distributed.initialize._done


def test_initialize_raises_on_explicit_args_failure(_fresh_initialize):
    """Explicit distributed arguments that cannot be satisfied must raise,
    never silently degrade to single-process (VERDICT r2 weak #5)."""
    distributed = _fresh_initialize
    with pytest.raises(RuntimeError, match="refusing to silently degrade"):
        distributed.initialize(
            coordinator_address="localhost:1", num_processes=2, process_id=1
        )
    # the latch must NOT be set after a failure
    assert not getattr(distributed.initialize, "_done", False)


def test_initialize_raises_when_env_marks_distributed(
    _fresh_initialize, monkeypatch
):
    """Auto-detection failure inside a distributed launch (env markers
    present) is a misconfiguration, not a single-machine run."""
    distributed = _fresh_initialize
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "localhost:1")
    with pytest.raises(RuntimeError, match="refusing to silently degrade"):
        distributed.initialize()


def test_initialize_degrades_quietly_on_plain_single_machine(
    _fresh_initialize, monkeypatch
):
    """No args, no env markers: the ordinary laptop case stays a no-op."""
    distributed = _fresh_initialize
    for v in distributed._DISTRIBUTED_ENV_MARKERS:
        monkeypatch.delenv(v, raising=False)
    distributed.initialize()  # must not raise
    assert distributed.initialize._done


# ---------------------------------------------------------------------------
# two-process integration
# ---------------------------------------------------------------------------

_WORKER = r"""
import json, os, sys
sys.path.insert(0, "@REPO@")
import numpy as np

from randomprojection_tpu.parallel import distributed

pid = int(sys.argv[1])
distributed.initialize(
    coordinator_address="@COORD@", num_processes=2, process_id=pid
)
import jax

assert jax.process_count() == 2, jax.process_count()
assert distributed.is_multi_process()

from randomprojection_tpu import GaussianRandomProjection

X = np.random.default_rng(0).normal(size=(301, 64)).astype(np.float32)
lo, hi = distributed.host_row_range(X.shape[0])
est = GaussianRandomProjection(16, random_state=7, backend="jax")
est.fit_schema(*X.shape, dtype=X.dtype)  # fit-from-schema: no data needed
Y = np.asarray(est.transform(X[lo:hi]))
np.save(sys.argv[2], Y)
print(json.dumps({"pid": pid, "lo": lo, "hi": hi, "shape": list(Y.shape)}))
"""


def test_two_process_transform_matches_single(tmp_path):
    port = _free_port()
    coord = f"localhost:{port}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # each process is a plain 1-device CPU host: drop the suite's
        # virtual 8-device flag so the two runtimes agree on topology
        "XLA_FLAGS": "",
        "PYTHONPATH": REPO_ROOT,
    }
    script = _WORKER.replace("@REPO@", REPO_ROOT).replace("@COORD@", coord)
    procs = []
    outs = [str(tmp_path / f"y{p}.npy") for p in range(2)]
    for p in range(2):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script, str(p), outs[p]],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = [pr.communicate(timeout=240) for pr in procs]
    for pr, (so, se) in zip(procs, results):
        assert pr.returncode == 0, f"worker failed:\n{so}\n{se}"

    metas = [json.loads(so.splitlines()[-1]) for so, _ in results]
    assert metas[0]["lo"] == 0 and metas[1]["hi"] == 301
    assert metas[0]["hi"] == metas[1]["lo"]

    # single-process reference: same seed => same matrix => same output.
    # Workers always run on CPU; under RP_TEST_TPU=1 this reference runs on
    # the real chip, whose f32 'high' mode (3-pass bf16) differs from true
    # CPU f32 at ~1e-4 relative — the assertion checks partitioning and
    # matrix identity, so distortion-level tolerance is the contract
    # (wrong partitioning would be off by O(1), not O(1e-4)).
    from randomprojection_tpu import GaussianRandomProjection

    X = np.random.default_rng(0).normal(size=(301, 64)).astype(np.float32)
    est = GaussianRandomProjection(16, random_state=7, backend="jax")
    est.fit_schema(*X.shape, dtype=X.dtype)
    ref = np.asarray(est.transform(X))
    got = np.concatenate([np.load(o) for o in outs])
    if os.environ.get("RP_TEST_TPU", "") not in ("", "0"):
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)
    else:
        # all-CPU: both sides are true f32 — keep the tight contract so a
        # numerics regression in either path cannot hide
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_host_row_range_rejects_partial_pair():
    from randomprojection_tpu.parallel.distributed import host_row_range

    with pytest.raises(ValueError, match="together"):
        host_row_range(100, process_count=4)
    with pytest.raises(ValueError, match="together"):
        host_row_range(100, process_id=0)
