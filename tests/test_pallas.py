"""Fused Pallas lazy-mask kernel tests.

The in-kernel PRNG (`pltpu.prng_*`) has no CPU emulation (the interpreter
returns zero bits), so the kernel tests require the real chip:

    RP_TEST_TPU=1 python -m pytest tests/test_pallas.py

On the default CPU suite only the refusal behavior is tested.  The verify
recipe runs the TPU half on every milestone.
"""

import numpy as np
import pytest

requires_tpu = pytest.mark.skipif(
    __import__("jax").default_backend() == "cpu",
    reason="pltpu PRNG has no CPU emulation; run with RP_TEST_TPU=1",
)


@pytest.fixture(scope="module")
def x():
    return np.random.default_rng(0).normal(size=(300, 700)).astype(np.float32)


@requires_tpu
@pytest.mark.parametrize("density", [1.0, 1 / 3, 0.05])
def test_fused_matches_materialized_matrix(x, density):
    """The fused projection must equal X @ Rᵀ for the matrix the kernel
    defines (same (seed, block) PRNG streams)."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        fused_sparse_project,
        pallas_sparse_matrix,
    )

    k = 32
    y = np.asarray(fused_sparse_project(jnp.asarray(x), 42, k, density))
    R = np.asarray(pallas_sparse_matrix(42, k, x.shape[1], density))
    # MXU bf16 passes: ~3e-3 relative on O(10) values → scale atol
    np.testing.assert_allclose(y, x @ R.T, rtol=5e-3, atol=0.05)


@requires_tpu
def test_mask_distribution():
    from randomprojection_tpu.ops.pallas_kernels import pallas_sparse_matrix

    R = np.asarray(pallas_sparse_matrix(0, 64, 4096, 1 / 3))
    v = 1.0 / np.sqrt((1 / 3) * 64)
    vals = np.unique(R)
    np.testing.assert_allclose(sorted(vals), [-v, 0.0, v], rtol=1e-6)
    assert abs((R > 0).mean() - 1 / 6) < 0.01
    assert abs((R < 0).mean() - 1 / 6) < 0.01
    # variance of entries: density · v² = 1/k
    np.testing.assert_allclose(R.var(), 1 / 64, rtol=0.05)


@requires_tpu
@pytest.mark.parametrize("density", [1.0, 1 / 3, 0.05])
def test_fused_split2_f32_grade(x, density):
    """mxu_mode='split2' contracts the SAME matrix as 'f32' but at f32-grade
    accuracy (X split hi/lo bf16 in VMEM vs the exact-in-bf16 mask): the
    output must match X @ Rᵀ far tighter than the one-pass mode can."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        fused_sparse_project,
        pallas_sparse_matrix,
    )

    k = 32
    y = np.asarray(
        fused_sparse_project(jnp.asarray(x), 42, k, density, mxu_mode="split2")
    )
    R = np.asarray(pallas_sparse_matrix(42, k, x.shape[1], density))
    ref = x.astype(np.float64) @ R.astype(np.float64).T
    # split2: exact ±1/0 products, error only from the lo-half bf16 rounding
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)
    # and it is the same matrix the f32 mode contracts (bf16-grade agreement)
    y_f32 = np.asarray(fused_sparse_project(jnp.asarray(x), 42, k, density))
    np.testing.assert_allclose(y, y_f32, rtol=5e-3, atol=0.05)


@requires_tpu
def test_fused_split2_deterministic(x):
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import fused_sparse_project

    a = np.asarray(
        fused_sparse_project(jnp.asarray(x), 7, 32, 0.25, mxu_mode="split2")
    )
    b = np.asarray(
        fused_sparse_project(jnp.asarray(x), 7, 32, 0.25, mxu_mode="split2")
    )
    np.testing.assert_array_equal(a, b)
    c = np.asarray(
        fused_sparse_project(
            jnp.asarray(x), 7, 32, 0.25, block_n=128, mxu_mode="split2"
        )
    )
    np.testing.assert_array_equal(a, c)  # row tiling is not part of the matrix


@requires_tpu
def test_determinism_and_row_tile_independence(x):
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import fused_sparse_project

    a = np.asarray(fused_sparse_project(jnp.asarray(x), 7, 32, 0.25))
    b = np.asarray(fused_sparse_project(jnp.asarray(x), 7, 32, 0.25))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(fused_sparse_project(jnp.asarray(x), 7, 32, 0.25, block_n=128))
    np.testing.assert_array_equal(a, c)  # row tiling must not change the matrix
    d = np.asarray(fused_sparse_project(jnp.asarray(x), 8, 32, 0.25))
    assert not np.array_equal(a, d)


@requires_tpu
def test_block_streams_differ():
    """Adjacent column blocks must use distinct PRNG streams."""
    from randomprojection_tpu.ops.pallas_kernels import (
        BLOCK_D,
        pallas_sparse_matrix,
    )

    R = np.asarray(pallas_sparse_matrix(3, 16, 2 * BLOCK_D, 1.0))
    assert not np.array_equal(R[:, :BLOCK_D], R[:, BLOCK_D:])


def test_validation():
    """Argument validation fires before pallas_call — runs on any backend."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import fused_sparse_project

    x = jnp.zeros((8, 64))
    with pytest.raises(ValueError, match="multiple of 8"):
        fused_sparse_project(x, 0, 12, 0.5)
    with pytest.raises(ValueError, match="density"):
        fused_sparse_project(x, 0, 16, 1.5)
    with pytest.raises(ValueError, match="mxu_mode"):
        fused_sparse_project(x, 0, 16, 0.5, mxu_mode="f64")


def test_structural_invariants_everywhere():
    """Shape/padding/seed-folding contracts, checked WITHOUT executing the
    kernel (abstract eval only), so the default CPU suite guards them.

    These are load-bearing for persisted lazy models: the (seed, block)
    streams, BLOCK_D, and the pad-then-slice layout define the matrix.
    Changing any of them silently redefines every saved lazy model — run
    RP_TEST_TPU=1 pytest tests/test_pallas.py before touching BLOCK_D,
    the PRNG seeding, or _uniform_from_bits.
    """
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        BLOCK_D,
        BLOCK_N,
        fused_sparse_project,
        pallas_sparse_matrix,
        _seed_to_i32,
    )

    # the matrix definition constants themselves (serialization depends on
    # them; a changed value must fail here, not silently re-key models)
    assert BLOCK_D == 512 and BLOCK_N == 256

    # seed folding: mod 2^32 then signed int32 reinterpretation
    assert _seed_to_i32(0) == 0
    assert _seed_to_i32(5) == 5
    assert _seed_to_i32(2**31) == -(2**31)
    assert _seed_to_i32(2**32 + 7) == 7
    assert _seed_to_i32(-1) == -1

    # ragged n and d are padded to (block_n, BLOCK_D) multiples internally
    # and sliced back: output shape must be exact for any input shape, in
    # every MXU mode (the mode changes arithmetic, never the contract)
    for n, d, k in [(300, 700, 32), (1, 1, 8), (256, 512, 64), (257, 513, 8)]:
        for mode in ("f32", "split2", "bf16"):
            out = jax.eval_shape(
                lambda a, k=k, mode=mode: fused_sparse_project(
                    a, 0, k, 0.5, mxu_mode=mode
                ),
                jax.ShapeDtypeStruct((n, d), jnp.float32),
            )
            assert out.shape == (n, k) and out.dtype == jnp.float32
        R = jax.eval_shape(
            lambda k=k, d=d: pallas_sparse_matrix(0, k, d, 0.5)
        )
        assert R.shape == (k, d) and R.dtype == jnp.float32

    # row tile is NOT part of the matrix definition: changing block_n must
    # not change the output contract (shape here; values on TPU in
    # test_determinism_and_row_tile_independence)
    out = jax.eval_shape(
        lambda a: fused_sparse_project(a, 0, 32, 0.5, block_n=128),
        jax.ShapeDtypeStruct((300, 700), jnp.float32),
    )
    assert out.shape == (300, 32)


@requires_tpu
def test_lazy_backend_end_to_end():
    """Estimator with materialization='lazy': transform, components_,
    inverse round-trip all work without R in HBM."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.backends.jax_backend import _LazyMask

    X = np.random.default_rng(1).normal(size=(200, 1024)).astype(np.float32)
    est = SparseRandomProjection(
        n_components=64,
        density=1 / 3,
        random_state=5,
        backend="jax",
        backend_options={"materialization": "lazy"},
    ).fit(X)
    assert isinstance(est.components_, _LazyMask)  # nothing materialized
    Y = np.asarray(est.transform(X))
    R = est.components_as_numpy()
    np.testing.assert_allclose(Y, X @ R.T, rtol=1e-2, atol=0.05)
    np.testing.assert_array_equal(Y, np.asarray(est.transform(X)))
    Xhat = est.inverse_transform(Y)
    np.testing.assert_allclose(
        np.asarray(est.transform(Xhat)), Y, rtol=5e-2, atol=0.1
    )


@requires_tpu
def test_lazy_split2_backend_end_to_end():
    """materialization='lazy' × precision='split2': the estimator output must
    match X @ Rᵀ at f32 grade (the T1 headline path) with no R in HBM."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.backends.jax_backend import _LazyMask

    X = np.random.default_rng(1).normal(size=(200, 1024)).astype(np.float32)
    common = dict(n_components=64, density=1 / 3, random_state=5, backend="jax")
    est = SparseRandomProjection(
        **common,
        backend_options={"materialization": "lazy", "precision": "split2"},
    ).fit(X)
    assert isinstance(est.components_, _LazyMask)  # nothing materialized
    Y = np.asarray(est.transform(X))
    R = est.components_as_numpy()
    np.testing.assert_allclose(Y, X @ R.T, rtol=1e-4, atol=1e-4)
    # the backend's f32 default precision ('high') maps to the same split2
    # arithmetic under lazy (Mosaic has no multi-pass f32 dot): bit-identical
    est_default = SparseRandomProjection(
        **common, backend_options={"materialization": "lazy"}
    ).fit(X)
    np.testing.assert_array_equal(Y, np.asarray(est_default.transform(X)))
    # explicit precision='default' opts into the single-pass f32 dot:
    # same matrix, bf16-grade agreement only
    est_fast = SparseRandomProjection(
        **common,
        backend_options={"materialization": "lazy", "precision": "default"},
    ).fit(X)
    np.testing.assert_allclose(
        Y, np.asarray(est_fast.transform(X)), rtol=5e-3, atol=0.05
    )


def _require_8_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (default suite)")


@pytest.mark.mesh_env
def test_lazy_tp_shard_map_abstract_eval():
    """DP×TP lazy path structure on the 8-device CPU mesh: the shard_map'd
    kernel with per-shard block offsets must trace and produce the right
    shapes (abstract eval only; values need the real chip)."""
    _require_8_devices()
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from randomprojection_tpu.ops.pallas_kernels import (
        BLOCK_D,
        fused_sparse_project,
    )
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 4, "feature": 2})
    k = 16

    def local(x):
        offset = jax.lax.axis_index("feature") * (x.shape[1] // BLOCK_D)
        p = fused_sparse_project(x, 0, k, 0.5, block_offset=offset)
        return jax.lax.psum(p, "feature")

    fn = jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P("data", "feature"),),
            out_specs=P("data", None), check_vma=False,
        )
    )
    out = jax.eval_shape(
        fn, jax.ShapeDtypeStruct((64, 4 * BLOCK_D), jnp.float32)
    )
    assert out.shape == (64, k) and out.dtype == jnp.float32


def test_lazy_tp_alignment_validated_at_fit():
    """Ragged per-shard column blocks would redefine the matrix; the fit
    must refuse before any kernel runs (checked on any platform)."""
    _require_8_devices()
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 4, "feature": 2})
    X = np.zeros((16, 700), dtype=np.float32)  # 700 % (2*512) != 0
    with pytest.raises(ValueError, match="feature_shards"):
        SparseRandomProjection(
            8, random_state=0, density=0.5, backend="jax",
            backend_options={
                "mesh": mesh, "feature_axis": "feature",
                "materialization": "lazy",
            },
        ).fit(X)


@requires_tpu
def test_fused_bf16_mode(x):
    """mxu_mode='bf16': bf16 input contracts the SAME matrix in one exact
    MXU pass — near-exact vs the f64 contraction of the bf16 data (products
    of bf16 values with {±1, 0} are exact; only the f32 accumulation
    rounds), at half the x HBM traffic."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        fused_sparse_project,
        pallas_sparse_matrix,
    )

    k = 32
    x16 = jnp.asarray(x, dtype=jnp.bfloat16)
    y = np.asarray(
        fused_sparse_project(x16, 42, k, 1 / 3, mxu_mode="bf16")
    )
    R = np.asarray(pallas_sparse_matrix(42, k, x.shape[1], 1 / 3))
    ref = np.asarray(x16).astype(np.float64) @ R.astype(np.float64).T
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


@requires_tpu
def test_lazy_bf16_estimator_end_to_end():
    """A bf16-fitted lazy model keeps x bf16 through the fused kernel: the
    output must match the f64 contraction of the bf16 data against the
    kernel's own matrix at the data's precision (distortion no worse than
    the dense-bf16 mode's — VERDICT r3 missing #5)."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.utils.validation import bfloat16_dtype

    bf16 = bfloat16_dtype()
    if bf16 is None:
        pytest.skip("ml_dtypes bfloat16 unavailable")
    X = np.random.default_rng(4).normal(size=(256, 1024)).astype(np.float32)
    X16 = X.astype(bf16)
    est = SparseRandomProjection(
        64, density=1 / 3, random_state=11, backend="jax",
        backend_options={"materialization": "lazy"},
    ).fit(X16)
    Y16 = np.asarray(est.transform(X16))
    assert Y16.dtype == bf16  # bf16 in → bf16 out
    Y = Y16.astype(np.float64)
    R = est.components_as_numpy().astype(np.float64)
    ref = X16.astype(np.float64) @ R.T
    # Y is itself bf16 at the host edge: agreement is bf16-grade
    np.testing.assert_allclose(Y, ref, rtol=1e-2, atol=0.05)


@requires_tpu
def test_mask_cache_respects_vmem_limit():
    """Large-k regression (round-4 review finding): the mask-block cache
    must never push a shape over Mosaic's scoped-VMEM limit.  At k=2048
    one f32 cache slot is 4 MiB — the sizing must budget the +1 overflow
    regen slot against the same pool (or drop the scratch entirely) so the
    kernel still compiles, degenerating to regenerate-every-step."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        fused_sparse_project,
        pallas_sparse_matrix,
    )

    x = np.random.default_rng(1).standard_normal((512, 8192)).astype(np.float32)
    R = np.asarray(pallas_sparse_matrix(7, 2048, 8192, 1 / 3))
    ref = x.astype(np.float64) @ R.astype(np.float64).T
    scale = np.std(ref)
    y32 = np.asarray(
        fused_sparse_project(jnp.asarray(x), 7, 2048, 1 / 3, mxu_mode="split2")
    )
    assert np.max(np.abs(y32 - ref)) / scale < 1e-4  # f32-grade


@requires_tpu
def test_lazy_dp_mesh_matches_single_device(x):
    """Lazy under a DP mesh must reproduce the no-mesh lazy result exactly
    (the matrix definition is row-tile- and shard-independent)."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.parallel import default_mesh

    mesh = default_mesh()  # all real chips on 'data'
    common = dict(
        n_components=32, density=1 / 3, random_state=5, backend="jax",
    )
    est_m = SparseRandomProjection(
        **common, backend_options={"mesh": mesh, "materialization": "lazy"}
    ).fit(x)
    est_1 = SparseRandomProjection(
        **common, backend_options={"materialization": "lazy"}
    ).fit(x)
    np.testing.assert_array_equal(
        np.asarray(est_m.transform(x)), np.asarray(est_1.transform(x))
    )


@requires_tpu
def test_lazy_bf16_mesh_matches_single_device():
    """bf16-fitted lazy under a DP mesh routes mxu_mode='bf16' through the
    shard_map'd kernel (the mesh-fn cache keys on the mode): result must
    equal the no-mesh bf16 lazy path exactly."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.parallel import default_mesh
    from randomprojection_tpu.utils.validation import bfloat16_dtype

    bf16 = bfloat16_dtype()
    if bf16 is None:
        pytest.skip("ml_dtypes bfloat16 unavailable")
    Xf = np.random.default_rng(6).normal(size=(128, 1024)).astype(np.float32)
    X16 = Xf.astype(bf16)
    common = dict(
        n_components=32, density=1 / 3, random_state=9, backend="jax",
    )
    est_m = SparseRandomProjection(
        **common,
        backend_options={"mesh": default_mesh(), "materialization": "lazy"},
    ).fit(X16)
    # populate the mesh-fn cache with the f32-input mode FIRST: a cache
    # key missing mxu_mode would hand the bf16 transform below the wrong
    # shard_map fn
    est_m.transform(Xf)
    est_1 = SparseRandomProjection(
        **common, backend_options={"materialization": "lazy"}
    ).fit(X16)
    Ym, Y1 = np.asarray(est_m.transform(X16)), np.asarray(est_1.transform(X16))
    assert Ym.dtype == bf16
    np.testing.assert_array_equal(Ym, Y1)


@requires_tpu
def test_lazy_tp_mesh_single_shard_matches():
    """The TP lazy code path (offset fold-in + psum) on however many real
    chips exist; with one feature shard the offset is zero and the result
    must equal the unsharded kernel bit-for-bit."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.parallel import make_mesh

    import jax

    X = np.random.default_rng(2).normal(size=(64, 1024)).astype(np.float32)
    mesh = make_mesh({"data": len(jax.devices()), "feature": 1})
    common = dict(n_components=16, density=0.25, random_state=3, backend="jax")
    est_tp = SparseRandomProjection(
        **common,
        backend_options={
            "mesh": mesh, "feature_axis": "feature", "materialization": "lazy",
        },
    ).fit(X)
    est_1 = SparseRandomProjection(
        **common, backend_options={"materialization": "lazy"}
    ).fit(X)
    np.testing.assert_array_equal(
        np.asarray(est_tp.transform(X)), np.asarray(est_1.transform(X))
    )


def test_lazy_rejects_gaussian_kind():
    from randomprojection_tpu import GaussianRandomProjection

    X = np.zeros((10, 64), dtype=np.float32)
    with pytest.raises((ValueError, RuntimeError), match="lazy"):
        GaussianRandomProjection(
            8, random_state=0, backend="jax",
            backend_options={"materialization": "lazy"},
        ).fit(X)


def test_lazy_on_cpu_fails_loudly():
    """On CPU the lazy path must refuse (the interpreter PRNG yields zero
    bits → a silent zero matrix)."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("CPU-only behavior")
    from randomprojection_tpu import SparseRandomProjection

    X = np.zeros((10, 64), dtype=np.float32)
    with pytest.raises(RuntimeError, match="requires a TPU"):
        SparseRandomProjection(
            8, random_state=0, density=0.5, backend="jax",
            backend_options={"materialization": "lazy"},
        ).fit(X)


@requires_tpu
def test_lazy_streaming_matches_transform(tmp_path):
    """Lazy materialization composes with the streaming layer: streamed
    batches (including a ragged tail) must equal one-shot transform, and a
    cursor resume must be bit-identical (the mask is a pure function of
    (seed, block) — row batching cannot change it)."""
    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.streaming import (
        ArraySource,
        StreamCursor,
        stream_to_memmap,
    )

    X = np.random.default_rng(3).normal(size=(530, 1024)).astype(np.float32)
    est = SparseRandomProjection(
        32, density=1 / 3, random_state=9, backend="jax",
        backend_options={"materialization": "lazy", "precision": "split2"},
    ).fit(X)
    ref = np.asarray(est.transform(X))

    got = np.concatenate(
        [y for _, y in est.transform_stream(ArraySource(X, 128))]
    )
    np.testing.assert_array_equal(got, ref)

    out_path = str(tmp_path / "y.npy")
    ckpt = str(tmp_path / "c.json")
    stream_to_memmap(est, ArraySource(X, 128), out_path, checkpoint_path=ckpt)
    first = np.load(out_path).copy()
    np.testing.assert_array_equal(first, ref)
    # rewind and resume: recomputation is bit-identical
    StreamCursor(rows_done=256).save(ckpt)
    stream_to_memmap(est, ArraySource(X, 128), out_path, checkpoint_path=ckpt)
    np.testing.assert_array_equal(np.load(out_path), first)


def test_auto_block_n_shape_aware():
    """block_n=None resolves to the largest row tile that (a) fits scoped
    VMEM (a 2048-row tile measurably exceeds Mosaic's limit; large k
    shrinks the budget), (b) pads no extra rows vs the 256 baseline, and
    (c) never starves a mask cache that is full at the baseline."""
    from randomprojection_tpu.ops.pallas_kernels import (
        _VMEM_LIMIT,
        _auto_block_n,
        _reserved_bytes,
    )

    # headline shapes: full cache at every tile -> largest wins
    assert _auto_block_n(131072, 4096, 256, "split2") == 1024
    assert _auto_block_n(131072, 4096, 256, "bf16") == 1024
    assert _auto_block_n(131072, 4096, 256, "f32") == 1024
    # k=2048: only the 256 tile fits VMEM
    bn = _auto_block_n(131072, 4096, 2048, "f32")
    assert bn == 256
    assert _reserved_bytes(bn, 2048, "f32", 4) <= _VMEM_LIMIT
    # small batches: one tile, no over-padding past the sublane multiple
    assert _auto_block_n(100, 4096, 256, "f32") == 104
    assert _auto_block_n(8, 4096, 256, "f32") == 8
    # padding guard: bucketed row counts must not balloon (1280 is a real
    # row_bucket output; 1024/512 would pad it to 2048/1536)
    assert _auto_block_n(1280, 4096, 256, "f32") == 256
    assert _auto_block_n(600, 4096, 256, "f32") == 256  # base pads to 768
    # cache guard: k=512 d=4096 has a FULL 8-block cache at 256 but a
    # starved one at 1024 -> settle on 512 (full cache, bigger tile)
    assert _auto_block_n(131072, 4096, 512, "split2") == 512
    # partial cache either way (d=16384: 32 blocks never fit) -> largest
    # tile wins (measured faster: fewer grid rows regenerating)
    assert _auto_block_n(16384, 16384, 512, "split2") == 1024


@requires_tpu
def test_no_cache_fallback_is_value_identical():
    """The VMEM-safety degeneration (ADVICE r4: retry with the mask cache
    disabled when an untested shape blows scoped VMEM) must not change
    values: the (seed, block) mask streams are cache-independent."""
    import jax.numpy as jnp

    from randomprojection_tpu.ops import pallas_kernels as pk

    x = np.random.default_rng(5).normal(size=(700, 900)).astype(np.float32)
    k = 64
    key = ((700, 900), None, k, "split2")
    ref = np.asarray(
        pk.fused_sparse_project(jnp.asarray(x), 3, k, 0.25, mxu_mode="split2")
    )
    pk._NO_CACHE_KEYS.add(key)
    try:
        got = np.asarray(
            pk.fused_sparse_project(
                jnp.asarray(x), 3, k, 0.25, mxu_mode="split2"
            )
        )
    finally:
        pk._NO_CACHE_KEYS.discard(key)
    np.testing.assert_array_equal(ref, got)
