"""Telemetry spine tests (ISSUE r7): metrics registry, JSONL event log
round-trip, tail-safe bench compact line, and the regression tripwire.

Schema-validation contract (tier-1): the FINAL stdout line of a bench
invocation is a self-contained ≤2 KB JSON summary carrying the headline
mode record, per-config digests and a ``regressions`` key computed
against the newest committed ``BENCH_r*.json`` — and every committed
``BENCH_r*.json`` must keep parsing through the shipped loader.
"""

import glob
import json
import pathlib
import threading

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import benchmark
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.observability import StreamStats, batch_nbytes
from randomprojection_tpu.utils.telemetry import (
    MetricsRegistry,
    TelemetryLog,
    parse_event,
    read_events,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _no_global_sink():
    """Tests that configure the process-wide sink must not leak it."""
    yield
    telemetry.shutdown()


# -- MetricsRegistry ---------------------------------------------------------


def test_registry_counters_and_gauges():
    r = MetricsRegistry()
    assert r.counter("x") == 0
    r.counter_inc("x")
    r.counter_inc("x", 4)
    assert r.counter("x") == 5
    r.gauge_set("q", 2)
    r.gauge_set("q", 7)
    r.gauge_set("q", 3)
    assert r.gauge_max("q") == 7
    assert r.gauge_mean("q") == pytest.approx(4.0)
    assert r.gauge("q")["last"] == 3
    # unset gauge reads as zeros, not KeyError
    assert r.gauge_max("nope") == 0 and r.gauge_mean("nope") == 0.0


def test_registry_log2_histogram_buckets():
    """Fixed log2 buckets: bucket i holds [2^i, 2^(i+1)) microseconds,
    sub-microsecond samples clamp into bucket 0, and the exact sum rides
    along (the StreamStats stage-wall contract is the SUM, buckets are
    only distribution shape)."""
    r = MetricsRegistry()
    r.observe("t", 1.5e-6)   # bucket 0: [1us, 2us)
    r.observe("t", 3.0e-6)   # bucket 1: [2us, 4us)
    r.observe("t", 0.4e-6)   # clamps to bucket 0
    r.observe("t", 1.5)      # [~1s, ~2s) = bucket 20
    snap = r.snapshot()["histograms"]["t"]
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(1.5 + 1.5e-6 + 3.0e-6 + 0.4e-6)
    assert snap["buckets"]["0"] == 2
    assert snap["buckets"]["1"] == 1
    assert snap["buckets"]["20"] == 1
    assert r.hist_sum("t") == pytest.approx(snap["sum"])


def test_registry_snapshot_is_plain_json():
    r = MetricsRegistry()
    r.counter_inc("a", 2)
    r.gauge_set("g", 1.5)
    with r.timer("w"):
        pass
    snap = r.snapshot()
    assert json.loads(json.dumps(snap)) == snap


# -- JSONL event log ---------------------------------------------------------


def test_event_log_round_trips_through_parser(tmp_path):
    p = str(tmp_path / "t.jsonl")
    log = TelemetryLog(p)
    log.emit("unit.test", a=1, b="x", nested={"k": [1, 2]})
    log.emit("unit.other")
    log.close()
    events = list(read_events(p))
    assert [e["event"] for e in events] == ["unit.test", "unit.other"]
    assert events[0]["v"] == telemetry.SCHEMA_VERSION
    assert events[0]["a"] == 1 and events[0]["nested"] == {"k": [1, 2]}
    assert all(isinstance(e["ts"], float) for e in events)


def test_event_parser_rejects_garbage_and_wrong_version():
    with pytest.raises(ValueError, match="JSON"):
        parse_event("not json at all")
    with pytest.raises(ValueError, match="version"):
        parse_event(json.dumps({"v": 99, "ts": 0.0, "event": "x"}))
    with pytest.raises(ValueError, match="event"):
        parse_event(json.dumps({"v": 1, "ts": 0.0}))
    with pytest.raises(ValueError, match="object"):
        parse_event("[1, 2]")


def test_read_events_tolerates_torn_final_line_only(tmp_path):
    """A crash mid-write can tear at most the LAST line — tolerated; a
    torn line mid-file means corruption and must raise."""
    good = json.dumps({"v": 1, "ts": 0.0, "event": "a"})
    p = tmp_path / "torn_tail.jsonl"
    p.write_text(good + "\n" + good + "\n" + good[: len(good) // 2])
    assert [e["event"] for e in read_events(str(p))] == ["a", "a"]
    p2 = tmp_path / "torn_mid.jsonl"
    p2.write_text(good[: len(good) // 2] + "\n" + good + "\n")
    with pytest.raises(ValueError):
        list(read_events(str(p2)))


def test_reopened_sink_repairs_torn_tail(tmp_path):
    """Appending a second run onto a file the first run left torn must
    not merge the fragment with the new run's first event: the whole
    multi-run file stays readable end to end."""
    good = json.dumps({"v": 1, "ts": 0.0, "event": "run1"})
    p = tmp_path / "multi.jsonl"
    # crash left a genuinely torn fragment: it is dropped on reopen
    p.write_text(good + "\n" + good[: len(good) // 2])
    log = TelemetryLog(str(p))
    log.emit("run2")
    log.close()
    assert [e["event"] for e in read_events(str(p))] == ["run1", "run2"]
    # crash lost only the newline: the complete event is kept
    p2 = tmp_path / "unterminated.jsonl"
    p2.write_text(good + "\n" + good)  # no trailing \n
    log = TelemetryLog(str(p2))
    log.emit("run2")
    log.close()
    assert [e["event"] for e in read_events(str(p2))] == [
        "run1", "run1", "run2"
    ]


def test_repair_never_truncates_foreign_files(tmp_path):
    """--telemetry-jsonl pointed at an existing NON-telemetry file with no
    trailing newline must not destroy its content — the repair only drops
    a torn fragment when the file is provably our own log."""
    p = tmp_path / "results.json"
    p.write_text('{"my": "precious", "data": [1, 2, 3]}')  # no trailing \n
    log = TelemetryLog(str(p))
    log.emit("appended")
    log.close()
    content = p.read_text()
    assert content.startswith('{"my": "precious"')  # preserved
    assert '"event":"appended"' in content
    # a lone torn FIRST event (sink's own prefix) is still cleaned up
    p2 = tmp_path / "fresh.jsonl"
    p2.write_text('{"v":1,"ts":123.0,"eve')  # torn mid-first-event
    log = TelemetryLog(str(p2))
    log.emit("only")
    log.close()
    assert [e["event"] for e in read_events(str(p2))] == ["only"]


def test_emit_is_noop_without_sink(tmp_path):
    telemetry.shutdown()
    telemetry.emit("never.lands", x=1)  # must not raise
    p = str(tmp_path / "s.jsonl")
    telemetry.configure(p)
    assert telemetry.enabled()
    telemetry.emit("lands", x=1)
    telemetry.shutdown()
    assert not telemetry.enabled()
    telemetry.emit("after.shutdown")  # dropped
    assert [e["event"] for e in read_events(p)] == ["lands"]


# -- instrumented pipeline end-to-end (the --telemetry-jsonl acceptance) -----


def test_cli_project_telemetry_jsonl_round_trips(tmp_path):
    """A CLI run with --telemetry-jsonl produces a JSONL event log whose
    events round-trip through the shipped parser, and the stream's
    stage/commit/dispatch events are all present."""
    from randomprojection_tpu import cli

    X = np.random.default_rng(0).normal(size=(300, 64)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    yout = str(tmp_path / "y.npy")
    tel = str(tmp_path / "events.jsonl")
    np.save(xin, X)
    cli.main([
        "project", "--input", xin, "--output", yout,
        "--kind", "gaussian", "--n-components", "8",
        "--backend", "numpy", "--batch-rows", "100",
        "--telemetry-jsonl", tel, "--log-level", "warning",
    ])
    events = list(read_events(tel))
    kinds = {e["event"] for e in events}
    assert {"stream.dispatch", "stream.commit", "stage.wall"} <= kinds
    commits = [e for e in events if e["event"] == "stream.commit"]
    assert sum(e["rows"] for e in commits) == 300
    assert all(e["v"] == telemetry.SCHEMA_VERSION for e in events)


def test_prefetch_token_stream_emits_producer_events(tmp_path):
    """The overlapped pipeline's producer side emits delivery + hash
    events; the consumer side emits dispatch/commit — all into one file,
    interleaved from two threads, every line parseable."""
    from randomprojection_tpu.models.sketch import CountSketch
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.streaming import (
        PrefetchSource,
        TokenSource,
        stream_transform,
    )

    tel = str(tmp_path / "ev.jsonl")
    telemetry.configure(tel)
    words = np.asarray([f"w{i}" for i in range(500)])

    def read_tokens(lo, hi):
        rng = np.random.default_rng(lo + 1)
        toks = words[rng.integers(0, len(words), size=(hi - lo) * 8)]
        return toks, np.arange(0, (hi - lo) * 8 + 1, 8)

    fh = FeatureHasher(1 << 12, input_type="string", dtype=np.float32)
    stats = StreamStats()
    source = PrefetchSource(
        TokenSource(read_tokens, 96, fh, batch_rows=32, stats=stats),
        depth=2, stats=stats,
    )
    cs = CountSketch(16, random_state=0, backend="numpy").fit_source(source)
    rows = sum(
        y.shape[0] for _, y in stream_transform(cs, source, stats=stats)
    )
    telemetry.shutdown()
    assert rows == 96
    events = list(read_events(tel))
    kinds = {e["event"] for e in events}
    assert {"stream.prefetch.deliver", "hash.batch", "stage.wall",
            "stream.dispatch", "stream.commit"} <= kinds
    hash_events = [e for e in events if e["event"] == "hash.batch"]
    assert all(e["path"] in ("strided", "list", "python")
               for e in hash_events)
    deliveries = [e for e in events if e["event"] == "stream.prefetch.deliver"]
    assert len(deliveries) == 3  # one per produced batch
    assert all(0 <= e["queue_depth"] <= 2 for e in deliveries)


def test_vmem_oom_retry_recorder_shared(tmp_path):
    """Both degraded-retry call sites (eager Pallas fallback, mesh path)
    go through one recorder: one counter name, one event schema."""
    from randomprojection_tpu.ops.pallas_kernels import record_vmem_oom_retry

    tel = str(tmp_path / "oom.jsonl")
    telemetry.configure(tel)
    before = telemetry.registry().counter("backend.vmem_oom_retries")
    record_vmem_oom_retry((128, 4096), "split2", 256)
    telemetry.shutdown()
    assert telemetry.registry().counter(
        "backend.vmem_oom_retries"
    ) == before + 1
    (ev,) = read_events(tel)
    assert ev["event"] == "backend.vmem_oom_retry"
    assert ev["shape"] == [128, 4096] and ev["mxu_mode"] == "split2"


def test_simhash_query_dispatch_counters():
    jnp = pytest.importorskip("jax.numpy")  # noqa: F841
    from randomprojection_tpu.models.sketch import SimHashIndex

    rng = np.random.default_rng(0)
    codes = rng.integers(0, 256, size=(100, 4), dtype=np.uint8)
    idx = SimHashIndex(codes)
    idx.add(rng.integers(0, 256, size=(50, 4), dtype=np.uint8))
    before = telemetry.registry().counter("simhash.chunk_dispatches")
    idx.query(codes[:8], tile=4)  # 2 tiles × 2 chunks
    assert telemetry.registry().counter(
        "simhash.chunk_dispatches"
    ) == before + 4
    before = telemetry.registry().counter("simhash.chunk_dispatches")
    idx.query_topk(codes[:4], 3, tile=4)  # 1 tile × 2 chunks
    assert telemetry.registry().counter(
        "simhash.chunk_dispatches"
    ) == before + 2


# -- StreamStats edge cases (satellite) --------------------------------------


def test_stream_stats_overlap_ratio_zero_elapsed():
    s = StreamStats()
    assert s.overlap_ratio() == 0.0  # nothing recorded at all
    with s.stage("hash"):
        pass
    # stage wall exists but no commits → elapsed 0 → ratio clamps to 0
    assert s.elapsed_s() == 0.0
    assert s.overlap_ratio() == 0.0


def test_stream_stats_on_commit_without_start():
    s = StreamStats()
    s.on_commit(0, 128, np.zeros((4, 8), dtype=np.float32))
    assert s.batches == 1 and s.rows == 4 and s.bytes_in == 128
    assert s.bytes_out == 4 * 8 * 4
    # the degraded clock must yield a finite, sane rate — not inf/1e18
    assert np.isfinite(s.rows_per_s()) and s.rows_per_s() < 1e10
    assert "rows_per_s" in s.summary()


def test_stream_stats_concurrent_stage_writers():
    """Producer and consumer threads attribute stages concurrently; no
    sample may be lost and per-stage totals must be non-negative."""
    s = StreamStats()
    n_iter = 400

    def worker(name):
        for _ in range(n_iter):
            with s.stage(name):
                pass

    threads = [
        threading.Thread(target=worker, args=(nm,))
        for nm in ("producer", "consumer")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = s.registry.snapshot()["histograms"]
    assert snap["stage.producer"]["count"] == n_iter
    assert snap["stage.consumer"]["count"] == n_iter
    assert set(s.stage_wall) == {"producer", "consumer"}
    assert all(v >= 0.0 for v in s.stage_wall.values())


def test_batch_nbytes_lil_dok_estimate_formula():
    """LIL/DOK have no flat payload arrays: the estimate is the
    COO-equivalent nnz·(itemsize + 2·intp) — never the 8-pointer-bytes-
    per-row (LIL) or 0 (DOK) silent undercount."""
    dense = np.zeros((32, 16), dtype=np.float32)
    dense[::2, ::4] = 2.0
    nnz = int((dense != 0).sum())
    expect = nnz * (4 + 2 * np.dtype(np.intp).itemsize)
    assert batch_nbytes(sp.lil_array(dense)) == expect
    assert batch_nbytes(sp.dok_array(dense)) == expect
    # and the estimate tracks the dtype's itemsize
    assert batch_nbytes(sp.lil_array(dense.astype(np.float64))) == nnz * (
        8 + 2 * np.dtype(np.intp).itemsize
    )


def test_stream_stats_summary_keys_unchanged():
    """The registry re-base must not change the summary() surface."""
    s = StreamStats()
    s.start()
    with s.stage("dispatch"):
        pass
    s.on_queue_depth(1)
    s.on_commit(0, 64, np.zeros((2, 4), dtype=np.float32))
    out = s.summary()
    assert set(out) == {
        "batches", "rows", "bytes_in", "bytes_out", "elapsed_s",
        "rows_per_s", "stage_wall_s", "pipeline_overlap_ratio",
        "queue_depth_max", "queue_depth_mean",
    }


# -- regression tripwire -----------------------------------------------------


def _rec(**over):
    rec = {
        "value": 1000.0, "mode": "m", "timing_suspect": False,
        "all_modes": {
            "m": {"rows_per_s": 1000.0, "distortion": 1e-6,
                  "timing_suspect": False},
        },
        "config1": {"rows_per_s": 500.0, "host_suspect": False},
        "config5": {"end_to_end_docs_per_s": 100.0,
                    "ingest_tokens_per_s": 1e6,
                    "pipeline_timing_suspect": False,
                    "ingest_host_suspect": False},
    }
    rec.update(over)
    return rec


def test_compute_regressions_flags_only_real_drops():
    prev = _rec()
    cur = _rec(
        value=860.0,
        all_modes={"m": {"rows_per_s": 860.0, "distortion": 1e-6,
                         "timing_suspect": False}},
        config1={"rows_per_s": 495.0, "host_suspect": False},  # -1%: fine
    )
    regs = benchmark.compute_regressions(cur, prev)
    names = {r["metric"] for r in regs}
    # the headline entry dedupes into the per-mode entry (same mode both
    # rounds, identical numbers)
    assert names == {"mode.m.rows_per_s"}
    r = regs[0]
    assert r["drop_pct"] == pytest.approx(14.0)
    assert r["previous"] == 1000.0 and r["current"] == 860.0


def test_compute_regressions_skips_suspect_rates_both_sides():
    prev = _rec()
    prev["config1"]["host_suspect"] = True  # previous side self-flagged
    cur = _rec(
        config1={"rows_per_s": 100.0, "host_suspect": False},  # -80% but…
        config5={"end_to_end_docs_per_s": 10.0,  # -90% but current suspect
                 "ingest_tokens_per_s": 1e6,
                 "pipeline_timing_suspect": True,
                 "ingest_host_suspect": False},
    )
    assert benchmark.compute_regressions(cur, prev) == []


def test_serial_e2e_rate_gated_on_its_own_suspect_flag():
    """A cache-served pipelined sample (pipeline_timing_suspect=True)
    must not exclude the independently-measured SERIAL rate from the
    tripwire — and a suspect serial sample must not become a baseline."""
    c5 = {"end_to_end_serial_docs_per_s": 100.0,
          "pipeline_timing_suspect": True,  # pipelined run disowned
          "serial_timing_suspect": False}
    assert benchmark.bench_rates({"config5": c5})[
        "config5.end_to_end_serial_docs_per_s"
    ] == (100.0, False)
    c5["serial_timing_suspect"] = True
    assert benchmark.bench_rates({"config5": c5})[
        "config5.end_to_end_serial_docs_per_s"
    ] == (100.0, True)


def test_bench_rates_reads_flattened_compact_topk_rate():
    """A previous round surviving only as its compact line flattens
    topk_serving.queries_per_s to config4.topk_queries_per_s — the
    tripwire must still compare the serving rate against it."""
    prev = {"config4": {"rows_per_s": 5e7, "timing_suspect": False,
                        "topk_queries_per_s": 1687.0}}
    assert benchmark.bench_rates(prev)["config4.topk.queries_per_s"] == (
        1687.0, False
    )
    cur = {"config4": {"rows_per_s": 5e7, "timing_suspect": False,
                       "topk_serving": {"queries_per_s": 800.0,
                                        "timing_suspect": False}}}
    regs = benchmark.compute_regressions(cur, prev)
    assert any(r["metric"] == "config4.topk.queries_per_s" for r in regs)
    # the nested record wins when both shapes are present
    both = {"config4": {"topk_queries_per_s": 1.0, "timing_suspect": False,
                        "topk_serving": {"queries_per_s": 2.0,
                                         "timing_suspect": False}}}
    assert benchmark.bench_rates(both)["config4.topk.queries_per_s"] == (
        2.0, False
    )
    # the serving bench's OWN suspect flag survives compaction and gates
    # the fallback — a suspect serving rate never becomes a baseline
    rec = {"config4": {"rows_per_s": 1.0, "timing_suspect": False,
                       "topk_serving": {"queries_per_s": 9.9,
                                        "timing_suspect": True}}}
    c = benchmark.compact_summary(rec)
    assert c["config4"]["topk_timing_suspect"] is True
    assert benchmark.bench_rates(c)["config4.topk.queries_per_s"] == (
        9.9, True
    )


def test_compute_regressions_exact_threshold_not_flagged():
    prev = _rec()
    cur = _rec(
        value=900.0,
        all_modes={"m": {"rows_per_s": 900.0, "distortion": 1e-6,
                         "timing_suspect": False}},
    )
    # exactly 10% is the boundary, only STRICTLY beyond trips
    assert benchmark.compute_regressions(cur, prev) == []


def test_attach_regressions_gates_on_preset_and_shape():
    rec = _rec(preset="smoke", shape_is_default=True)
    out = benchmark.attach_regressions(rec)
    assert out["regressions"] == [] and "regressions_skipped" in out
    rec = _rec(preset="full", shape_is_default=False)
    out = benchmark.attach_regressions(rec)
    assert out["regressions"] == [] and "regressions_skipped" in out


def test_compute_regressions_dedupes_headline_same_mode():
    """Same mode headlining both rounds: its drop is listed once (the
    per-mode entry), not twice with identical numbers."""
    prev = _rec(mode="m")
    cur = _rec(
        mode="m", value=800.0,
        all_modes={"m": {"rows_per_s": 800.0, "distortion": 1e-6,
                         "timing_suspect": False}},
    )
    names = [r["metric"] for r in benchmark.compute_regressions(cur, prev)]
    assert names == ["mode.m.rows_per_s"]
    # a headline-mode CHANGE keeps the headline entry: the flagship rate
    # moved for selection reasons worth flagging
    cur2 = _rec(
        mode="other", value=800.0,
        all_modes={"other": {"rows_per_s": 800.0, "distortion": 1e-6,
                             "timing_suspect": False}},
    )
    names2 = [r["metric"] for r in benchmark.compute_regressions(cur2, prev)]
    assert "headline.rows_per_s" in names2


def test_attach_regressions_falls_back_past_garbage_newest(tmp_path):
    """A round whose bench crashed (unusable newest BENCH file) must not
    turn the tripwire off — the next-newest intact record is used."""
    good = {"config1": {"rows_per_s": 1000.0, "host_suspect": False}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(good))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "cmd": "", "rc": 1, "tail": "Traceback …", "parsed": None}
    ))
    rec = _rec(
        preset="full", shape_is_default=True,
        config1={"rows_per_s": 700.0, "host_suspect": False},
    )
    out = benchmark.attach_regressions(rec, root=str(tmp_path))
    assert out["regressions_vs"] == "BENCH_r01.json"
    assert any(
        r["metric"] == "config1.rows_per_s" for r in out["regressions"]
    )


def test_attach_regressions_compares_against_committed_record():
    """The real tripwire path: a full-preset record 20% under the newest
    committed BENCH file must come back with that drop on file."""
    newest = benchmark.newest_committed_bench(str(REPO))
    assert newest is not None
    prev = benchmark.load_bench_record(newest)
    prev_rate = prev["config1"]["rows_per_s"]
    rec = _rec(
        preset="full", shape_is_default=True,
        config1={"rows_per_s": prev_rate * 0.8, "host_suspect": False},
    )
    out = benchmark.attach_regressions(rec, root=str(REPO))
    assert out["regressions_vs"] == pathlib.Path(newest).name
    assert any(
        r["metric"] == "config1.rows_per_s"
        and r["drop_pct"] == pytest.approx(20.0, abs=0.2)
        for r in out["regressions"]
    )


# -- committed BENCH records keep parsing ------------------------------------


def test_tail_recovery_keeps_headline_suspect_flag():
    """An all-suspect recovered run must not become a trusted baseline:
    the re-derived headline inherits its mode's own suspect flag."""
    tail = (
        '"xmode": {"rows_per_s": 5e7, "distortion": 1e-06, '
        '"timing_suspect": true}}'
    )
    rec = benchmark.recover_bench_tail(tail)
    assert rec["timing_suspect"] is True
    assert benchmark.bench_rates(rec)["headline.rows_per_s"] == (5e7, True)


def test_all_committed_bench_files_parse():
    files = sorted(glob.glob(str(REPO / "BENCH_r*.json")))
    assert files, "no committed BENCH_r*.json"
    for path in files:
        rec = benchmark.load_bench_record(path)
        assert isinstance(rec, dict)
        rates = benchmark.bench_rates(rec)
        assert rates, f"{path} yielded no comparable rates"
        for name, (v, sus) in rates.items():
            assert v > 0 and isinstance(sus, bool), (path, name)


def test_load_bench_record_prefers_compact_line(tmp_path):
    """A wrapper whose full line is front-truncated but whose tail keeps
    the intact compact summary must be served from the compact line."""
    compact = {
        benchmark.COMPACT_MARKER: benchmark.COMPACT_SCHEMA_VERSION,
        "metric": "rows/sec/chip", "mode": "lazy_split2", "value": 3.3e7,
        "all_modes": {"lazy_split2": {"rows_per_s": 3.3e7,
                                      "distortion": 3e-6,
                                      "timing_suspect": False}},
        "config1": {"rows_per_s": 1.6e6, "host_suspect": False},
        "regressions": [], "regressions_vs": "BENCH_r05.json",
    }
    # front-truncated full line (no '{"metric"' survives) + compact line
    tail = (
        '_s": 123.4, "timing_suspect": false}}\n'
        + json.dumps(compact, separators=(",", ":"))
        + "\n"
    )
    p = tmp_path / "BENCH_r98.json"
    p.write_text(json.dumps(
        {"n": 98, "cmd": "", "rc": 0, "tail": tail, "parsed": None}
    ))
    rec = benchmark.load_bench_record(str(p))
    assert rec["_from_compact_summary"]
    assert rec["mode"] == "lazy_split2" and rec["value"] == 3.3e7
    # an embedded regressions entry ({"metric": ...}) in the surviving
    # tail must NOT be mistaken for the full record — the compact line
    # still wins
    reg_entry = json.dumps({"metric": "config3.rows_per_s",
                            "previous": 3e6, "current": 2.5e6,
                            "drop_pct": 16.7})
    p2 = tmp_path / "BENCH_r97.json"
    p2.write_text(json.dumps({
        "n": 97, "cmd": "", "rc": 0, "parsed": None,
        "tail": '..._s": 1.0}, "regressions": [' + reg_entry + ']}\n'
                + json.dumps(compact, separators=(",", ":")) + "\n",
    }))
    rec2 = benchmark.load_bench_record(str(p2))
    assert rec2.get("_from_compact_summary")
    assert rec2["mode"] == "lazy_split2"
    # a driver that parses the LAST stdout line hands us the compact
    # digest as `parsed` — the intact full record in the tail still wins
    full = {"metric": "rows/sec/chip", "value": 3.3e7, "mode": "lazy_split2",
            "all_modes": {"lazy_split2": {"rows_per_s": 3.3e7,
                                          "distortion": 3e-6,
                                          "elapsed_s": 1.0,
                                          "timing_suspect": False}}}
    p3 = tmp_path / "BENCH_r96.json"
    p3.write_text(json.dumps({
        "n": 96, "cmd": "", "rc": 0, "parsed": compact,
        "tail": json.dumps(full) + "\n"
                + json.dumps(compact, separators=(",", ":")) + "\n",
    }))
    rec3 = benchmark.load_bench_record(str(p3))
    assert "_from_compact_summary" not in rec3
    assert rec3["all_modes"]["lazy_split2"]["elapsed_s"] == 1.0
    # ...and with no full record in the tail, the parsed compact is used
    p4 = tmp_path / "BENCH_r95.json"
    p4.write_text(json.dumps(
        {"n": 95, "cmd": "", "rc": 0, "parsed": compact, "tail": ""}
    ))
    rec4 = benchmark.load_bench_record(str(p4))
    assert rec4.get("_from_compact_summary") and rec4["mode"] == "lazy_split2"
    assert benchmark.bench_rates(rec)["config1.rows_per_s"] == (1.6e6, False)
    # and the doc renderer accepts a compact-derived record
    import sys as _sys

    _sys.path.insert(0, str(REPO / "docs"))
    try:
        import gen_bench_tables as g
    finally:
        _sys.path.pop(0)
    block = g.render(str(p))
    assert "compact summary" in block and "lazy_split2" in block


# -- tail-safe compact summary line (the acceptance contract) ----------------


def _full_style_record():
    """A record shaped like a real full-preset run (smoke-style values)."""
    modes = {
        n: {"rows_per_s": 1e7 * (i + 1), "distortion": 1e-6 * (i + 1),
            "elapsed_s": 0.5, "implied_tflops": 10.0 * (i + 1),
            "executed_tflops": 20.0 * (i + 1), "mxu_utilization": 0.1,
            "harness_hbm_cap_rows_per_s": 4.4e7, "timing_suspect": False}
        for i, n in enumerate(
            ("bf16", "bf16_split2", "f32_high", "lazy", "lazy_split2",
             "lazy_bf16")
        )
    }
    return {
        "metric": "rows/sec/chip 4096->256 (Achlioptas s=3, data-resident, "
                  "lazy_split2)",
        "value": 5e7, "unit": "rows/s", "vs_baseline": 12.3,
        "cpu_baseline_rows_per_s": 4.8e6,
        "distortion_eps_vs_cpu": 3.1e-6, "mode": "lazy_split2",
        "all_modes": modes, "rows_timed": 100663296,
        "implied_tflops": 70.4, "timing_suspect": False,
        "elapsed_pass_invariant": False, "checksum": 61.5,
        "config1": {"workload": "w", "rows_per_s": 1.6e6,
                    "trial_spread": 1.1, "trials": 3, "host_suspect": False},
        "config3": {"workload": "w3", "rows_per_s": 2.9e6,
                    "distortion": 1.9e-6, "executed_tflops": 96.6,
                    "mxu_utilization": 0.491, "timing_suspect": False},
        "config4": {"workload": "w4", "rows_per_s": 5.3e7,
                    "raw_kernel_rows_per_s": 6.4e7, "estimator_vs_raw": 0.83,
                    "sign_mismatch_rate_vs_cpu": 0.0,
                    "timing_suspect": False,
                    "topk_serving": {"index_codes": 1 << 24, "m": 16,
                                     "queries_per_s": 1687.3,
                                     "timing_suspect": False,
                                     "d2h_bytes_per_query": 128,
                                     "dense_d2h_bytes_per_query": 1 << 26,
                                     "executed_tflops": 14.5,
                                     "mxu_utilization": 0.074}},
        "config5": {"ingest_tokens_per_s": 7.2e6,
                    "ingest_host_suspect": False,
                    "device_sketch_docs_per_s": 8.4e5,
                    "sketch_timing_suspect": False,
                    "end_to_end_docs_per_s": 1.58e4,
                    "end_to_end_serial_docs_per_s": 1.2e4,
                    "pipeline_timing_suspect": False},
        "preset": "full", "shape_is_default": True,
    }


def test_compact_line_schema_from_bench_style_invocation(capsys):
    """Drive the real output path (cli bench → emit_bench_output) with a
    measured-shaped record and validate the FINAL stdout line: ≤2 KB,
    self-contained, headline mode record, per-config digests, and the
    regressions tripwire computed against the newest committed BENCH."""
    from randomprojection_tpu import cli

    rec = benchmark.attach_regressions(_full_style_record(), root=str(REPO))
    orig_run = benchmark.run
    benchmark.run = lambda *a, **k: rec
    try:
        cli.main(["bench", "--preset", "smoke"])
    finally:
        benchmark.run = orig_run
    lines = capsys.readouterr().out.splitlines()
    assert len(lines) == 2
    # line 1: the full record, intact
    assert json.loads(lines[0])["mode"] == "lazy_split2"
    # FINAL line: the compact summary
    raw = lines[-1]
    assert len(raw.encode()) <= benchmark.COMPACT_MAX_BYTES
    c = json.loads(raw)
    assert c[benchmark.COMPACT_MARKER] == benchmark.COMPACT_SCHEMA_VERSION
    # headline mode record
    assert c["mode"] == "lazy_split2"
    assert c["value"] == pytest.approx(5e7)
    assert c["all_modes"]["lazy_split2"]["rows_per_s"] == pytest.approx(5e7)
    assert c["all_modes"]["lazy_split2"]["timing_suspect"] is False
    # per-config digests
    assert c["config1"]["rows_per_s"] == pytest.approx(1.6e6)
    assert c["config4"]["estimator_vs_raw"] == pytest.approx(0.83)
    assert c["config4"]["topk_queries_per_s"] == pytest.approx(1687.0, abs=1)
    assert c["config5"]["end_to_end_docs_per_s"] == pytest.approx(1.58e4)
    # the tripwire key is ALWAYS present and names its baseline
    assert "regressions" in c and isinstance(c["regressions"], list)
    assert c["regressions_vs"] == pathlib.Path(
        benchmark.newest_committed_bench(str(REPO))
    ).name
    # round-trip: the compact line is loadable as a bench record
    assert benchmark.find_compact_line(raw) == c


def test_compact_summary_of_minimal_record_stays_valid():
    c = benchmark.compact_summary({"metric": "fake", "value": 1})
    assert c[benchmark.COMPACT_MARKER] == benchmark.COMPACT_SCHEMA_VERSION
    assert c["regressions"] == []
    assert len(json.dumps(c).encode()) <= benchmark.COMPACT_MAX_BYTES
