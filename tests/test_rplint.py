"""rplint (ISSUE r10, grown flow-sensitive in ISSUE 11, concurrency-
aware in ISSUE 12, lifecycle/durability/degraded-path-aware in ISSUE
20): every rule against its known-bad fixture, the pragma grammar
(continuation lines, multi-rule pragmas, stale detection), the
registry drift check, the stable --json schema (v4: wall_s + the
process-pool fan-out's deterministic ordering), the exit-code contract
(findings→1, clean→0, internal error→2), baseline diffing +
--update-baseline rewriting, SARIF 2.1.0 output, the RP04/RP08 dedupe,
and — the acceptance gate — that the shipped tree (including all four
thread/queue substrates under RP10/RP11 and the RP12/RP13/RP14
contracts) lints clean through the real `cli lint` entry point with
zero non-baselined findings."""

import json
import os

import pytest

from randomprojection_tpu import cli
from randomprojection_tpu.analysis import rplint

FIXTURES = os.path.join(os.path.dirname(__file__), "rplint_fixtures")


def _lint_fixture(name, relpath=None, registry=None):
    with open(os.path.join(FIXTURES, name)) as f:
        src = f.read()
    return rplint.lint_source(src, relpath or name, registry=registry)


def _split(findings):
    return (
        [f for f in findings if not f.suppressed],
        [f for f in findings if f.suppressed],
    )


# -- per-rule fixtures -------------------------------------------------------


def test_rp00_malformed_pragmas():
    active, suppressed = _split(_lint_fixture("rp00_bad.py"))
    assert [f.rule for f in active] == ["RP00", "RP00", "RP00"]
    assert not suppressed  # pragma hygiene is not suppressible
    msgs = " | ".join(f.message for f in active)
    assert "reason required" in msgs and "unknown rule" in msgs


def test_rp01_span_balance():
    active, suppressed = _split(_lint_fixture("rp01_bad.py"))
    assert [f.rule for f in active] == ["RP01", "RP01", "RP01"]
    # straight-line end, discarded handle, hand-rolled span event —
    # and nothing from the balanced/escaping functions
    msgs = [f.message for f in active]
    assert sum("neither escapes" in m for m in msgs) == 2
    assert sum("span event" in m for m in msgs) == 1
    assert [f.rule for f in suppressed] == ["RP01"]
    assert suppressed[0].reason.startswith("fixture:")


def test_rp02_event_registry():
    reg = rplint.EventRegistry(
        events={"GOOD": "good.event"}, families=("fam.",), lines={}
    )
    active, suppressed = _split(
        _lint_fixture("rp02_bad.py", registry=reg)
    )
    errors = [f for f in active if f.severity == "error"]
    infos = [f for f in active if f.severity == "info"]
    assert [f.rule for f in errors] == ["RP02", "RP02", "RP02"]
    msgs = " | ".join(f.message for f in errors)
    assert "'rogue.event'" in msgs
    assert "EVENTS.NOPE" in msgs
    assert "'other.'" in msgs
    # the Name-argument emit that r10 skipped silently is now counted
    assert len(infos) == 1 and "unresolvable-emit" in infos[0].message
    assert [f.rule for f in suppressed] == ["RP02"]
    # without a registry (standalone file lint) the rule stays silent
    assert _lint_fixture("rp02_bad.py", registry=None) == []


def test_rp03_hot_path_host_syncs():
    active, suppressed = _split(
        _lint_fixture("rp03_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP03"] * 4
    msgs = " | ".join(f.message for f in active)
    for probe in ("np.asarray", "block_until_ready", "float()",
                  "jax.device_get"):
        assert probe in msgs
    assert [f.rule for f in suppressed] == ["RP03"]
    # the same code outside a hot module is not RP03's business
    assert _lint_fixture("rp03_bad.py") == []


def test_rp04_thread_hygiene():
    active, suppressed = _split(_lint_fixture("rp04_bad.py"))
    assert [f.rule for f in active] == ["RP04", "RP04", "RP04"]
    msgs = " | ".join(f.message for f in active)
    assert "daemon=" in msgs and "unbounded" in msgs
    # ISSUE 20 satellite: SimpleQueue has no maxsize at all — it is
    # flagged as unbounded-by-construction, distinct from Queue()
    assert "SimpleQueue" in msgs and "by construction" in msgs
    assert [f.line for f in active] == [8, 9, 10]
    assert [f.rule for f in suppressed] == ["RP04"]

    nojoin = _lint_fixture("rp04_nojoin.py")
    assert [f.rule for f in nojoin] == ["RP04"]
    assert "no .join(" in nojoin[0].message


def test_rp05_determinism_in_ops():
    active, suppressed = _split(
        _lint_fixture("rp05_bad.py", relpath="ops/fixture.py")
    )
    assert [f.rule for f in active] == ["RP05"] * 3
    msgs = " | ".join(f.message for f in active)
    assert "time.time()" in msgs
    assert "random.random()" in msgs
    assert "np.random.rand" in msgs
    assert [f.rule for f in suppressed] == ["RP05"]
    assert _lint_fixture("rp05_bad.py") == []  # outside ops/: silent


def test_rp06_silent_swallow():
    active, suppressed = _split(
        _lint_fixture("rp06_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP06"]
    assert "swallows" in active[0].message
    assert [f.rule for f in suppressed] == ["RP06"]
    assert _lint_fixture("rp06_bad.py") == []  # outside the pipeline set


def test_rp02_unregistered_recovery_event_fixture():
    """ISSUE 6 satellite: an unregistered ``recover.*`` emit is caught
    against the REAL shipped registry — the recovery namespace has no
    family prefix, so each event must be individually registered, and
    the registered one in the same fixture stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("recover.resume")
    assert not real.knows("recover.rogue_replay")
    active, suppressed = _split(
        _lint_fixture("rp02_recover_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'recover.rogue_replay'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_topk_kernel_event_fixture():
    """ISSUE 7 satellite: an unregistered ``topk.kernel.*`` emit is
    caught against the REAL shipped registry — the serving-kernel
    namespace has no family prefix, so each event must be individually
    registered, and the registered dispatch event in the same fixture
    stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("topk.kernel.dispatch")
    assert real.knows("topk.kernel.vmem_retry")
    assert real.knows("topk.kernel.scan_fallback")
    assert not real.knows("topk.kernel.rogue_dispatch")
    active, suppressed = _split(
        _lint_fixture("rp02_topk_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'topk.kernel.rogue_dispatch'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_dma_event_caught_against_real_registry():
    """ISSUE 9 satellite: an unregistered ``kernel.dma.*`` emit is
    caught against the REAL shipped registry — the transform-route
    namespace has no family prefix, so each event must be individually
    registered, and the registered dispatch/fallback events in the same
    fixture stay clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("kernel.dma.dispatch")
    assert real.knows("kernel.dma.fallback")
    assert real.knows("backend.dispatch_fused")
    assert not real.knows("kernel.dma.rogue_retry")
    active, suppressed = _split(
        _lint_fixture("rp02_dma_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'kernel.dma.rogue_retry'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_live_plane_events_caught():
    """ISSUE r17 satellite: rogue ``telemetry.subscriber.*`` /
    ``serve.latency.*`` / ``loadgen.*`` emits are caught against the
    REAL shipped registry — the live-plane namespaces have no family
    prefix, so each event must be individually registered, and the
    registered dropped/latency/run events in the same fixture stay
    clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None
    assert real.knows("telemetry.subscriber.dropped")
    assert real.knows("serve.latency.request")
    assert real.knows("loadgen.run")
    assert not real.knows("serve.latency.rogue_window")
    active, suppressed = _split(
        _lint_fixture("rp02_live_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"] * 3
    msgs = " | ".join(f.message for f in active)
    assert "'telemetry.subscriber.rogue_overflow'" in msgs
    assert "'serve.latency.rogue_window'" in msgs
    assert "'loadgen.rogue_tick'" in msgs
    assert not suppressed


def test_rp03_rp10_scope_includes_live_plane_modules():
    """ISSUE r17 satellite: the metrics endpoint and the load generator
    are hot/concurrency modules — their loops and threads are checked
    like the four substrates'."""
    for mod in ("utils/metrics_server.py", "loadgen.py"):
        assert mod in rplint.HOT_MODULES
        assert mod in rplint.PIPELINE_MODULES
        assert mod in rplint.CONCURRENCY_MODULES


def test_rp02_unregistered_health_event_fixture():
    """ISSUE r20 satellite: rogue ``health.*`` emits are caught against
    the REAL shipped registry — the health namespace has no family
    prefix, so each verdict/dump event must be individually registered,
    and the registered burn/dump events in the same fixture stay
    clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None
    assert real.knows("health.slo_burn")
    assert real.knows("health.stall")
    assert real.knows("health.queue_pinned")
    assert real.knows("health.degraded_spike")
    assert real.knows("health.flight_dump")
    assert not real.knows("health.rogue_burn")
    active, suppressed = _split(
        _lint_fixture("rp02_health_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"] * 2
    msgs = " | ".join(f.message for f in active)
    assert "'health.rogue_burn'" in msgs
    assert "'health.rogue_dump'" in msgs
    assert not suppressed


def test_rp03_rp10_scope_includes_health_plane_module():
    """ISSUE r20 satellite: the health engine's event fold and tick
    loop run process-long beside the serving path, and its lock is
    shared by the subscriber-dispatch and tick threads — it belongs to
    the hot, pipeline and concurrency sets."""
    assert "utils/health.py" in rplint.HOT_MODULES
    assert "utils/health.py" in rplint.PIPELINE_MODULES
    assert "utils/health.py" in rplint.CONCURRENCY_MODULES


def test_rp04_zero_and_negative_maxsize_are_unbounded():
    """Python treats any maxsize <= 0 as unbounded — every spelling of
    that must trip RP04, not just the bare constructor."""
    for spelling in ("queue.Queue()", "queue.Queue(0)",
                     "queue.Queue(maxsize=0)", "queue.Queue(maxsize=-1)"):
        fs = rplint.lint_source(f"import queue\nq = {spelling}\n", "x.py")
        assert [f.rule for f in fs] == ["RP04"], spelling
    ok = rplint.lint_source(
        "import queue\nq = queue.Queue(maxsize=8)\n", "x.py"
    )
    assert ok == []


def test_pragma_with_any_unknown_rule_suppresses_nothing():
    """allow[RP04,RP99] is void in full: the RP04 finding stays active
    (plus the RP00 for the typo) — a typo can never accept a
    violation."""
    src = (
        "import queue\n"
        "# rplint: allow[RP04,RP99] — typo'd rule voids the pragma\n"
        "q = queue.Queue()\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert {f.rule for f in fs if not f.suppressed} == {"RP00", "RP04"}
    assert not [f for f in fs if f.suppressed]


def test_drift_check_requires_the_repo_doc(tmp_path):
    """Installed layout (no docs/ next to the package): the drift check
    stands down instead of flagging every documented-only event; the
    repo layout (doc present) enforces it."""
    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "telemetry.py").write_text(
        "class EVENTS:\n    ROGUE = 'rogue.event'\n    FAMILIES = ()\n"
    )
    (pkg / "utils" / "trace_report.py").write_text("# consumes nothing\n")
    rep = rplint.lint_package(root=str(pkg))
    assert rep["ok"] is True  # no doc on disk: drift leg skipped
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "ARCHITECTURE.md").write_text("nothing here\n")
    rep2 = rplint.lint_package(root=str(pkg))
    assert rep2["ok"] is False
    assert rep2["counts"] == {"RP02": 1}
    assert "rogue.event" in rep2["findings"][-1]["message"]


# -- registry drift ----------------------------------------------------------


def test_registry_drift_check():
    reg = rplint.EventRegistry(
        events={"A": "a.event", "B": "b.event", "C": "c.event"},
        families=(),
        lines={"A": 10, "B": 11, "C": 12},
    )
    findings = rplint.check_registry_drift(
        reg,
        consumer_text="... reads EVENTS.A and also 'b.event' ...",
        doc_text="only c.event is documented here",
    )
    # A consumed by constant reference, B by literal, C documented
    assert findings == []
    findings = rplint.check_registry_drift(
        reg, consumer_text="EVENTS.A", doc_text=""
    )
    assert [(f.rule, f.line) for f in findings] == [
        ("RP02", 11), ("RP02", 12)
    ]
    assert "neither consumed" in findings[0].message


def test_real_registry_parses_statically():
    with open(os.path.join(
        rplint.package_root(), "utils", "telemetry.py"
    )) as f:
        reg = rplint.load_event_registry(f.read())
    assert reg is not None
    assert "stream.commit" in reg.events.values()
    assert "span_start" in reg.events.values()
    assert "hash.batches." in reg.families
    # the static parse agrees with the live module
    from randomprojection_tpu.utils import telemetry

    assert set(reg.events.values()) == set(telemetry._EVENT_NAMES)
    assert reg.families == telemetry.EVENTS.FAMILIES


# -- the shipped tree (acceptance gate) --------------------------------------


def test_shipped_tree_lints_clean():
    """`cli lint` exits 0 on the repo at merge time — the tentpole's
    acceptance criterion.  Every suppression in the tree must carry a
    reason (the pragma grammar guarantees it; assert anyway)."""
    report = rplint.lint_package()
    bad = [f for f in report["findings"] if not f["suppressed"]]
    assert report["ok"], "rplint findings on the shipped tree:\n" + "\n".join(
        "%s:%s: %s %s" % (f["path"], f["line"], f["rule"], f["message"])
        for f in bad
    )
    assert all(
        f["reason"] for f in report["findings"] if f["suppressed"]
    )
    assert report["files"] >= 30  # the walk saw the whole package


def test_cli_lint_exits_zero_and_json_schema(capsys):
    assert cli.main(["lint"]) == 0
    capsys.readouterr()
    assert cli.main(["lint", "--json"]) == 0
    out = capsys.readouterr().out.strip()
    rec = json.loads(out)
    assert rec["rplint"] == 4 and rec["ok"] is True
    assert set(rec) == {
        "rplint", "root", "files", "findings", "counts", "suppressed",
        "unresolvable_emits", "wall_s", "ok",
    }
    assert isinstance(rec["wall_s"], float) and rec["wall_s"] >= 0.0
    assert rec["unresolvable_emits"] == 0  # the tree emits constants only
    for f in rec["findings"]:  # the suppressed ones in the tree
        assert set(f) == {
            "rule", "path", "line", "message", "suppressed", "reason",
            "severity",
        }
        assert f["suppressed"] is True and f["severity"] == "error"


def test_cli_lint_seeded_violation(tmp_path, capsys):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import queue\nimport threading\n\n"
        "q = queue.Queue()\n"
        "t = threading.Thread(target=print)\n"
        "t.start()\n"
    )
    assert cli.main(["lint", str(bad)]) == 1
    capsys.readouterr()
    assert cli.main(["lint", "--json", str(bad)]) == 1
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["ok"] is False
    rules = {f["rule"] for f in rec["findings"]}
    assert rules == {"RP04"}
    assert rec["counts"]["RP04"] == 3  # unbounded q, no daemon=, no join
    # a pragma with a reason suppresses it, restoring exit 0
    bad.write_text(
        "import queue\n\n"
        "# rplint: allow[RP04] — test: bounded by construction elsewhere\n"
        "q = queue.Queue()\n"
    )
    capsys.readouterr()
    assert cli.main(["lint", str(bad)]) == 0


# -- trace_report's registry-drift warning (ISSUE r10 satellite) -------------


def test_trace_report_warns_on_unregistered_events(tmp_path):
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.trace_report import (
        build_report,
        render_report,
    )

    p = str(tmp_path / "t.jsonl")
    telemetry.configure(p)
    telemetry.emit(telemetry.EVENTS.STREAM_COMMIT, row=0, rows=1)
    telemetry.emit("totally.unknown", x=1)
    telemetry.emit(telemetry.EVENTS.HASH_BATCHES_FAMILY + "strided")
    telemetry.shutdown()
    report = build_report(p)
    assert report["unregistered_events"] == {"totally.unknown": 1}
    text = render_report(report)
    assert "not in the telemetry.EVENTS registry" in text
    assert "totally.unknown" in text

    # a clean file keeps the audit quiet
    p2 = str(tmp_path / "clean.jsonl")
    telemetry.configure(p2)
    telemetry.emit(telemetry.EVENTS.STREAM_COMMIT, row=0, rows=1)
    telemetry.shutdown()
    r2 = build_report(p2)
    assert r2["unregistered_events"] == {}
    assert "not in the telemetry.EVENTS registry" not in render_report(r2)


def test_registered_event_families():
    from randomprojection_tpu.utils import telemetry

    assert telemetry.registered_event("stream.commit")
    assert telemetry.registered_event("hash.batches.python")
    assert not telemetry.registered_event("hash.batch.python")
    assert not telemetry.registered_event("made.up")


def test_rp02_unregistered_shard_event_fixture():
    """ISSUE 8 satellite: an unregistered ``shard.*`` emit is caught
    against the REAL shipped registry — the sharded-tier namespaces
    (`shard.`, `serve.shard.`) have no family prefix, so each event
    must be individually registered, and the registered merge event in
    the same fixture stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("shard.merge")
    assert real.knows("shard.topk_tile")
    assert real.knows("serve.shard.batch")
    assert not real.knows("shard.rogue_merge")
    active, suppressed = _split(
        _lint_fixture("rp02_shard_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'shard.rogue_merge'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_lsh_event_fixture():
    """ISSUE 15 satellite: an unregistered ``index.lsh.*`` emit is
    caught against the REAL shipped registry — the candidate-tier
    namespace has no family prefix, so each event must be individually
    registered, and the registered dispatch event in the same fixture
    stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("index.lsh.dispatch")
    assert real.knows("index.lsh.fallback")
    assert real.knows("index.lsh.build")
    assert not real.knows("index.lsh.rogue_probe")
    active, suppressed = _split(
        _lint_fixture("rp02_lsh_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'index.lsh.rogue_probe'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_tier_event_fixture():
    """ISSUE 19 / r21 satellite: an unregistered ``index.tier.*`` emit
    is caught against the REAL shipped registry — the residency
    namespace has no family prefix, so each event must be individually
    registered, and the registered fetch event in the same fixture
    stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("index.tier.hit")
    assert real.knows("index.tier.fetch")
    assert real.knows("index.tier.evict")
    assert real.knows("index.tier.fallback")
    assert not real.knows("index.tier.rogue_prefetch")
    active, suppressed = _split(
        _lint_fixture("rp02_tier_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'index.tier.rogue_prefetch'" in active[0].message
    assert not suppressed


def test_rplint_scope_includes_tiering_module():
    """ISSUE 19 / r21 satellite: the residency manager is a
    hot/pipeline/concurrency module (its stager loop re-serializes the
    overlap if it blocks; its worker thread + manager lock are shared
    with every serving thread) and its admission planner carries a
    kernel-budget contract."""
    assert "tiering.py" in rplint.HOT_MODULES
    assert "tiering.py" in rplint.PIPELINE_MODULES
    assert "tiering.py" in rplint.CONCURRENCY_MODULES
    assert rplint.KERNEL_BUDGET_FNS.get("tiering.py") == "plan_residency"


# -- ISSUE 11: flow-sensitive rules (RP07-RP09) ------------------------------


def test_rp07_dma_fixture():
    """Kernel-module scoping: unbudgeted VMEM alloc, never-waited copy,
    conditional wait (warm-up + in-loop start), slot re-target, modulus
    mismatch — each seeded exactly once."""
    active, suppressed = _split(
        _lint_fixture("rp07_bad.py", relpath="ops/pallas_kernels.py")
    )
    assert [f.rule for f in active] == ["RP07"] * 6
    msgs = [f.message for f in active]
    joined = " | ".join(msgs)
    assert "not charged by the _reserved_bytes() budget" in joined
    assert "never waited" in joined
    assert sum("without a matching .wait() on some path" in m
               for m in msgs) == 2
    assert "re-targeted before its wait" in joined
    assert "% 4 does not match" in joined
    assert [f.rule for f in suppressed] == ["RP07"]
    assert suppressed[0].reason.startswith("fixture:")
    # outside the kernel modules the rule (and its pragma) stand down
    assert _lint_fixture("rp07_bad.py") == []


def test_rp07_real_kernels_pass_flow_checks():
    """The shipped DMA kernels (r12 topk, r14 transform) satisfy the
    copy/wait/slot discipline the parity tests previously carried
    alone — the one accepted finding is the budgeted-by-construction
    cache allocation, pragma'd with its reason."""
    root = rplint.package_root()
    reg = rplint.load_event_registry(
        open(os.path.join(root, "utils", "telemetry.py")).read()
    )
    for rel in ("ops/topk_kernels.py", "ops/pallas_kernels.py"):
        src = open(os.path.join(root, *rel.split("/"))).read()
        fs = rplint.lint_source(src, rel, registry=reg)
        active = [f for f in fs if not f.suppressed and f.rule == "RP07"]
        assert active == [], rel + ": " + "; ".join(
            f.message for f in active
        )
    # the pallas cache alloc is the accepted, reasoned suppression
    src = open(os.path.join(root, "ops", "pallas_kernels.py")).read()
    fs = rplint.lint_source(src, "ops/pallas_kernels.py", registry=reg)
    sup = [f for f in fs if f.suppressed and f.rule == "RP07"]
    assert len(sup) == 1 and "charged by construction" in sup[0].reason


def test_rp08_fixture():
    active, suppressed = _split(_lint_fixture("rp08_bad.py"))
    assert [f.rule for f in active] == ["RP08"] * 4
    joined = " | ".join(f.message for f in active)
    assert "not joined on every path" in joined
    assert "never joined in this function" in joined
    assert "shutdown sentinel" in joined
    assert "dominates its batch's yield" in joined
    assert [f.rule for f in suppressed] == ["RP08"]
    # the ok-cases in the same fixture (finally join, pool join, closed-
    # flag-guarded sentinel, ack-after-yield) produced nothing
    lines = {f.line for f in active}
    assert len(lines) == 4


def test_rp08_shipped_substrates_pass():
    """The four thread/queue substrates (PrefetchSource,
    StagedIngestSource, TopKServer, ShardedTopKServer) satisfy the
    join/sentinel/ack contracts flow-sensitively — no pragma needed."""
    root = rplint.package_root()
    for rel in ("streaming.py", "models/sketch.py", "serving/server.py"):
        src = open(os.path.join(root, *rel.split("/"))).read()
        fs = rplint.lint_source(src, rel)
        bad = [f for f in fs if f.rule == "RP08"]
        assert bad == [], rel + ": " + "; ".join(f.message for f in bad)


def test_rp09_fixture():
    active, suppressed = _split(
        _lint_fixture("rp09_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP09"] * 2
    joined = " | ".join(f.message for f in active)
    assert "_materialize" in joined and "self._fetch" in joined
    assert "np.asarray" in joined
    assert "float() on an expression" in joined
    assert [f.rule for f in suppressed] == ["RP09"]
    # outside the hot modules the rule (and its pragma) stand down
    assert _lint_fixture("rp09_bad.py") == []


def test_rp09_cross_module_resolution():
    """One-level from-import resolution: the sync lives in another
    package file; suppressing it THERE (the owning file's pragma) also
    silences the caller-side finding."""
    import ast as _ast

    from randomprojection_tpu.analysis import cfg as cfgmod
    from randomprojection_tpu.analysis import flowrules

    helper_src = (
        "import numpy as np\n\n"
        "def fetch(y):\n"
        "    return np.asarray(y)\n"
    )
    hot_src = (
        "from randomprojection_tpu.utils.helpers import fetch\n\n"
        "def loop(ys):\n"
        "    out = []\n"
        "    for y in ys:\n"
        "        out.append(fetch(y))\n"
        "    return out\n"
    )
    idx = cfgmod.PackageIndex()
    idx.add(cfgmod.index_module("utils/helpers.py", _ast.parse(helper_src)))
    fs = flowrules.rule_rp09(_ast.parse(hot_src), "streaming.py", index=idx)
    assert len(fs) == 1
    assert "utils/helpers.py:4" in fs[0][1]
    idx2 = cfgmod.PackageIndex()
    idx2.add(cfgmod.index_module(
        "utils/helpers.py", _ast.parse(helper_src), {4: {"RP03"}}
    ))
    assert flowrules.rule_rp09(
        _ast.parse(hot_src), "streaming.py", index=idx2
    ) == []


# -- ISSUE 11: pragma edge cases ---------------------------------------------


def test_pragma_on_continuation_line():
    """A pragma on ANY physical line of a multi-line statement covers
    the whole statement — findings anchor at sub-expression lines."""
    src = (
        "import queue\n"
        "q = queue.Queue(\n"
        "    maxsize=0,  # rplint: allow[RP04] — bounded upstream\n"
        ")\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert [(f.rule, f.suppressed) for f in fs] == [("RP04", True)]


def test_pragma_two_rules_one_line_both_match():
    # the missing daemon= (RP04) and the missing join (RP08) both
    # anchor on the one-line statement; the ISSUE 12 dedupe drops only
    # RP04's *no-join* duplicate, never its daemon finding
    src = (
        "import queue\nimport threading\n"
        "def f(x):\n"
        "    # rplint: allow[RP04,RP08] — fixture: one reason, two rules\n"
        "    t = threading.Thread(target=print); t.start()\n"
        "    return None\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert sorted(f.rule for f in fs) == ["RP04", "RP08"]
    assert all(f.suppressed for f in fs)
    assert "daemon" in next(f for f in fs if f.rule == "RP04").message


def test_stale_pragma_is_rp00():
    """A pragma whose violation was edited away is itself a finding —
    but only when every rule it names actually ran for the file."""
    src = (
        "import queue\n\n"
        "# rplint: allow[RP04] — the queue this excused is gone\n"
        "q = queue.Queue(maxsize=8)\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert [f.rule for f in fs] == ["RP00"]
    assert "stale pragma" in fs[0].message and fs[0].line == 3
    # RP03 never runs outside the hot modules: the same pragma shape is
    # NOT judged stale where its rule was not evaluated
    src2 = (
        "import numpy as np\n\n"
        "# rplint: allow[RP03] — would matter in a hot module\n"
        "y = np.asarray([1])\n"
    )
    assert rplint.lint_source(src2, "cold.py") == []


# -- ISSUE 11: exit codes, unresolvable emits, baseline ----------------------


def test_cli_lint_internal_error_exits_2(tmp_path, capsys):
    """An unreadable target or malformed baseline is an internal error
    (exit 2) — a partial run must never report success."""
    missing = tmp_path / "nope.py"
    assert cli.main(["lint", str(missing)]) == 2
    assert "internal error" in capsys.readouterr().err
    ok_file = tmp_path / "ok.py"
    ok_file.write_text("x = 1\n")
    not_json = tmp_path / "base.json"
    not_json.write_text("{ torn")
    assert cli.main(["lint", "--baseline", str(not_json),
                     str(ok_file)]) == 2
    assert "internal error" in capsys.readouterr().err
    not_record = tmp_path / "base2.json"
    not_record.write_text('{"not": "a record"}')
    assert cli.main(["lint", "--baseline", str(not_record),
                     str(ok_file)]) == 2
    assert "internal error" in capsys.readouterr().err


def test_unresolvable_emit_is_informational():
    src = (
        "from randomprojection_tpu.utils.telemetry import emit\n"
        "def g(name):\n"
        "    emit(name, x=1)\n"
        "    emit('rogue.event')\n"
    )
    reg = rplint.EventRegistry(events={}, families=(), lines={})
    fs = rplint.lint_source(src, "x.py", registry=reg)
    info = [f for f in fs if f.severity == "info"]
    errors = [f for f in fs if f.severity == "error"]
    assert len(info) == 1 and "unresolvable-emit" in info[0].message
    assert [f.rule for f in errors] == ["RP02"]  # the rogue constant


def test_unresolvable_emit_counted_in_json(tmp_path, capsys):
    """The info class never fails the lint but --json counts it, so
    registry coverage is honest about its blind spot."""
    f = tmp_path / "dyn.py"
    f.write_text(
        "from randomprojection_tpu.utils.telemetry import emit\n"
        "def g(name):\n"
        "    emit(name, x=1)\n"
    )
    # note: explicit-file lints resolve the registry from the real
    # package root, so the dynamic name is evaluated
    assert cli.main(["lint", "--json", str(f)]) == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["ok"] is True
    assert rec["unresolvable_emits"] == 1
    infos = [x for x in rec["findings"] if x["severity"] == "info"]
    assert len(infos) == 1 and not infos[0]["suppressed"]


def test_family_anchored_concatenation_resolves():
    reg = rplint.EventRegistry(
        events={}, families=("hash.batches.",), lines={},
        family_attrs={"HASH_BATCHES_FAMILY": "hash.batches."},
    )
    src = (
        "from randomprojection_tpu.utils.telemetry import EVENTS, emit\n"
        "def g(p):\n"
        "    emit(EVENTS.HASH_BATCHES_FAMILY + p)\n"
        "    emit('hash.batches.' + p)\n"
        "    emit('rogue.' + p)\n"
    )
    fs = rplint.lint_source(src, "x.py", registry=reg)
    errors = [f for f in fs if f.severity == "error"]
    assert len(errors) == 1 and "'rogue.'" in errors[0].message
    assert [f for f in fs if f.severity == "info"] == []


def test_lint_baseline_diff(tmp_path, capsys):
    """--baseline fails only on NEW findings; line drift of a baselined
    finding is not new (rule+path+message matching)."""
    bad = tmp_path / "seeded.py"
    bad.write_text("import queue\nq = queue.Queue()\n")
    assert cli.main(["lint", "--json", str(bad)]) == 1
    rec = json.loads(capsys.readouterr().out.strip())
    basefile = tmp_path / "base.json"
    basefile.write_text(json.dumps(rec))
    assert cli.main(["lint", "--json", "--baseline", str(basefile),
                     str(bad)]) == 0
    rec2 = json.loads(capsys.readouterr().out.strip())
    assert rec2["baseline"]["matched"] == 1
    assert rec2["baseline"]["new"] == [] and rec2["baseline"]["ok"] is True
    # the old finding moves down a line AND a second identical-message
    # violation appears: 1 matched (despite the drift), 1 new -> exit 1
    bad.write_text(
        "import queue\n\nq = queue.Queue()\nq2 = queue.Queue(maxsize=0)\n"
    )
    assert cli.main(["lint", "--json", "--baseline", str(basefile),
                     str(bad)]) == 1
    rec3 = json.loads(capsys.readouterr().out.strip())
    assert rec3["baseline"]["matched"] == 1
    assert len(rec3["baseline"]["new"]) == 1
    # fixing everything leaves the baseline entry stale (reported, ok)
    bad.write_text("import queue\nq = queue.Queue(maxsize=4)\n")
    assert cli.main(["lint", "--json", "--baseline", str(basefile),
                     str(bad)]) == 0
    rec4 = json.loads(capsys.readouterr().out.strip())
    assert rec4["baseline"]["stale"] == 1 and rec4["baseline"]["new"] == []


def test_shipped_tree_zero_nonbaselined_findings():
    """ISSUE 11 satellite: the `make lint-ci` contract — the committed
    .rplint_baseline.json covers every finding the shipped tree
    produces, and (since the tree lints clean) carries no active
    finding that could grandfather a future regression."""
    base_path = os.path.join(
        os.path.dirname(rplint.package_root()), ".rplint_baseline.json"
    )
    with open(base_path) as fh:
        base = json.load(fh)
    report = rplint.lint_package()
    diff = rplint.diff_baseline(report, base)
    assert diff["new"] == [], diff["new"]
    active_in_base = [
        f for f in base["findings"]
        if not f["suppressed"] and f.get("severity", "error") == "error"
    ]
    assert active_in_base == []


# -- CFG regression cases (review round, same PR) ----------------------------


def test_rp08_while_condition_exit_path_is_not_pruned():
    """A while-loop condition is re-evaluated each iteration: a start
    inside the body DOES reach the loop-exit edge on a later pass, so
    a join skipped via the normal exit must be flagged (the condition
    must not persist as a branch fact)."""
    src = (
        "import threading\n"
        "def f(self, items):\n"
        "    while self.running:\n"
        "        t = threading.Thread(target=print, daemon=True)\n"
        "        t.start()\n"
        "        if self.fast:\n"
        "            continue\n"
        "        t.join()\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert any(
        f.rule == "RP08" and "not joined on every path" in f.message
        for f in fs
    ), [f.message for f in fs]


def test_rp08_break_runs_enclosing_finally():
    """break/continue exit through finally blocks entered since the
    loop — a join in such a finally covers the break path (no false
    positive), while a try around the WHOLE loop is not exited by the
    break."""
    src = (
        "import threading\n"
        "def f(items, work):\n"
        "    for item in items:\n"
        "        t = threading.Thread(target=print, daemon=True)\n"
        "        t.start()\n"
        "        try:\n"
        "            if item is None:\n"
        "                break\n"
        "            work(item)\n"
        "        finally:\n"
        "            t.join(timeout=5.0)\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert [f for f in fs if f.rule == "RP08"] == [], [
        f.message for f in fs
    ]


def test_rp07_trailing_constant_dim_is_not_a_slot_count():
    """Only the LEADING dim of a VMEM allocation declares revolving
    slots: a trailing constant (a tile width) must not let a bogus
    modulus pass the declared-slot-count check."""
    src = (
        "import jax\n"
        "from jax.experimental.pallas import tpu as pltpu\n\n"
        "def _reserved_bytes(blk):\n"
        "    return 2 * blk\n\n"
        "def _launch(blk):\n"
        "    return [pltpu.VMEM((blk, 2), 'f32'),\n"
        "            pltpu.SemaphoreType.DMA((2,))]\n\n"
        "def _kernel(x_hbm, buf, sem, *, n):\n"
        "    def tile_copy(t):\n"
        "        return pltpu.make_async_copy(\n"
        "            x_hbm.at[t], buf.at[t % 2], sem.at[t % 2])\n"
        "    tile_copy(0).start()\n"
        "    def body(t, _):\n"
        "        tile_copy(t + 1).start()\n"
        "        tile_copy(t).wait()\n"
        "        return 0\n"
        "    jax.lax.fori_loop(0, n, body, 0)\n"
    )
    fs = rplint.lint_source(src, "ops/pallas_kernels.py")
    mods = [f for f in fs if "does not match a declared slot count"
            in f.message]
    assert len(mods) == 1, [f.message for f in fs]


def test_rp07_inline_async_copy_start_is_tracked():
    """The inline form — make_async_copy(...).start() with no helper
    and no bound name — is a copy family too (keyed by the targeted
    buffer): an unwaited inline start is flagged, and a
    reconstructed-descriptor wait on the same buffer matches it."""
    head = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "from jax.experimental.pallas import tpu as pltpu\n\n"
        "def _reserved_bytes(blk):\n"
        "    return 2 * blk\n\n"
    )
    unwaited = head + (
        "def _kernel(x_hbm, buf, sem):\n"
        "    pltpu.make_async_copy(x_hbm.at[pl.ds(0, 8)], buf, sem"
        ").start()\n"
    )
    fs = rplint.lint_source(unwaited, "ops/pallas_kernels.py")
    assert any("never waited" in f.message for f in fs), [
        f.message for f in fs
    ]
    paired = head + (
        "def _kernel(x_hbm, buf, sem):\n"
        "    pltpu.make_async_copy(x_hbm.at[pl.ds(0, 8)], buf, sem"
        ").start()\n"
        "    pltpu.make_async_copy(x_hbm.at[pl.ds(0, 8)], buf, sem"
        ").wait()\n"
    )
    fs = rplint.lint_source(paired, "ops/pallas_kernels.py")
    assert [f for f in fs if f.rule == "RP07"] == [], [
        f.message for f in fs
    ]


def test_rp07_multi_deep_warmup_is_legal():
    """A K=3 pipeline warming two slots (starts 0 and 1, loop start
    t+2, wait t) is correct — warm-up slot 1 is waited at iteration 1,
    within its slot window — and must not be flagged."""
    src = (
        "import jax\n"
        "from jax.experimental.pallas import tpu as pltpu\n\n"
        "def _reserved_bytes(blk):\n"
        "    return 3 * blk\n\n"
        "def _launch(blk):\n"
        "    return [pltpu.VMEM((3, blk, 128), 'f32'),\n"
        "            pltpu.SemaphoreType.DMA((3,))]\n\n"
        "def _kernel(x_hbm, buf, sem, *, n):\n"
        "    def tile_copy(t):\n"
        "        return pltpu.make_async_copy(\n"
        "            x_hbm.at[t], buf.at[t % 3], sem.at[t % 3])\n"
        "    tile_copy(0).start()\n"
        "    tile_copy(1).start()\n"
        "    def body(t, _):\n"
        "        tile_copy(t + 2).start()\n"
        "        tile_copy(t).wait()\n"
        "        return 0\n"
        "    jax.lax.fori_loop(0, n, body, 0)\n"
    )
    fs = rplint.lint_source(src, "ops/pallas_kernels.py")
    assert [f for f in fs if f.rule == "RP07"] == [], [
        f.message for f in fs
    ]


def test_rp08_append_built_pool_joined_in_finally_is_clean():
    """The canonical accumulate-then-join idiom — pool.append(t) after
    each start, `for t in pool: t.join()` in a finally — must not be
    flagged (append makes the pool a tracked thread collection)."""
    src = (
        "import threading\n"
        "def f(n, work):\n"
        "    pool = []\n"
        "    try:\n"
        "        for i in range(n):\n"
        "            t = threading.Thread(target=print, daemon=True)\n"
        "            t.start()\n"
        "            pool.append(t)\n"
        "        work()\n"
        "    finally:\n"
        "        for t in pool:\n"
        "            t.join(timeout=5.0)\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert [f for f in fs if f.rule == "RP08"] == [], [
        f.message for f in fs
    ]


# -- ISSUE 12: RP10 shared-state races / RP11 lock-order deadlocks -----------


def test_rp10_fixture():
    """Concurrency-module scoping: unlocked cross-role read/write,
    one-side-only lock, write published after start(), and the
    lock-consistency leg — each seeded exactly once; the ok-twins
    (same-lock, queue handoff, init-only-dominates-start) silent."""
    active, suppressed = _split(
        _lint_fixture("rp10_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP10"] * 4
    msgs = [f.message for f in active]
    joined = " | ".join(msgs)
    assert "self._count of UnlockedTallies" in joined
    assert "self._total of OneSideLocked" in joined
    assert "self._late of WriteAfterStart" in joined
    assert "written by role 'main' (__init__" in joined  # post-start write
    assert "self._n of InconsistentNoThreads" in joined
    assert "locked inconsistently" in joined
    assert sum("with no common lock" in m for m in msgs) == 3
    # the ok-twins produced nothing
    for clean in ("LockedOk", "QueueHandoffOk", "InitOnlyOk"):
        assert clean not in joined
    assert [f.rule for f in suppressed] == ["RP10"]
    assert suppressed[0].reason.startswith("fixture:")
    # outside the concurrency modules the rule (and its pragma) stand down
    assert _lint_fixture("rp10_bad.py") == []


def test_rp11_fixture():
    """Direct and call-level lock-order cycles plus the three blocking
    classes (queue.put / thread.join / future.result) under a lock; the
    ok-twins (acyclic order, put_nowait, str/path joins) silent."""
    active, suppressed = _split(
        _lint_fixture("rp11_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP11"] * 5
    msgs = [f.message for f in active]
    joined = " | ".join(msgs)
    assert sum("lock-order cycle" in m for m in msgs) == 2
    assert "OrderCycle._a -> OrderCycle._b" in joined
    assert "CallLevelCycle._x -> CallLevelCycle._y" in joined
    assert "OrderOk" not in joined  # acyclic twin clean
    assert "blocking .put()" in joined
    assert "blocking .join()" in joined
    assert "blocking .result()" in joined
    assert [f.rule for f in suppressed] == ["RP11"]
    assert suppressed[0].reason.startswith("fixture:")
    assert _lint_fixture("rp11_bad.py") == []


def test_rp10_rp11_shipped_concurrency_modules_pass():
    """The acceptance gate for ISSUE 12: all four thread/queue
    substrates plus telemetry/sharded-index/hashing pass RP10/RP11 with
    every remaining suppression carrying a reasoned pragma — run
    through lint_package so subclass roles resolve across modules."""
    report = rplint.lint_package()
    conc = [f for f in report["findings"] if f["rule"] in ("RP10", "RP11")]
    active = [f for f in conc if not f["suppressed"]]
    assert active == [], active
    # the two accepted dispatcher-tally suppressions live in sketch.py
    sup = [f for f in conc if f["suppressed"]]
    assert {f["path"] for f in sup} == {"models/sketch.py"}
    assert all(f["reason"] for f in sup)
    assert {f["rule"] for f in sup} == {"RP10", "RP11"}


def test_rp10_telemetry_run_token_lock_regression():
    """The configure() fix (ISSUE 12): rebinding _RUN_TOKEN without
    _SPAN_LOCK while _new_span_id reads it under the lock is exactly
    the inconsistent-locking class RP10's module-global leg flags."""
    import ast as _ast

    from randomprojection_tpu.analysis import flowrules

    bad = (
        "import threading\n"
        "_LOCK = threading.Lock()\n"
        "_TOKEN = '0'\n"
        "def reconfigure():\n"
        "    global _TOKEN\n"
        "    _TOKEN = 'fresh'\n"
        "def read_id():\n"
        "    with _LOCK:\n"
        "        return _TOKEN + '-1'\n"
    )
    fs = flowrules.rule_rp10(_ast.parse(bad), "utils/telemetry.py")
    assert len(fs) == 1 and "module global _TOKEN" in fs[0][1]
    assert "locked inconsistently" in fs[0][1]
    # the shipped telemetry module is clean (the fix holds the lock)
    src = open(os.path.join(
        rplint.package_root(), "utils", "telemetry.py"
    )).read()
    fs = rplint.lint_source(src, "utils/telemetry.py")
    assert [f for f in fs if f.rule in ("RP10", "RP11")] == [], [
        f.message for f in fs
    ]


def test_rp10_subclass_roles_resolve_through_index():
    """A subclass hook in one file joins the thread roles its base
    class constructs in another (the ShardedTopKServer shape): the
    dispatcher-written attribute read by main-role stats() is flagged
    in the SUBCLASS's file, and guarding both sides with the same lock
    clears it."""
    import ast as _ast

    from randomprojection_tpu.analysis import cfg as cfgmod
    from randomprojection_tpu.analysis import flowrules

    base_src = (
        "import threading\n"
        "class Base:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=self._run, daemon=True)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        self._hook()\n"
        "    def close(self):\n"
        "        self._t.join()\n"
    )
    sub_src = (
        "from randomprojection_tpu.models.sketch import Base\n"
        "class Sub(Base):\n"
        "    def __init__(self):\n"
        "        self._tally = 0\n"
        "        super().__init__()\n"
        "    def _hook(self):\n"
        "        self._tally += 1\n"
        "    def stats(self):\n"
        "        return self._tally\n"
    )
    idx = cfgmod.PackageIndex()
    idx.add(cfgmod.index_module("models/sketch.py", _ast.parse(base_src)))
    idx.add(cfgmod.index_module(
        "serving/server.py", _ast.parse(sub_src)
    ))
    fs = flowrules.rule_rp10(
        _ast.parse(sub_src), "serving/server.py", index=idx
    )
    assert len(fs) == 1, fs
    assert "self._tally" in fs[0][1] and "self._run" in fs[0][1]
    # same shape with both sides under one lock: clean
    locked_sub = sub_src.replace(
        "        self._tally = 0\n",
        "        import threading\n"
        "        self._tally = 0\n"
        "        self._lk = threading.Lock()\n",
    ).replace(
        "        self._tally += 1\n",
        "        with self._lk:\n"
        "            self._tally += 1\n",
    ).replace(
        "        return self._tally\n",
        "        with self._lk:\n"
        "            return self._tally\n",
    )
    idx2 = cfgmod.PackageIndex()
    idx2.add(cfgmod.index_module("models/sketch.py", _ast.parse(base_src)))
    idx2.add(cfgmod.index_module(
        "serving/server.py", _ast.parse(locked_sub)
    ))
    assert flowrules.rule_rp10(
        _ast.parse(locked_sub), "serving/server.py", index=idx2
    ) == []


def test_rp04_rp08_dedupe_one_bug_one_report():
    """ISSUE 12 satellite: a thread RP08 flow-checks (started,
    non-escaping) stands RP04's per-line no-join heuristic down — the
    missing join reports exactly once (as the flow finding)."""
    src = (
        "import threading\n"
        "def leak(work):\n"
        "    t = threading.Thread(target=print, daemon=True)\n"
        "    t.start()\n"  # no .join( anywhere in this module
        "    work()\n"
    )
    fs = rplint.lint_source(src, "x.py")
    rules = [f.rule for f in fs]
    assert rules == ["RP08"], [(f.rule, f.message) for f in fs]
    assert "never joined in this function" in fs[0].message
    # a module-level thread (not covered by the flow check) still gets
    # the per-line heuristic — the dedupe never widens a blind spot
    nojoin = _lint_fixture("rp04_nojoin.py")
    assert [f.rule for f in nojoin] == ["RP04"]
    # and rp08_bad.py (the regression target) reports each seeded bug
    # exactly once: RP08 findings only, no RP04 duplicates
    active, _sup = _split(_lint_fixture("rp08_bad.py"))
    assert [f.rule for f in active] == ["RP08"] * 4


def test_sarif_output(tmp_path, capsys):
    """--sarif emits a SARIF 2.1.0 log: rule metadata, one result per
    finding with the region line, info → note level, and
    pragma-suppressed findings carrying an inSource suppression."""
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import queue\n"
        "q = queue.Queue()\n"
        "# rplint: allow[RP04] — test: bounded upstream\n"
        "q2 = queue.Queue()\n"
    )
    sarif_path = tmp_path / "out.sarif"
    assert cli.main(["lint", "--sarif", str(sarif_path), str(bad)]) == 1
    capsys.readouterr()
    log = json.loads(sarif_path.read_text())
    assert log["version"] == "2.1.0"
    assert "sarif-2.1.0" in log["$schema"]
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "rplint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"RP00", "RP04", "RP10", "RP11"} <= rule_ids
    results = run["results"]
    assert len(results) == 2
    by_sup = {bool(r.get("suppressions")): r for r in results}
    active, sup = by_sup[False], by_sup[True]
    assert active["ruleId"] == "RP04" and active["level"] == "error"
    loc = active["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("seeded.py")
    assert loc["region"]["startLine"] == 2
    assert sup["suppressions"][0]["kind"] == "inSource"
    assert sup["suppressions"][0]["justification"] == "test: bounded upstream"


def test_update_baseline_rewrites_in_place(tmp_path, capsys):
    """--update-baseline: first run creates the baseline from the
    current findings (exit 0), the diffed run then passes, and after
    the fix a second update prunes the stale entry."""
    bad = tmp_path / "seeded.py"
    bad.write_text("import queue\nq = queue.Queue()\n")
    basefile = tmp_path / "base.json"
    # without --update-baseline a missing baseline is an internal error
    assert cli.main(["lint", "--baseline", str(basefile), str(bad)]) == 2
    capsys.readouterr()
    assert cli.main(["lint", "--baseline", str(basefile),
                     "--update-baseline", str(bad)]) == 0
    out = capsys.readouterr().out
    assert "baseline updated" in out and "1 new finding(s) accepted" in out
    base = json.loads(basefile.read_text())
    assert base["rplint"] == 4
    assert [f["rule"] for f in base["findings"]] == ["RP04"]
    # the accepted finding now passes the diffed gate
    assert cli.main(["lint", "--baseline", str(basefile), str(bad)]) == 0
    capsys.readouterr()
    # fix the violation: the stale entry is pruned by the next update
    bad.write_text("import queue\nq = queue.Queue(maxsize=4)\n")
    assert cli.main(["lint", "--baseline", str(basefile),
                     "--update-baseline", str(bad)]) == 0
    out = capsys.readouterr().out
    assert "1 stale entr(ies) pruned" in out
    base2 = json.loads(basefile.read_text())
    assert base2["findings"] == []
    # --update-baseline without --baseline is a usage error (exit 2)
    assert cli.main(["lint", "--update-baseline", str(bad)]) == 2
    assert "requires --baseline" in capsys.readouterr().err


def test_rp10_same_role_unlocked_read_does_not_void_locked_pair():
    """Review fix (same PR): races are judged per CROSS-ROLE pair — an
    unlocked read on the writer's own thread cannot race the write, so
    it must not fail a properly locked cross-role pair."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._t = threading.Thread(target=self._run, "
        "daemon=True)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        with self._lk:\n"
        "            self._n += 1\n"
        "        print(self._n)  # same-role read: cannot race _run\n"
        "    def read(self):\n"
        "        with self._lk:\n"
        "            return self._n\n"
        "    def close(self):\n"
        "        self._t.join()\n"
    )
    fs = rplint.lint_source(src, "streaming.py")
    assert [f for f in fs if f.rule == "RP10"] == [], [
        f.message for f in fs
    ]
    # the cross-role pair going bare is still caught
    bad = src.replace(
        "    def read(self):\n        with self._lk:\n"
        "            return self._n\n",
        "    def read(self):\n        return self._n\n",
    )
    fs = rplint.lint_source(bad, "streaming.py")
    assert any(f.rule == "RP10" for f in fs), [f.message for f in fs]


def test_rp11_rlock_reentry_is_not_a_self_deadlock():
    """Review fix (same PR): re-entering a threading.RLock is legal —
    the self-edge finding applies to plain Lock only (order cycles
    through an RLock still count)."""
    rlock = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.RLock()\n"
        "    def outer(self):\n"
        "        with self._lk:\n"
        "            return self.inner()\n"
        "    def inner(self):\n"
        "        with self._lk:\n"
        "            return 1\n"
    )
    fs = rplint.lint_source(rlock, "streaming.py")
    assert [f for f in fs if f.rule == "RP11"] == [], [
        f.message for f in fs
    ]
    plain = rlock.replace("threading.RLock()", "threading.Lock()")
    fs = rplint.lint_source(plain, "streaming.py")
    assert any(
        f.rule == "RP11" and "not reentrant" in f.message for f in fs
    ), [f.message for f in fs]


def test_rp11_string_join_on_variable_separator_is_not_blocking():
    """Review fix (same PR): sep.join(parts) is a string join — only
    the thread-join call shapes (no positional args, or one numeric
    timeout) count as blocking under a lock."""
    src = (
        "import threading\n"
        "_L = threading.Lock()\n"
        "def render(sep, parts):\n"
        "    with _L:\n"
        "        return sep.join(parts)\n"
    )
    fs = rplint.lint_source(src, "streaming.py")
    assert [f for f in fs if f.rule == "RP11"] == [], [
        f.message for f in fs
    ]
    timeout_join = (
        "import threading\n"
        "_L = threading.Lock()\n"
        "def halt(t):\n"
        "    with _L:\n"
        "        t.join(5.0)\n"
    )
    fs = rplint.lint_source(timeout_join, "streaming.py")
    assert any(
        f.rule == "RP11" and "blocking .join()" in f.message for f in fs
    ), [f.message for f in fs]


# -- ISSUE 20: RP12 lifecycle / RP13 durable commit / RP14 degraded paths ----


def test_rp12_fixture():
    """Leaked acquires (subscription, open() handle, mkdtemp dir) and
    the r17 acquire-ordering shape, each seeded exactly once; the
    ok-twins (with-managed, escaping, guarded release, exception-
    protected ordering) silent."""
    active, suppressed = _split(_lint_fixture("rp12_bad.py"))
    assert [f.rule for f in active] == ["RP12"] * 4
    assert [f.line for f in active] == [22, 30, 39, 49]
    msgs = [f.message for f in active]
    joined = " | ".join(msgs)
    assert "telemetry subscription 'sub'" in joined
    assert "open() handle 'f'" in joined
    assert "mkdtemp temp dir 'd'" in joined
    assert "MetricsServer 'server' is acquired while 'sub'" in joined
    assert "not exception-protected" in joined
    assert sum("not released on every path out" in m for m in msgs) == 3
    for clean in ("ok_with", "ok_escape", "ok_guarded", "ok_ordering"):
        assert clean not in joined
    assert [f.rule for f in suppressed] == ["RP12"]
    assert suppressed[0].line == 95
    assert suppressed[0].reason.startswith("fixture:")


def test_rp13_fixture():
    """Durable-commit discipline on a durable-plane module: raw final
    write, unflushed replace, missing directory fsync, and a manifest
    committed before its chunks — the conformant twins (including the
    loop/if-promoted manifest-last shape) silent."""
    active, suppressed = _split(
        _lint_fixture("rp13_bad.py", relpath="durable.py")
    )
    assert [f.rule for f in active] == ["RP13"] * 4
    assert [f.line for f in active] == [25, 33, 43, 47]
    joined = " | ".join(f.message for f in active)
    assert "raw open(..., 'w') writes the final path in place" in joined
    assert "without a flush or an os.fsync" in joined
    assert "no directory fsync is reachable after this os.replace" in joined
    assert "manifest must be replaced LAST" in joined
    for clean in ("ok_commit", "ok_manifest_last"):
        assert clean not in joined
    assert [f.rule for f in suppressed] == ["RP13"]
    assert suppressed[0].line == 75
    # outside the durable-plane modules the rule stands down
    assert _lint_fixture("rp13_bad.py") == []


def test_rp14_fixture():
    """Degraded-path contracts on a fallback-bearing module: a silent
    rung, a classified rung with no degraded-key memo, and a fallback
    counter with no adjacent event emit — the ok-twins (handler memo,
    memo-after-the-ladder reachable through the CFG, counter+emit
    adjacency) silent."""
    with open(os.path.join(FIXTURES, "rp14_bad.py")) as f:
        src = f.read()
    findings = rplint.lint_source(
        src, "ann/lsh.py", degraded={"INDEX_LSH_FALLBACK"}
    )
    active, suppressed = _split(findings)
    assert [f.rule for f in active] == ["RP14"] * 3
    assert [f.line for f in active] == [20, 29, 38]
    joined = " | ".join(f.message for f in active)
    assert "doctor cannot see this degradation" in joined
    assert "never memoizes the degraded key" in joined
    assert "without an adjacent degraded-event emit" in joined
    for clean in ("ok_rung", "ok_ladder", "ok_counter"):
        assert clean not in joined
    assert [f.rule for f in suppressed] == ["RP14"]
    assert suppressed[0].line == 78
    # without a degraded set (standalone lint) any EVENTS.* emit
    # satisfies the forward leg — the same three findings fire
    solo = [f for f in rplint.lint_source(src, "ann/lsh.py")
            if f.rule == "RP14" and not f.suppressed]
    assert [f.line for f in solo] == [20, 29, 38]
    # outside the fallback-bearing modules the rule stands down
    assert _lint_fixture("rp14_bad.py") == []


def test_rp12_rp13_rp14_shipped_tree_passes():
    """The ISSUE 20 acceptance gate: the shipped tree carries ZERO
    RP12/RP13/RP14 findings — the real leaks the sweep caught
    (health_smoke's unprotected HealthEngine acquire, FlightRecorder's
    missing directory fsync, rplint's own raw baseline/SARIF writes)
    were fixed, not suppressed."""
    report = rplint.lint_package()
    new = [f for f in report["findings"]
           if f["rule"] in ("RP12", "RP13", "RP14")]
    assert new == [], new


def test_degraded_events_load_and_drift():
    """RP14's reverse leg: DEGRADED_EVENTS parses out of the real
    consumer, and the drift check flags both an unregistered member and
    a registered-but-never-emitted member."""
    consumer = open(os.path.join(
        rplint.package_root(), "utils", "trace_report.py"
    )).read()
    attrs, line = rplint.load_degraded_events(consumer)
    assert "INDEX_LSH_FALLBACK" in attrs and "KERNEL_DMA_FALLBACK" in attrs
    assert len(attrs) >= 10 and line > 1
    reg = rplint.EventRegistry(
        events={"GOOD": "good.event"}, families=(), lines={},
    )
    findings = rplint.check_degraded_drift(
        {"GOOD", "ROGUE"}, 7, reg,
        [("a.py", "emit(EVENTS.GOOD)"), ("utils/trace_report.py", "")],
    )
    assert [f.rule for f in findings] == ["RP14"]
    assert f"EVENTS.ROGUE" in findings[0].message
    assert findings[0].line == 7
    # a member only the consumer itself mentions is consumed-not-produced
    findings = rplint.check_degraded_drift(
        {"GOOD"}, 7, reg,
        [("utils/trace_report.py", "EVENTS.GOOD")],
    )
    assert len(findings) == 1
    assert "nothing raises" in findings[0].message
    # registered and emitted: clean
    assert rplint.check_degraded_drift(
        {"GOOD"}, 7, reg, [("a.py", "emit(EVENTS.GOOD)")]
    ) == []


def test_rule_scope_sets_name_real_modules():
    """ISSUE 20 satellite: every module the scoped rules target exists
    on disk — a rename that silently un-scopes a rule is drift this
    guard catches."""
    root = rplint.package_root()
    scoped = set()
    for group in (rplint.HOT_MODULES, rplint.PIPELINE_MODULES,
                  rplint.CONCURRENCY_MODULES, rplint.RP13_MODULES,
                  rplint.RP14_MODULES, tuple(rplint.KERNEL_BUDGET_FNS)):
        scoped.update(group)
    assert scoped, "rule scope sets are empty"
    missing = [rel for rel in sorted(scoped)
               if not os.path.exists(os.path.join(root, *rel.split("/")))]
    assert missing == [], missing
    # the budget functions themselves still exist in their modules
    for rel, fn in rplint.KERNEL_BUDGET_FNS.items():
        src = open(os.path.join(root, *rel.split("/"))).read()
        assert f"def {fn}(" in src, (rel, fn)


def test_lint_package_jobs_deterministic():
    """ISSUE 20 tentpole-adjacent: the process-pool fan-out returns
    byte-identical findings in the same order as the serial path."""
    root = rplint.package_root()
    files = [
        os.path.join(root, *rel.split("/"))
        for rel in ("models/sketch.py", "utils/telemetry.py",
                    "streaming.py", "ann/lsh.py", "tiering.py",
                    "durable.py")
    ]
    serial = rplint.lint_package(files=files, jobs=1)
    pooled = rplint.lint_package(files=files, jobs=4)
    assert serial["findings"] == pooled["findings"]
    assert serial["counts"] == pooled["counts"]
    assert serial["files"] == pooled["files"] == len(files)
    assert pooled["rplint"] == 4 and "wall_s" in pooled


def test_rp12_pragma_and_baseline_lifecycle(tmp_path, capsys):
    """The new rules ride the existing suppression machinery: a seeded
    RP12 leak fails `cli lint` (exit 1), a reasoned pragma restores 0,
    and --update-baseline accepts the unpragma'd finding."""
    leak = (
        "def leak(fn, flag):\n"
        "    sub = telemetry.subscribe(fn)\n"
        "    if flag:\n"
        "        return None\n"
        "    sub.close()\n"
        "    return None\n"
    )
    bad = tmp_path / "seeded.py"
    bad.write_text(leak)
    assert cli.main(["lint", str(bad)]) == 1
    capsys.readouterr()
    assert cli.main(["lint", "--json", str(bad)]) == 1
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["counts"] == {"RP12": 1}
    bad.write_text(leak.replace(
        "    sub = telemetry.subscribe(fn)\n",
        "    # rplint: allow[RP12] — test: caller owns the release\n"
        "    sub = telemetry.subscribe(fn)\n",
    ))
    assert cli.main(["lint", str(bad)]) == 0
    capsys.readouterr()
    # baseline route: the raw leak is accepted, then gates clean
    bad.write_text(leak)
    basefile = tmp_path / "base.json"
    assert cli.main(["lint", "--baseline", str(basefile),
                     "--update-baseline", str(bad)]) == 0
    capsys.readouterr()
    assert cli.main(["lint", "--baseline", str(basefile), str(bad)]) == 0


def test_ci_workflow_runs_lint_ci_and_fast_tier1():
    """ISSUE 12 satellite: the committed GitHub workflow gates pushes
    and PRs on `make lint-ci` plus a budgeted 'not slow' tier-1 run."""
    wf = os.path.join(
        os.path.dirname(rplint.package_root()),
        ".github", "workflows", "ci.yml",
    )
    with open(wf) as fh:
        text = fh.read()
    assert "make lint-ci" in text
    assert "-m 'not slow'" in text
    assert "pull_request" in text and "push" in text
    assert "timeout" in text  # the test-time budget
