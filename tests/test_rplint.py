"""rplint (ISSUE r10): every rule against its known-bad fixture, the
pragma grammar, the registry drift check, the stable --json schema, and
— the acceptance gate — that the shipped tree lints clean through the
real `cli lint` entry point."""

import json
import os

import pytest

from randomprojection_tpu import cli
from randomprojection_tpu.analysis import rplint

FIXTURES = os.path.join(os.path.dirname(__file__), "rplint_fixtures")


def _lint_fixture(name, relpath=None, registry=None):
    with open(os.path.join(FIXTURES, name)) as f:
        src = f.read()
    return rplint.lint_source(src, relpath or name, registry=registry)


def _split(findings):
    return (
        [f for f in findings if not f.suppressed],
        [f for f in findings if f.suppressed],
    )


# -- per-rule fixtures -------------------------------------------------------


def test_rp00_malformed_pragmas():
    active, suppressed = _split(_lint_fixture("rp00_bad.py"))
    assert [f.rule for f in active] == ["RP00", "RP00", "RP00"]
    assert not suppressed  # pragma hygiene is not suppressible
    msgs = " | ".join(f.message for f in active)
    assert "reason required" in msgs and "unknown rule" in msgs


def test_rp01_span_balance():
    active, suppressed = _split(_lint_fixture("rp01_bad.py"))
    assert [f.rule for f in active] == ["RP01", "RP01", "RP01"]
    # straight-line end, discarded handle, hand-rolled span event —
    # and nothing from the balanced/escaping functions
    msgs = [f.message for f in active]
    assert sum("neither escapes" in m for m in msgs) == 2
    assert sum("span event" in m for m in msgs) == 1
    assert [f.rule for f in suppressed] == ["RP01"]
    assert suppressed[0].reason.startswith("fixture:")


def test_rp02_event_registry():
    reg = rplint.EventRegistry(
        events={"GOOD": "good.event"}, families=("fam.",), lines={}
    )
    active, suppressed = _split(
        _lint_fixture("rp02_bad.py", registry=reg)
    )
    assert [f.rule for f in active] == ["RP02", "RP02", "RP02"]
    msgs = " | ".join(f.message for f in active)
    assert "'rogue.event'" in msgs
    assert "EVENTS.NOPE" in msgs
    assert "'other.'" in msgs
    assert [f.rule for f in suppressed] == ["RP02"]
    # without a registry (standalone file lint) the rule stays silent
    assert _lint_fixture("rp02_bad.py", registry=None) == []


def test_rp03_hot_path_host_syncs():
    active, suppressed = _split(
        _lint_fixture("rp03_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP03"] * 4
    msgs = " | ".join(f.message for f in active)
    for probe in ("np.asarray", "block_until_ready", "float()",
                  "jax.device_get"):
        assert probe in msgs
    assert [f.rule for f in suppressed] == ["RP03"]
    # the same code outside a hot module is not RP03's business
    assert _lint_fixture("rp03_bad.py") == []


def test_rp04_thread_hygiene():
    active, suppressed = _split(_lint_fixture("rp04_bad.py"))
    assert [f.rule for f in active] == ["RP04", "RP04"]
    msgs = " | ".join(f.message for f in active)
    assert "daemon=" in msgs and "unbounded" in msgs
    assert [f.rule for f in suppressed] == ["RP04"]

    nojoin = _lint_fixture("rp04_nojoin.py")
    assert [f.rule for f in nojoin] == ["RP04"]
    assert "no .join(" in nojoin[0].message


def test_rp05_determinism_in_ops():
    active, suppressed = _split(
        _lint_fixture("rp05_bad.py", relpath="ops/fixture.py")
    )
    assert [f.rule for f in active] == ["RP05"] * 3
    msgs = " | ".join(f.message for f in active)
    assert "time.time()" in msgs
    assert "random.random()" in msgs
    assert "np.random.rand" in msgs
    assert [f.rule for f in suppressed] == ["RP05"]
    assert _lint_fixture("rp05_bad.py") == []  # outside ops/: silent


def test_rp06_silent_swallow():
    active, suppressed = _split(
        _lint_fixture("rp06_bad.py", relpath="streaming.py")
    )
    assert [f.rule for f in active] == ["RP06"]
    assert "swallows" in active[0].message
    assert [f.rule for f in suppressed] == ["RP06"]
    assert _lint_fixture("rp06_bad.py") == []  # outside the pipeline set


def test_rp02_unregistered_recovery_event_fixture():
    """ISSUE 6 satellite: an unregistered ``recover.*`` emit is caught
    against the REAL shipped registry — the recovery namespace has no
    family prefix, so each event must be individually registered, and
    the registered one in the same fixture stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("recover.resume")
    assert not real.knows("recover.rogue_replay")
    active, suppressed = _split(
        _lint_fixture("rp02_recover_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'recover.rogue_replay'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_topk_kernel_event_fixture():
    """ISSUE 7 satellite: an unregistered ``topk.kernel.*`` emit is
    caught against the REAL shipped registry — the serving-kernel
    namespace has no family prefix, so each event must be individually
    registered, and the registered dispatch event in the same fixture
    stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("topk.kernel.dispatch")
    assert real.knows("topk.kernel.vmem_retry")
    assert real.knows("topk.kernel.scan_fallback")
    assert not real.knows("topk.kernel.rogue_dispatch")
    active, suppressed = _split(
        _lint_fixture("rp02_topk_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'topk.kernel.rogue_dispatch'" in active[0].message
    assert not suppressed


def test_rp02_unregistered_dma_event_caught_against_real_registry():
    """ISSUE 9 satellite: an unregistered ``kernel.dma.*`` emit is
    caught against the REAL shipped registry — the transform-route
    namespace has no family prefix, so each event must be individually
    registered, and the registered dispatch/fallback events in the same
    fixture stay clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("kernel.dma.dispatch")
    assert real.knows("kernel.dma.fallback")
    assert real.knows("backend.dispatch_fused")
    assert not real.knows("kernel.dma.rogue_retry")
    active, suppressed = _split(
        _lint_fixture("rp02_dma_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'kernel.dma.rogue_retry'" in active[0].message
    assert not suppressed


def test_rp04_zero_and_negative_maxsize_are_unbounded():
    """Python treats any maxsize <= 0 as unbounded — every spelling of
    that must trip RP04, not just the bare constructor."""
    for spelling in ("queue.Queue()", "queue.Queue(0)",
                     "queue.Queue(maxsize=0)", "queue.Queue(maxsize=-1)"):
        fs = rplint.lint_source(f"import queue\nq = {spelling}\n", "x.py")
        assert [f.rule for f in fs] == ["RP04"], spelling
    ok = rplint.lint_source(
        "import queue\nq = queue.Queue(maxsize=8)\n", "x.py"
    )
    assert ok == []


def test_pragma_with_any_unknown_rule_suppresses_nothing():
    """allow[RP04,RP99] is void in full: the RP04 finding stays active
    (plus the RP00 for the typo) — a typo can never accept a
    violation."""
    src = (
        "import queue\n"
        "# rplint: allow[RP04,RP99] — typo'd rule voids the pragma\n"
        "q = queue.Queue()\n"
    )
    fs = rplint.lint_source(src, "x.py")
    assert {f.rule for f in fs if not f.suppressed} == {"RP00", "RP04"}
    assert not [f for f in fs if f.suppressed]


def test_drift_check_requires_the_repo_doc(tmp_path):
    """Installed layout (no docs/ next to the package): the drift check
    stands down instead of flagging every documented-only event; the
    repo layout (doc present) enforces it."""
    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "telemetry.py").write_text(
        "class EVENTS:\n    ROGUE = 'rogue.event'\n    FAMILIES = ()\n"
    )
    (pkg / "utils" / "trace_report.py").write_text("# consumes nothing\n")
    rep = rplint.lint_package(root=str(pkg))
    assert rep["ok"] is True  # no doc on disk: drift leg skipped
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "ARCHITECTURE.md").write_text("nothing here\n")
    rep2 = rplint.lint_package(root=str(pkg))
    assert rep2["ok"] is False
    assert rep2["counts"] == {"RP02": 1}
    assert "rogue.event" in rep2["findings"][-1]["message"]


# -- registry drift ----------------------------------------------------------


def test_registry_drift_check():
    reg = rplint.EventRegistry(
        events={"A": "a.event", "B": "b.event", "C": "c.event"},
        families=(),
        lines={"A": 10, "B": 11, "C": 12},
    )
    findings = rplint.check_registry_drift(
        reg,
        consumer_text="... reads EVENTS.A and also 'b.event' ...",
        doc_text="only c.event is documented here",
    )
    # A consumed by constant reference, B by literal, C documented
    assert findings == []
    findings = rplint.check_registry_drift(
        reg, consumer_text="EVENTS.A", doc_text=""
    )
    assert [(f.rule, f.line) for f in findings] == [
        ("RP02", 11), ("RP02", 12)
    ]
    assert "neither consumed" in findings[0].message


def test_real_registry_parses_statically():
    with open(os.path.join(
        rplint.package_root(), "utils", "telemetry.py"
    )) as f:
        reg = rplint.load_event_registry(f.read())
    assert reg is not None
    assert "stream.commit" in reg.events.values()
    assert "span_start" in reg.events.values()
    assert "hash.batches." in reg.families
    # the static parse agrees with the live module
    from randomprojection_tpu.utils import telemetry

    assert set(reg.events.values()) == set(telemetry._EVENT_NAMES)
    assert reg.families == telemetry.EVENTS.FAMILIES


# -- the shipped tree (acceptance gate) --------------------------------------


def test_shipped_tree_lints_clean():
    """`cli lint` exits 0 on the repo at merge time — the tentpole's
    acceptance criterion.  Every suppression in the tree must carry a
    reason (the pragma grammar guarantees it; assert anyway)."""
    report = rplint.lint_package()
    bad = [f for f in report["findings"] if not f["suppressed"]]
    assert report["ok"], "rplint findings on the shipped tree:\n" + "\n".join(
        "%s:%s: %s %s" % (f["path"], f["line"], f["rule"], f["message"])
        for f in bad
    )
    assert all(
        f["reason"] for f in report["findings"] if f["suppressed"]
    )
    assert report["files"] >= 30  # the walk saw the whole package


def test_cli_lint_exits_zero_and_json_schema(capsys):
    assert cli.main(["lint"]) == 0
    capsys.readouterr()
    assert cli.main(["lint", "--json"]) == 0
    out = capsys.readouterr().out.strip()
    rec = json.loads(out)
    assert rec["rplint"] == 1 and rec["ok"] is True
    assert set(rec) == {
        "rplint", "root", "files", "findings", "counts", "suppressed", "ok"
    }
    for f in rec["findings"]:  # the suppressed ones in the tree
        assert set(f) == {
            "rule", "path", "line", "message", "suppressed", "reason"
        }
        assert f["suppressed"] is True


def test_cli_lint_seeded_violation(tmp_path, capsys):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import queue\nimport threading\n\n"
        "q = queue.Queue()\n"
        "t = threading.Thread(target=print)\n"
        "t.start()\n"
    )
    assert cli.main(["lint", str(bad)]) == 1
    capsys.readouterr()
    assert cli.main(["lint", "--json", str(bad)]) == 1
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["ok"] is False
    rules = {f["rule"] for f in rec["findings"]}
    assert rules == {"RP04"}
    assert rec["counts"]["RP04"] == 3  # unbounded q, no daemon=, no join
    # a pragma with a reason suppresses it, restoring exit 0
    bad.write_text(
        "import queue\n\n"
        "# rplint: allow[RP04] — test: bounded by construction elsewhere\n"
        "q = queue.Queue()\n"
    )
    capsys.readouterr()
    assert cli.main(["lint", str(bad)]) == 0


# -- trace_report's registry-drift warning (ISSUE r10 satellite) -------------


def test_trace_report_warns_on_unregistered_events(tmp_path):
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.trace_report import (
        build_report,
        render_report,
    )

    p = str(tmp_path / "t.jsonl")
    telemetry.configure(p)
    telemetry.emit(telemetry.EVENTS.STREAM_COMMIT, row=0, rows=1)
    telemetry.emit("totally.unknown", x=1)
    telemetry.emit(telemetry.EVENTS.HASH_BATCHES_FAMILY + "strided")
    telemetry.shutdown()
    report = build_report(p)
    assert report["unregistered_events"] == {"totally.unknown": 1}
    text = render_report(report)
    assert "not in the telemetry.EVENTS registry" in text
    assert "totally.unknown" in text

    # a clean file keeps the audit quiet
    p2 = str(tmp_path / "clean.jsonl")
    telemetry.configure(p2)
    telemetry.emit(telemetry.EVENTS.STREAM_COMMIT, row=0, rows=1)
    telemetry.shutdown()
    r2 = build_report(p2)
    assert r2["unregistered_events"] == {}
    assert "not in the telemetry.EVENTS registry" not in render_report(r2)


def test_registered_event_families():
    from randomprojection_tpu.utils import telemetry

    assert telemetry.registered_event("stream.commit")
    assert telemetry.registered_event("hash.batches.python")
    assert not telemetry.registered_event("hash.batch.python")
    assert not telemetry.registered_event("made.up")


def test_rp02_unregistered_shard_event_fixture():
    """ISSUE 8 satellite: an unregistered ``shard.*`` emit is caught
    against the REAL shipped registry — the sharded-tier namespaces
    (`shard.`, `serve.shard.`) have no family prefix, so each event
    must be individually registered, and the registered merge event in
    the same fixture stays clean."""
    real = rplint.load_event_registry(
        open(os.path.join(
            rplint.package_root(), "utils", "telemetry.py"
        )).read()
    )
    assert real is not None and real.knows("shard.merge")
    assert real.knows("shard.topk_tile")
    assert real.knows("serve.shard.batch")
    assert not real.knows("shard.rogue_merge")
    active, suppressed = _split(
        _lint_fixture("rp02_shard_bad.py", registry=real)
    )
    assert [f.rule for f in active] == ["RP02"]
    assert "'shard.rogue_merge'" in active[0].message
    assert not suppressed
