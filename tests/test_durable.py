"""Durable index lifecycle (ISSUE 6): snapshot/restore with checksummed
versioned manifests, tombstones + compaction, exactly-once crash
recovery of a durable ingest, and the subprocess SIGKILL fault
harness asserting bit-identical recovery at every injection point."""

import json
import os
import signal

import numpy as np
import pytest

from randomprojection_tpu import durable
from randomprojection_tpu.durable import (
    DurableIngest,
    check_coverage,
    crash_smoke,
    demo_ingest,
    load_index,
    read_manifest,
    run_child,
    save_index,
    verify_snapshot,
)
from randomprojection_tpu.models.sketch import (
    SimHashIndex,
    SignRandomProjection,
    pairwise_hamming,
    _host_topk_select,
)
from randomprojection_tpu.streaming import CallableSource, FaultInjectionSource
from randomprojection_tpu.utils import telemetry


def _codes(n=300, nbytes=8, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, nbytes), dtype=np.uint8
    )


def _queries(n=7, nbytes=8, seed=99):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, nbytes), dtype=np.uint8
    )


def _filtered_reference(q, codes, dead_ids, m):
    """Host reference: brute-force distances with tombstoned columns
    forced to lose, then the shared (distance, lower-id) selection."""
    D = pairwise_hamming(q, codes).astype(np.int64)
    D[:, np.asarray(dead_ids)] = 10**6
    return _host_topk_select(D, m)


# -- tombstones + compaction -------------------------------------------------


def test_delete_filters_query_topk():
    codes, q = _codes(), _queries()
    idx = SimHashIndex(codes[:120])
    idx.add(codes[120:])
    d0, i0 = idx.query_topk(q, 5)
    # tombstone the top hit of query 0 plus assorted ids across chunks
    dead = sorted({0, 5, int(i0[0, 0]), 250})
    assert idx.delete(dead) == len(dead)
    assert idx.n_deleted == len(dead)
    assert idx.n_live == codes.shape[0] - len(dead)
    ref_d, ref_i = _filtered_reference(q, codes, dead, 5)
    d1, i1 = idx.query_topk(q, 5)
    np.testing.assert_array_equal(d1, ref_d)
    np.testing.assert_array_equal(i1, ref_i)
    assert not np.isin(i1, dead).any()
    # idempotent: re-deleting counts zero and changes nothing
    assert idx.delete([dead[0]]) == 0
    d2, i2 = idx.query_topk(q, 5)
    np.testing.assert_array_equal(i2, i1)


def test_delete_duplicate_ids_count_once(tmp_path):
    """Regression: duplicate ids in ONE delete call must count once —
    over-counting skewed n_deleted/n_live and produced snapshots whose
    manifest deleted-count disagreed with their own bitmap (unloadable)."""
    idx = SimHashIndex(_codes(20))
    assert idx.delete([3, 3, 3, 7]) == 2
    assert idx.n_deleted == 2 and idx.n_live == 18
    idx.save(str(tmp_path))
    assert SimHashIndex.load(str(tmp_path)).n_deleted == 2


def test_delete_validation_and_empty_live():
    idx = SimHashIndex(_codes(10))
    with pytest.raises(ValueError, match="in \\[0, 10\\)"):
        idx.delete([10])
    with pytest.raises(ValueError, match="in \\[0, 10\\)"):
        idx.delete([-1])
    with pytest.raises(ValueError, match="integers"):
        idx.delete([0.5])
    assert idx.delete([]) == 0
    idx.delete(np.arange(10))
    assert idx.n_live == 0
    with pytest.raises(ValueError, match="all deleted"):
        idx.query_topk(_queries(2), 3)


def test_m_eff_counts_live_codes_only():
    codes, q = _codes(20), _queries(3)
    idx = SimHashIndex(codes)
    idx.delete(np.arange(15))  # 5 live
    d, i = idx.query_topk(q, 12)  # m > n_live: width is n_live
    assert d.shape == (3, 5) and i.shape == (3, 5)
    assert set(i.ravel()) <= set(range(15, 20))


def test_dense_fallback_filters_tombstones(monkeypatch):
    import randomprojection_tpu.models.sketch as sk

    codes, q = _codes(60), _queries(4)
    idx = SimHashIndex(codes)
    dead = [2, 17, 40]
    idx.delete(dead)
    ref_d, ref_i = _filtered_reference(q, codes, dead, 6)
    # force the dense query()+host-selection path
    monkeypatch.setattr(
        sk.SimHashIndex, "_topk_route", lambda self, t, m: "dense"
    )
    d, i = idx.query_topk(q, 6)
    np.testing.assert_array_equal(d, ref_d)
    np.testing.assert_array_equal(i, ref_i)


def test_compact_folds_tombstones_and_merges_chunks():
    codes, q = _codes(), _queries()
    idx = SimHashIndex(codes[:100])
    idx.add(codes[100:200])
    idx.add(codes[200:])
    dead = [0, 150, 299]
    idx.delete(dead)
    ref_d, ref_i = _filtered_reference(q, codes, dead, 5)
    mapping = idx.compact()
    assert len(idx._chunks) == 1
    assert idx.n_codes == 297 and idx.n_deleted == 0
    assert mapping.shape == (297,)
    d, i = idx.query_topk(q, 5)
    np.testing.assert_array_equal(d, ref_d)
    # new ids translate back to the old id space through the mapping
    np.testing.assert_array_equal(mapping[i], ref_i)


def test_compact_without_tombstones_is_identity_mapping():
    codes, q = _codes(50), _queries(3)
    idx = SimHashIndex(codes[:20])
    idx.add(codes[20:])
    d0, i0 = idx.query_topk(q, 4)
    mapping = idx.compact()
    np.testing.assert_array_equal(mapping, np.arange(50))
    d1, i1 = idx.query_topk(q, 4)
    np.testing.assert_array_equal(d0, d1)
    np.testing.assert_array_equal(i0, i1)


# -- snapshot/restore --------------------------------------------------------


def test_snapshot_round_trip_multi_chunk_with_tombstones(tmp_path):
    codes, q = _codes(), _queries()
    idx = SimHashIndex(codes[:100], n_bits=61)  # ragged bits round-trip
    idx.add(codes[100:220])
    idx.add(codes[220:])
    idx.delete([3, 7, 150])
    manifest = idx.save(str(tmp_path))
    assert manifest["format_version"] == durable.INDEX_FORMAT_VERSION
    assert len(manifest["chunks"]) == 3
    assert manifest["tombstones"]["deleted"] == 3
    check_coverage(manifest)
    idx2 = SimHashIndex.load(str(tmp_path))
    assert idx2.n_codes == 300 and idx2.n_deleted == 3
    assert idx2.n_bits == 61 and idx2.n_bytes == 8
    assert len(idx2._chunks) == 3  # chunk structure round-trips
    assert [c.n for c in idx2._chunks] == [c.n for c in idx._chunks]
    da, ia = idx.query_topk(q, 6)
    db, ib = idx2.query_topk(q, 6)
    np.testing.assert_array_equal(da, db)
    np.testing.assert_array_equal(ia, ib)


def test_snapshot_resave_bumps_generation_and_sweeps(tmp_path):
    idx = SimHashIndex(_codes(40))
    m0 = save_index(idx, str(tmp_path))
    assert m0["generation"] == 0
    idx.add(_codes(10, seed=5))
    m1 = save_index(idx, str(tmp_path))
    assert m1["generation"] == 1
    # only the new generation's files remain on disk
    spills = sorted(
        f for f in os.listdir(tmp_path) if f.endswith(".npy")
    )
    assert spills == sorted(e["file"] for e in m1["chunks"])
    assert load_index(str(tmp_path)).n_codes == 50


def test_corrupted_chunk_fails_checksum_loudly(tmp_path):
    idx = SimHashIndex(_codes(64))
    manifest = save_index(idx, str(tmp_path))
    path = tmp_path / manifest["chunks"][0]["file"]
    raw = bytearray(path.read_bytes())
    raw[-1] ^= 0xFF
    path.write_bytes(bytes(raw))
    tel = str(tmp_path / "tel.jsonl")
    telemetry.configure(tel)
    try:
        with pytest.raises(ValueError, match="checksum"):
            load_index(str(tmp_path))
    finally:
        telemetry.shutdown()
    events = [
        e for e in telemetry.read_events(tel)
        if e["event"] == "recover.checksum_mismatch"
    ]
    assert len(events) == 1
    assert events[0]["file"] == manifest["chunks"][0]["file"]
    # the operational face reports it without raising, and exits dirty
    status = verify_snapshot(str(tmp_path))
    assert not status["ok"]
    assert [c["file"] for c in status["corrupt"]] == [
        manifest["chunks"][0]["file"]
    ]


def test_unknown_manifest_version_rejected(tmp_path):
    idx = SimHashIndex(_codes(8))
    save_index(idx, str(tmp_path))
    mpath = tmp_path / durable.MANIFEST_NAME
    m = json.loads(mpath.read_text())
    m["format_version"] = 99
    mpath.write_text(json.dumps(m))
    with pytest.raises(ValueError, match="version 99"):
        load_index(str(tmp_path))
    status = verify_snapshot(str(tmp_path))
    assert not status["ok"] and "version 99" in status["error"]


def test_check_coverage_rejects_gaps_and_overlaps():
    good = {"n_codes": 10, "chunks": [
        {"file": "a", "rows": 4, "row0": 0},
        {"file": "b", "rows": 6, "row0": 4},
    ]}
    assert check_coverage(good) == 10
    gap = {"n_codes": 10, "chunks": [
        {"file": "a", "rows": 4, "row0": 0},
        {"file": "b", "rows": 4, "row0": 6},
    ]}
    with pytest.raises(ValueError, match="gaps or overlaps"):
        check_coverage(gap)
    short = {"n_codes": 12, "chunks": good["chunks"]}
    with pytest.raises(ValueError, match="n_codes=12"):
        check_coverage(short)


def test_snapshot_round_trips_across_processes(tmp_path):
    """Acceptance: save/load round-trips a multi-chunk index WITH
    tombstones across processes — a fresh interpreter loads the
    snapshot and answers queries identically."""
    import subprocess
    import sys

    codes, q = _codes(), _queries()
    idx = SimHashIndex(codes[:150])
    idx.add(codes[150:])
    idx.delete([1, 42, 200])
    idx.save(str(tmp_path / "snap"))
    d, i = idx.query_topk(q, 5)
    qf, of = str(tmp_path / "q.npy"), str(tmp_path / "out.npz")
    np.save(qf, q)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", (
            "import numpy as np\n"
            "from randomprojection_tpu.models.sketch import SimHashIndex\n"
            f"idx = SimHashIndex.load({str(tmp_path / 'snap')!r})\n"
            "assert idx.n_deleted == 3 and len(idx._chunks) == 2\n"
            f"d, i = idx.query_topk(np.load({qf!r}), 5)\n"
            f"np.savez({of!r}, d=d, i=i)\n"
        )],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = np.load(of)
    np.testing.assert_array_equal(out["d"], d)
    np.testing.assert_array_equal(out["i"], i)


# -- cursor durability (satellite) -------------------------------------------


def test_stream_cursor_save_fsyncs_file_and_directory(
    tmp_path, monkeypatch
):
    """A machine crash (not just a process crash) must not surface an
    empty/stale cursor: the temp file is fsync'd before the rename and
    the directory after it."""
    import randomprojection_tpu.streaming as streaming

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(
        streaming.os, "fsync", lambda fd: (synced.append(fd),
                                           real_fsync(fd))[1]
    )
    path = str(tmp_path / "cursor.json")
    streaming.StreamCursor(rows_done=64).save(path)
    # one fsync for the temp file's data, one for the directory entry
    assert len(synced) >= 2
    assert streaming.StreamCursor.load(path).rows_done == 64
    assert not os.path.exists(path + ".tmp")


# -- durable ingest ----------------------------------------------------------


def _toy_stream(rows=96, batch_rows=32, d=8, bits=32, seed=1):
    def read(lo, hi):
        rng = np.random.default_rng([seed, lo])
        return rng.standard_normal((hi - lo, d), dtype=np.float32)

    source = CallableSource(read, rows, d, dtype=np.float32,
                            batch_rows=batch_rows)
    est = SignRandomProjection(bits, random_state=seed, backend="numpy")
    est.fit_source(source)
    return est, source


def test_durable_ingest_fresh_then_idempotent(tmp_path):
    est, source = _toy_stream()
    path = str(tmp_path / "run")
    idx = DurableIngest(path).run(est, source)
    assert idx.n_codes == 96 and len(idx._chunks) == 3
    manifest = read_manifest(path)
    assert manifest["ingest"]["rows_done"] == 96
    check_coverage(manifest)
    # re-running a completed ingest replays nothing and changes nothing
    shas = [e["sha256"] for e in manifest["chunks"]]
    idx2 = DurableIngest(path).run(est, source)
    assert idx2.n_codes == 96
    assert [e["sha256"] for e in read_manifest(path)["chunks"]] == shas


def test_durable_ingest_rejects_non_code_estimators(tmp_path):
    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.streaming import ArraySource

    X = np.zeros((8, 4), np.float32)
    est = GaussianRandomProjection(2, random_state=0, backend="numpy")
    source = ArraySource(X, 4)
    est.fit_source(source)
    with pytest.raises(ValueError, match="uint8"):
        DurableIngest(str(tmp_path / "x")).run(est, source)


def test_durable_ingest_rejects_mismatched_resume(tmp_path):
    est, source = _toy_stream(bits=32)
    path = str(tmp_path / "run")
    DurableIngest(path).run(est, source)
    est2, source2 = _toy_stream(bits=64)
    with pytest.raises(ValueError, match="mix two projections"):
        DurableIngest(path).run(est2, source2)
    # a plain snapshot dir is not an ingest dir
    snap = str(tmp_path / "snap")
    save_index(SimHashIndex(_codes(4, nbytes=4)), snap)
    with pytest.raises(ValueError, match="not a durable\\s+ingest"):
        DurableIngest(snap).run(est, source)


def test_durable_ingest_rejects_same_shape_different_projection(tmp_path):
    """Same bits/bytes but a different SEED is a different projection:
    the manifest records the estimator fingerprint and a mismatched
    resume is refused instead of silently mixing matrices."""
    est, source = _toy_stream(rows=160, batch_rows=32, seed=1)
    path = str(tmp_path / "run")
    faulty = FaultInjectionSource(source, fail_after_batches=2)
    with pytest.raises(FaultInjectionSource.InjectedFault):
        DurableIngest(path).run(est, faulty)
    manifest = read_manifest(path)
    assert manifest["ingest"]["estimator"]["class"] == (
        "SignRandomProjection"
    )
    other = SignRandomProjection(32, random_state=2, backend="numpy")
    other.fit_source(source)
    with pytest.raises(ValueError, match="mix two projections"):
        DurableIngest(path).run(other, source)


def test_verify_snapshot_reports_malformed_manifest_body(tmp_path):
    save_index(SimHashIndex(_codes(8)), str(tmp_path))
    mpath = tmp_path / durable.MANIFEST_NAME
    m = json.loads(mpath.read_text())
    del m["chunks"]  # right version/kind, truncated body
    mpath.write_text(json.dumps(m))
    status = verify_snapshot(str(tmp_path))
    assert not status["ok"]
    assert "malformed manifest" in status["error"]


def test_durable_ingest_crash_resume_bit_identical(tmp_path):
    """In-process crash (raised mid-stream) → resume replays exactly
    the uncommitted row ranges; manifest + codes bit-identical to an
    uninterrupted run, with recover.resume on the telemetry spine."""
    est, source = _toy_stream(rows=160, batch_rows=32)
    clean = str(tmp_path / "clean")
    DurableIngest(clean).run(est, source)
    clean_manifest = read_manifest(clean)

    crashed = str(tmp_path / "crashed")
    faulty = FaultInjectionSource(source, fail_after_batches=3)
    with pytest.raises(FaultInjectionSource.InjectedFault):
        DurableIngest(crashed).run(est, faulty)
    partial = read_manifest(crashed)
    assert 0 < partial["ingest"]["rows_done"] < 160
    check_coverage(partial)

    tel = str(tmp_path / "tel.jsonl")
    telemetry.configure(tel)
    try:
        faulty.disarm()
        idx = DurableIngest(crashed).run(est, faulty)
    finally:
        telemetry.shutdown()
    assert idx.n_codes == 160
    recovered = read_manifest(crashed)
    check_coverage(recovered)
    assert [e["sha256"] for e in recovered["chunks"]] == [
        e["sha256"] for e in clean_manifest["chunks"]
    ]
    resumes = [
        e for e in telemetry.read_events(tel)
        if e["event"] == "recover.resume"
    ]
    assert len(resumes) == 1
    assert resumes[0]["rows_done"] == partial["ingest"]["rows_done"]
    assert resumes[0]["replay_rows"] == 160 - resumes[0]["rows_done"]
    # the doctor consumes the resume into its recovery section
    from randomprojection_tpu.utils.trace_report import build_report

    report = build_report(tel)
    assert report["recovery"]["resumes"] == [{
        "rows_done": resumes[0]["rows_done"],
        "replay_rows": resumes[0]["replay_rows"],
    }]


def test_durable_ingest_commit_every_amortizes(tmp_path):
    est, source = _toy_stream(rows=96, batch_rows=16)
    path = str(tmp_path / "run")
    DurableIngest(path, commit_every_batches=3).run(est, source)
    manifest = read_manifest(path)
    assert manifest["ingest"]["rows_done"] == 96
    check_coverage(manifest)
    assert len(manifest["chunks"]) == 6  # still one spill per batch


def test_durable_ingest_compaction_bounds_chunks(tmp_path):
    est, source = _toy_stream(rows=96, batch_rows=16)
    compacted = str(tmp_path / "compacted")
    idx = DurableIngest(
        compacted, compact_after_chunks=3
    ).run(est, source)
    manifest = read_manifest(compacted)
    assert manifest["generation"] >= 1
    assert len(manifest["chunks"]) < 6
    check_coverage(manifest)
    # only referenced spills remain; content identical to the plain run
    spills = sorted(
        f for f in os.listdir(compacted) if f.endswith(".npy")
    )
    assert spills == sorted(e["file"] for e in manifest["chunks"])
    plain = str(tmp_path / "plain")
    DurableIngest(plain).run(est, source)
    np.testing.assert_array_equal(
        durable._codes_of(compacted), durable._codes_of(plain)
    )
    q = _queries(4, nbytes=4)  # 32-bit codes
    da, ia = idx.query_topk(q, 5)
    db, ib = load_index(plain).query_topk(q, 5)
    np.testing.assert_array_equal(da, db)
    np.testing.assert_array_equal(ia, ib)


# -- the subprocess SIGKILL fault harness ------------------------------------


def test_process_kill_matrix_recovers_bit_identical(tmp_path):
    """THE acceptance gate: SIGKILL a real subprocess ingest at every
    injected point (mid-batch, post-yield pre-ack, mid-snapshot-rename),
    restart it, and assert no row range was dropped or double-committed
    and the recovered index — codes, manifest checksums, query results —
    is bit-identical to an uninterrupted run."""
    verdict = crash_smoke(str(tmp_path), rows=128, batch_rows=32)
    assert verdict["ok"], json.dumps(verdict, indent=1)
    assert {c["kill_at"] for c in verdict["cases"]} == set(
        durable.KILL_POINTS
    )
    for case in verdict["cases"]:
        assert case["crash_returncode"] == -signal.SIGKILL
        assert case["resume_returncode"] == 0
        assert case["bit_identical_codes"]
        assert case["manifest_chunks_identical"]
        assert case["query_results_match"]


def test_kill_env_spec_fires_at_nth_hit(tmp_path):
    """The injection hook itself: a child with RP_DURABLE_KILL dies by
    SIGKILL (uncatchable — rc -9, not an exception path) exactly at the
    named point, leaving a committed prefix behind."""
    path = str(tmp_path / "run")
    proc = run_child(path, rows=128, batch_rows=32,
                     kill="post-yield-pre-ack@2")
    assert proc.returncode == -signal.SIGKILL
    manifest = read_manifest(path)
    # one batch committed (the kill fired during the second commit),
    # and the second batch's chunk file is an uncommitted orphan
    assert manifest["ingest"]["rows_done"] == 32
    orphans = durable._scan_orphans(path, manifest)
    assert len(orphans) == 1


# -- cli recover -------------------------------------------------------------


def test_cli_recover_status_and_child(tmp_path, capsys):
    from randomprojection_tpu import cli

    path = str(tmp_path / "run")
    rc = cli.main([
        "recover", "--child", path, "--rows", "64", "--batch-rows", "32",
        "--d", "8", "--bits", "32",
    ])
    assert rc == 0
    child = json.loads(capsys.readouterr().out)
    assert child["rows_done"] == 64 and child["chunks"] == 2
    rc = cli.main(["recover", path])
    assert rc == 0
    status = json.loads(capsys.readouterr().out)
    assert status["ok"] and status["rows_done"] == 64
    assert status["chunks"] == 2 and status["coverage_ok"]
    # corruption → non-zero exit, corrupt file named
    manifest = read_manifest(path)
    f = tmp_path / "run" / manifest["chunks"][1]["file"]
    raw = bytearray(f.read_bytes())
    raw[-1] ^= 0xFF
    f.write_bytes(bytes(raw))
    rc = cli.main(["recover", path])
    assert rc == 1
    status = json.loads(capsys.readouterr().out)
    assert not status["ok"]
    assert status["corrupt"][0]["file"] == manifest["chunks"][1]["file"]


def test_cli_recover_requires_dir(capsys):
    from randomprojection_tpu import cli

    with pytest.raises(SystemExit, match="requires DIR"):
        cli.main(["recover"])


# ---------------------------------------------------------------------------
# sharded snapshots (ISSUE 8): mesh-agnostic save/restore
# ---------------------------------------------------------------------------


def _sharded(codes, n_shards, **kw):
    from randomprojection_tpu.serving import ShardedSimHashIndex

    return ShardedSimHashIndex(
        codes, n_shards=n_shards, topk_impl="scan", **kw
    )


def test_sharded_snapshot_restores_under_any_layout(tmp_path):
    """Save under an 8-way layout, load under 4-way / 2-way / plain
    single-device: codes, tombstones and query_topk results must be
    bit-identical — the snapshot is the corpus in global id order, so
    the layout is a load-time choice."""
    from randomprojection_tpu.serving import ShardedSimHashIndex

    codes = _codes(260, 4, seed=11)
    queries = _codes(10, 4, seed=12)
    idx = _sharded(codes, 8)
    idx.delete(np.arange(60, 110))  # spans 8-way shard boundaries
    ref_d, ref_i = idx.query_topk(queries, 6)
    d = str(tmp_path / "snap")
    manifest = idx.save(d)
    assert manifest["sharded"] == {"shards": 8}
    assert len(manifest["chunks"]) == 8
    check_coverage(manifest)
    for n_shards in (4, 2, 1):
        r = ShardedSimHashIndex.load(d, n_shards=n_shards,
                                     topk_impl="scan")
        assert r.n_codes == 260 and r.n_deleted == 50
        got_d, got_i = r.query_topk(queries, 6)
        assert np.array_equal(got_d, ref_d), n_shards
        assert np.array_equal(got_i, ref_i), n_shards
    plain = load_index(d)
    assert plain.n_codes == 260 and plain.n_deleted == 50
    pd, pi = plain.query_topk(queries, 6)
    assert np.array_equal(pd, ref_d)
    assert np.array_equal(pi.astype(np.int64), ref_i)
    status = verify_snapshot(d)
    assert status["ok"] and status["sharded"] == 8
    assert status["deleted"] == 50


def test_plain_snapshot_loads_sharded(tmp_path):
    """The reverse direction: a plain save_index snapshot restores onto
    any shard layout with identical results."""
    from randomprojection_tpu.serving import ShardedSimHashIndex

    codes = _codes(200, 4, seed=13)
    queries = _codes(8, 4, seed=14)
    plain = SimHashIndex(codes, topk_impl="scan")
    plain.delete(np.arange(25))
    ref_d, ref_i = plain.query_topk(queries, 5)
    d = str(tmp_path / "snap")
    save_index(plain, d)
    r = ShardedSimHashIndex.load(d, n_shards=3, topk_impl="scan")
    got_d, got_i = r.query_topk(queries, 5)
    assert np.array_equal(got_d, ref_d)
    assert np.array_equal(got_i, ref_i.astype(np.int64))


def test_sharded_snapshot_id_offset_round_trip(tmp_path):
    """id_offset persists in the manifest, restores through the sharded
    loader, and the plain loader refuses the snapshot pointedly (it
    would silently renumber the corpus)."""
    from randomprojection_tpu.serving import ShardedSimHashIndex

    off = 2**31 + 23
    codes = _codes(120, 4, seed=15)
    queries = _codes(6, 4, seed=16)
    idx = _sharded(codes, 4, id_offset=off)
    ref_d, ref_i = idx.query_topk(queries, 4)
    assert int(ref_i.min()) > 2**31
    d = str(tmp_path / "snap")
    manifest = idx.save(d)
    assert manifest["id_offset"] == off
    r = ShardedSimHashIndex.load(d, n_shards=2, topk_impl="scan")
    assert r.id_offset == off
    got_d, got_i = r.query_topk(queries, 4)
    assert np.array_equal(got_d, ref_d)
    assert np.array_equal(got_i, ref_i)
    with pytest.raises(ValueError, match="id_offset"):
        load_index(d)


def test_sharded_snapshot_checksum_verified_before_upload(tmp_path):
    """A corrupted shard-chunk spill fails the load loudly BEFORE any
    upload, with the recover.checksum_mismatch event on the spine."""
    from randomprojection_tpu.serving import ShardedSimHashIndex

    codes = _codes(100, 4, seed=17)
    idx = _sharded(codes, 4)
    d = str(tmp_path / "snap")
    manifest = idx.save(d)
    victim = manifest["chunks"][2]["file"]
    path = os.path.join(d, victim)
    raw = np.load(path)
    raw[0, 0] ^= 0xFF
    with open(path, "wb") as f:
        np.save(f, raw)
    tel = str(tmp_path / "events.jsonl")
    telemetry.configure(tel)
    try:
        with pytest.raises(ValueError, match="checksum"):
            ShardedSimHashIndex.load(d, n_shards=2)
    finally:
        telemetry.shutdown()
    names = [e["event"] for e in telemetry.read_events(tel)]
    assert "recover.checksum_mismatch" in names


def test_sharded_snapshot_resave_advances_generation(tmp_path):
    """Re-saving a sharded index over its own snapshot writes a new
    generation and sweeps the old files — same crash discipline as
    save_index."""
    codes = _codes(90, 4, seed=18)
    idx = _sharded(codes, 3)
    d = str(tmp_path / "snap")
    m1 = idx.save(d)
    idx.add(_codes(30, 4, seed=19))
    m2 = idx.save(d)
    assert m2["generation"] == m1["generation"] + 1
    on_disk = {f for f in os.listdir(d) if f.endswith(".npy")}
    assert on_disk == {e["file"] for e in m2["chunks"]}
    assert check_coverage(m2) == 120
