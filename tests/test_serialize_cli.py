"""Persistence, observability, and CLI tests (SURVEY.md §6 subsystems)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from randomprojection_tpu import (
    CountSketch,
    GaussianRandomProjection,
    SignRandomProjection,
    SparseRandomProjection,
)
from randomprojection_tpu.serialize import load_model, save_model


@pytest.mark.parametrize(
    "make",
    [
        lambda: GaussianRandomProjection(16, random_state=7, backend="numpy"),
        lambda: SparseRandomProjection(16, random_state=7, density=0.25,
                                       backend="numpy"),
        lambda: SignRandomProjection(16, random_state=7, backend="numpy"),
        lambda: CountSketch(16, random_state=7, backend="numpy"),
    ],
)
def test_save_load_roundtrip(tmp_path, make):
    X = np.random.default_rng(0).normal(size=(50, 128)).astype(np.float32)
    est = make().fit(X)
    Y = np.asarray(est.transform(X))
    p = str(tmp_path / "model.json")
    save_model(est, p)
    est2 = load_model(p, backend="numpy")
    np.testing.assert_array_equal(np.asarray(est2.transform(X)), Y)


def test_save_load_cross_backend_same_family(tmp_path):
    """jax→jax reload reproduces exactly (counter-based PRNG from the seed)."""
    X = np.random.default_rng(0).normal(size=(40, 96)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=3, backend="jax").fit(X)
    Y = np.asarray(est.transform(X))
    p = str(tmp_path / "m.json")
    save_model(est, p)
    np.testing.assert_array_equal(
        np.asarray(load_model(p, backend="jax").transform(X)), Y
    )


def test_save_with_matrix_bundle(tmp_path):
    X = np.random.default_rng(0).normal(size=(30, 64))
    est = GaussianRandomProjection(
        8, random_state=0, backend="numpy", compute_inverse_components=True
    ).fit(X)
    p = str(tmp_path / "m.json")
    save_model(est, p, include_matrix=True)
    bundle = np.load(p + ".npz")
    np.testing.assert_array_equal(bundle["components"], est.components_)
    assert bundle["inverse_components"].shape == (64, 8)


def test_load_lazy_model_refuses_foreign_backend(tmp_path):
    """A lazy-fitted (Pallas-PRNG) model must not silently re-materialize
    as a different matrix family on another backend."""
    p = tmp_path / "m.json"
    p.write_text(json.dumps({
        "format_version": 1,
        "class": "SparseRandomProjection",
        "spec": {"kind": "sparse", "n_components": 16, "n_features": 64,
                 "seed": 3, "density": 0.25, "dtype": "float32"},
        "params": {"dense_output": False, "compute_inverse_components": False},
        "backend_options": {"materialization": "lazy"},
    }))
    with pytest.raises(ValueError, match="cannot be loaded"):
        load_model(str(p), backend="numpy")


def test_matrix_bundle_roundtrip_and_missing_npz_pointed_error(tmp_path):
    """ISSUE 6 satellite: both directions of the include_matrix round
    trip, and a payload promising a bundle whose sibling .npz is gone
    fails with a pointed error naming the expected path — not an opaque
    downstream exception."""
    X = np.random.default_rng(1).normal(size=(30, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=2, backend="numpy").fit(X)
    Y = np.asarray(est.transform(X))
    p = str(tmp_path / "m.json")
    # direction 1: save with bundle -> load (bundle present) -> identical
    save_model(est, p, include_matrix=True)
    est2 = load_model(p, backend="numpy")
    np.testing.assert_array_equal(np.asarray(est2.transform(X)), Y)
    # direction 2: the reloaded estimator re-saves to an equivalent
    # artifact a fresh load also reproduces from
    p2 = str(tmp_path / "m2.json")
    save_model(est2, p2, include_matrix=True)
    b1, b2 = np.load(p + ".npz"), np.load(p2 + ".npz")
    np.testing.assert_array_equal(b1["components"], b2["components"])
    np.testing.assert_array_equal(
        np.asarray(load_model(p2, backend="numpy").transform(X)), Y
    )
    # missing sibling bundle: pointed failure naming the expected path
    os.remove(p + ".npz")
    with pytest.raises(ValueError, match="include_matrix") as ei:
        load_model(p)
    assert str(tmp_path / "m.json.npz") in str(ei.value)


def test_load_rejects_bad_version(tmp_path):
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"format_version": 99, "class": "X"}))
    with pytest.raises(ValueError, match="version"):
        load_model(str(p))


def test_unfitted_save_raises(tmp_path):
    from randomprojection_tpu import NotFittedError

    with pytest.raises(NotFittedError):
        save_model(GaussianRandomProjection(4), str(tmp_path / "m.json"))


def test_stream_stats():
    from randomprojection_tpu.streaming import ArraySource
    from randomprojection_tpu.utils.observability import StreamStats

    X = np.random.default_rng(0).normal(size=(500, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    stats = StreamStats()
    for _ in est.transform_stream(ArraySource(X, 100), stats=stats):
        pass
    s = stats.summary()
    assert s["rows"] == 500 and s["batches"] == 5
    assert s["bytes_in"] == X.nbytes
    assert s["rows_per_s"] > 0


def test_stream_to_array_resume_and_empty(tmp_path):
    from randomprojection_tpu.streaming import (
        ArraySource,
        StreamCursor,
        stream_to_array,
    )

    X = np.random.default_rng(0).normal(size=(500, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    src = ArraySource(X, 100)
    ckpt = str(tmp_path / "c.json")
    Y = stream_to_array(est, src, checkpoint_path=ckpt)
    assert Y.shape == (500, 8)
    # completed checkpoint, no buffer → empty result, not a crash
    Y2 = stream_to_array(est, src, checkpoint_path=ckpt)
    assert Y2.shape == (0, 8)
    # partial checkpoint without the original buffer → loud error
    StreamCursor(rows_done=200).save(ckpt)
    with pytest.raises(ValueError, match="uninitialized"):
        stream_to_array(est, src, checkpoint_path=ckpt)
    # with the buffer: fills the remaining rows, result complete
    out = np.zeros((500, 8), dtype=np.float32)
    out[:200] = Y[:200]
    Y3 = stream_to_array(est, src, checkpoint_path=ckpt, out=out)
    np.testing.assert_array_equal(Y3, Y)


def test_countsketch_f64_identical_across_backends():
    X = np.random.default_rng(0).normal(size=(20, 100))  # float64
    Yj = CountSketch(16, random_state=0, backend="jax").fit(X).transform(X)
    Yn = CountSketch(16, random_state=0, backend="numpy").fit(X).transform(X)
    assert Yj.dtype == np.float64
    np.testing.assert_array_equal(Yj, Yn)


def test_stream_stats_single_batch_sane():
    from randomprojection_tpu.streaming import ArraySource
    from randomprojection_tpu.utils.observability import StreamStats

    X = np.random.default_rng(0).normal(size=(200, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    stats = StreamStats()
    for _ in est.transform_stream(ArraySource(X, 1000), stats=stats):
        pass
    # one batch: the clock must span the whole pipeline, not be ~1e-9
    assert stats.batches == 1
    assert stats.rows_per_s() < 1e10


def test_packaging_entry_point_and_version():
    """pyproject.toml must declare a resolvable console entry point and a
    version matching the package (`pip install -e . && randomprojection-tpu
    info` is the end-to-end check; this guards the wiring in CI)."""
    import importlib
    import os

    tomllib = pytest.importorskip("tomllib")  # stdlib from 3.11

    import randomprojection_tpu as rp

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    target = meta["project"]["scripts"]["randomprojection-tpu"]
    mod, fn = target.split(":")
    assert callable(getattr(importlib.import_module(mod), fn))
    assert meta["project"]["version"] == rp.__version__
    # the C++ source ships with the wheel (built at first use)
    assert "*.cpp" in str(meta["tool"]["setuptools"]["package-data"])


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "randomprojection_tpu", *argv],
        capture_output=True, text=True, timeout=300,
        env={"PYTHONPATH": ".", "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": "/root"},
    )


def test_cli_jl_dim():
    r = _run_cli("jl-dim", "--n-samples", "1000000", "--eps", "0.5")
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "663"


def test_cli_info():
    r = _run_cli("info")
    assert r.returncode == 0, r.stderr
    info = json.loads(r.stdout)
    assert "numpy" in info["backends"] and "jax" in info["backends"]
    assert info["native_murmur3"] is True


def test_cli_project_checkpoint_resume(tmp_path):
    """project --checkpoint: durable memmap output, resumable, and a
    completed run is never silently overwritten (in-process for speed)."""
    import os

    from randomprojection_tpu import cli
    from randomprojection_tpu.streaming import StreamCursor

    X = np.random.default_rng(0).normal(size=(300, 128)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    yout = str(tmp_path / "y.npy")
    ckpt = str(tmp_path / "cursor.json")
    np.save(xin, X)
    argv = [
        "project", "--input", xin, "--output", yout,
        "--kind", "gaussian", "--n-components", "16",
        "--backend", "numpy", "--batch-rows", "100", "--seed", "5",
        "--checkpoint", ckpt,
    ]
    cli.main(argv)
    ref = np.asarray(
        GaussianRandomProjection(16, random_state=5, backend="numpy")
        .fit(X).transform(X)
    )
    np.testing.assert_allclose(np.load(yout), ref, rtol=1e-6)
    assert StreamCursor.load(ckpt).rows_done == 300

    # rerun after completion: refuse, and leave the output untouched
    with pytest.raises(SystemExit, match="completed"):
        cli.main(argv)
    np.testing.assert_allclose(np.load(yout), ref, rtol=1e-6)

    # mid-run resume: corrupt the un-committed tail, rewind the cursor —
    # the rerun must fill exactly the remaining rows
    out = np.lib.format.open_memmap(yout, mode="r+")
    out[100:] = -1.0
    out.flush()
    del out
    StreamCursor(rows_done=100).save(ckpt)
    cli.main(argv)
    np.testing.assert_allclose(np.load(yout), ref, rtol=1e-6)

    # resuming with different parameters must refuse (would silently mix
    # two projections in one output file)
    StreamCursor(rows_done=100).save(ckpt)
    argv_other_seed = [a if a != "5" else "6" for a in argv]
    with pytest.raises(SystemExit, match="different parameters"):
        cli.main(argv_other_seed)

    # ...and so must resuming against a different input file, even one
    # with identical shape
    xin2 = str(tmp_path / "x2.npy")
    np.save(xin2, X)
    argv_other_input = [a if a != xin else xin2 for a in argv]
    with pytest.raises(SystemExit, match="different parameters"):
        cli.main(argv_other_input)

    # a partial cursor whose output file vanished cannot resume
    StreamCursor(rows_done=100).save(ckpt)
    os.remove(yout)
    with pytest.raises(SystemExit, match="does not exist"):
        cli.main(argv)


def test_cli_project_roundtrip(tmp_path):
    X = np.random.default_rng(0).normal(size=(300, 128)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    yout = str(tmp_path / "y.npy")
    np.save(xin, X)
    r = _run_cli(
        "project", "--input", xin, "--output", yout,
        "--kind", "gaussian", "--n-components", "16",
        "--backend", "numpy", "--batch-rows", "100", "--seed", "5",
    )
    assert r.returncode == 0, r.stderr
    meta = json.loads(r.stdout.splitlines()[-1])
    assert meta["shape"] == [300, 16] and meta["rows"] == 300
    Y = np.load(yout)
    ref = GaussianRandomProjection(16, random_state=5, backend="numpy").fit(X)
    np.testing.assert_allclose(Y, np.asarray(ref.transform(X)), rtol=1e-6)


def test_stream_bench_kinds_and_flags(capsys):
    """stream-bench must honor --kind and forward --precision/
    --materialization into the estimator (round-2 weak #1: the flags were
    accepted but silently dropped, and the kind was hardcoded gaussian)."""
    from randomprojection_tpu import cli

    argv = [
        "stream-bench", "--rows", "512", "--d", "64", "--k", "16",
        "--batch-rows", "256", "--kind", "sparse", "--density", "0.5",
        "--backend", "jax", "--precision", "split2",
    ]
    # the estimator the command builds carries the flags
    args = cli.build_parser().parse_args(argv)
    args.n_components = args.k
    est = cli._make_estimator(args)
    assert type(est).__name__ == "SparseRandomProjection"
    assert est.backend_options == {"precision": "split2"}
    assert est.density == 0.5

    cli.main(argv)
    out = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert out["kind"] == "sparse"
    assert out["backend_options"] == {"precision": "split2"}
    assert out["value"] > 0


def test_stream_bench_sign_kind(capsys):
    from randomprojection_tpu import cli

    cli.main([
        "stream-bench", "--rows", "256", "--d", "64", "--k", "16",
        "--batch-rows", "128", "--kind", "sign", "--backend", "numpy",
    ])
    out = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert out["kind"] == "sign" and out["value"] > 0


def test_countsketch_rejects_precision_flags():
    """CountSketch has no precision/materialization knobs: refusing beats
    silently dropping the flags (flag-honesty contract)."""
    from randomprojection_tpu import cli

    args = cli.build_parser().parse_args(
        ["stream-bench", "--kind", "countsketch", "--precision", "high"]
    )
    args.n_components = args.k
    with pytest.raises(SystemExit, match="not supported"):
        cli._make_estimator(args)


def test_density_flag_refused_for_non_sparse_kinds():
    from randomprojection_tpu import cli

    for kind in ("gaussian", "sign", "countsketch"):
        args = cli.build_parser().parse_args(
            ["stream-bench", "--kind", kind, "--density", "0.5"]
        )
        args.n_components = args.k
        with pytest.raises(SystemExit, match="density"):
            cli._make_estimator(args)


def test_cli_debug_flags_smoke(tmp_path):
    """--debug-nans/--disable-jit (SURVEY.md §6 debug switches) apply and the
    projection still runs; config is restored so other tests are unaffected."""
    import jax

    from randomprojection_tpu import cli

    X = np.random.default_rng(0).normal(size=(60, 32)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    yout = str(tmp_path / "y.npy")
    np.save(xin, X)
    try:
        cli.main([
            "project", "--input", xin, "--output", yout,
            "--kind", "gaussian", "--n-components", "8",
            "--backend", "jax", "--batch-rows", "32",
            "--debug-nans", "--disable-jit",
        ])
        assert jax.config.jax_debug_nans and jax.config.jax_disable_jit
    finally:
        jax.config.update("jax_debug_nans", False)
        jax.config.update("jax_disable_jit", False)
    assert np.load(yout).shape == (60, 8)


def test_profile_trace_emits_named_stages(tmp_path):
    """A profiled streamed run writes a trace and the stage annotations are
    live code paths (rp:stream/dispatch, rp:backend/prepare, ...)."""
    import os

    from randomprojection_tpu import GaussianRandomProjection
    from randomprojection_tpu.streaming import ArraySource, stream_to_array
    from randomprojection_tpu.utils.observability import annotate, profile_trace

    # annotate returns a live TraceAnnotation once jax is imported
    import jax  # noqa: F401

    ctx = annotate("rp:test")
    assert type(ctx).__name__ == "TraceAnnotation"

    X = np.random.default_rng(0).normal(size=(100, 32)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="jax").fit(X)
    trace_dir = str(tmp_path / "trace")
    with profile_trace(trace_dir):
        stream_to_array(est, ArraySource(X, batch_rows=50))
    files = [
        os.path.join(dp, f) for dp, _, fs in os.walk(trace_dir) for f in fs
    ]
    assert files, "profiler trace directory is empty"


def test_save_load_preserves_countsketch_use_mxu(tmp_path):
    """use_mxu is part of the numeric contract (MXU = f32-grade vs scatter =
    exact): it must survive save/load, or a reload silently reverts the
    exact-reproducibility opt-out."""
    from randomprojection_tpu import CountSketch

    X = np.zeros((10, 64), dtype=np.float32)
    p = str(tmp_path / "cs.json")
    est = CountSketch(16, random_state=0, backend="jax", use_mxu=False).fit(X)
    save_model(est, p)
    assert load_model(p).use_mxu is False
    est2 = CountSketch(16, random_state=0, backend="numpy").fit(X)
    save_model(est2, p)
    assert load_model(p).use_mxu is None


def test_api_doc_in_sync():
    """docs/API.md is generated; fail if the surface changed without
    regenerating (python docs/gen_api.py)."""
    import pathlib
    import subprocess
    import sys as _sys
    import tempfile

    repo = pathlib.Path(__file__).resolve().parents[1]
    current = (repo / "docs" / "API.md").read_text()
    with tempfile.TemporaryDirectory() as td:
        out = pathlib.Path(td) / "API.md"
        env = {"PYTHONPATH": str(repo), "PATH": "/usr/bin:/bin", "HOME": "/root",
               "JAX_PLATFORMS": "cpu", "RP_API_OUT": str(out)}
        subprocess.run(
            [_sys.executable, str(repo / "docs" / "gen_api.py")],
            check=True, env=env, timeout=240, capture_output=True,
        )
        regenerated = out.read_text()
    assert regenerated == current, (
        "docs/API.md is stale — run `python docs/gen_api.py`"
    )


def test_stream_bench_bf16_dtype(capsys):
    from randomprojection_tpu import cli

    cli.main([
        "stream-bench", "--rows", "256", "--d", "64", "--k", "16",
        "--batch-rows", "128", "--kind", "gaussian", "--backend", "jax",
        "--dtype", "bfloat16",
    ])
    out = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert out["dtype"] == "bfloat16" and out["value"] > 0
    # half the f32 bytes crossed the link
    assert out["bytes_in"] == 256 * 64 * 2


def test_bf16_model_loads_in_fresh_process(tmp_path):
    """A bf16-fitted model must reload in a fresh interpreter where
    ml_dtypes was never imported (np.dtype('bfloat16') alone raises there;
    the spec resolves it via the helper)."""
    import ml_dtypes

    X = np.random.default_rng(0).normal(size=(30, 64)).astype(ml_dtypes.bfloat16)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(X)
    p = str(tmp_path / "m16.json")
    save_model(est, p)

    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from randomprojection_tpu.serialize import load_model\n"
        "est = load_model(%r, backend='numpy')\n"
        "print(est.spec_.dtype)\n"
    ) % (str(__import__('pathlib').Path(__file__).resolve().parents[1]), p)
    r = subprocess.run(
        [sys.executable, "-I", "-c", code],
        capture_output=True, text=True, timeout=240,
        env={"PATH": "/usr/bin:/bin", "HOME": "/root", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "bfloat16"


def test_f32_sparse_estimator_accepts_bf16_input_numpy():
    """Review regression: f32-fitted sparse estimator on the numpy backend
    must not crash on bf16 input (scipy CSR can't matmul ml_dtypes); the
    spec owns the output dtype, so the result is f32."""
    import ml_dtypes

    X32 = np.random.default_rng(0).normal(size=(50, 128)).astype(np.float32)
    est = SparseRandomProjection(
        8, density=1 / 3, random_state=0, backend="numpy"
    ).fit(X32)
    Y = np.asarray(est.transform(X32.astype(ml_dtypes.bfloat16)))
    assert Y.dtype == np.float32
    np.testing.assert_allclose(
        Y, np.asarray(est.transform(X32)), rtol=2e-2, atol=2e-2
    )


def test_cli_project_consumes_bf16_npy(tmp_path):
    """The tool must consume its own bf16 outputs: np.load of a bf16 .npy
    yields raw void ('|V2'); cmd_project restores the typed view."""
    import ml_dtypes

    from randomprojection_tpu import cli

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X = np.random.default_rng(0).normal(size=(60, 32)).astype(bf16)
    xin, yout = str(tmp_path / "x.npy"), str(tmp_path / "y.npy")
    np.save(xin, X)
    assert np.load(xin).dtype.kind == "V"  # the degradation being fixed
    cli.main([
        "project", "--input", xin, "--output", yout,
        "--kind", "gaussian", "--n-components", "8", "--backend", "numpy",
    ])
    from randomprojection_tpu.utils.validation import restore_void_dtype

    Y = restore_void_dtype(np.load(yout))
    assert Y.shape == (60, 8) and Y.dtype == bf16


def test_bf16_spec_output_dtype_independent_of_input_sparsity():
    """A bf16-fitted estimator returns bf16 for dense AND sparse input
    (dense outputs; CSR outputs stay f32 — scipy cannot hold ml_dtypes)."""
    import ml_dtypes
    import scipy.sparse as sp

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X32 = np.random.default_rng(0).normal(size=(40, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="numpy").fit(
        X32.astype(bf16)
    )
    assert np.asarray(est.transform(X32)).dtype == bf16
    assert np.asarray(est.transform(sp.csr_array(X32))).dtype == bf16


def test_cli_bench_forwards_custom_shapes(monkeypatch, capsys):
    from randomprojection_tpu import benchmark, cli

    captured = {}

    def fake_run(preset, k=256, d=4096, density=1 / 3,
                 transform_dma=None, dispatch_steps=None):
        captured.update(preset=preset, k=k, d=d, density=density,
                        transform_dma=transform_dma,
                        dispatch_steps=dispatch_steps)
        return {"metric": "fake", "value": 1}

    monkeypatch.setattr(benchmark, "run", fake_run)
    cli.main(["bench", "--preset", "smoke", "--d", "512", "--k", "32",
              "--density", "0.5", "--transform-dma", "off",
              "--dispatch-steps", "4"])
    assert captured == {"preset": "smoke", "k": 32, "d": 512, "density": 0.5,
                        "transform_dma": False, "dispatch_steps": 4}
    # tail-safe output contract: full record line first, compact digest
    # as the FINAL line
    lines = capsys.readouterr().out.splitlines()
    assert json.loads(lines[0])["metric"] == "fake"
    compact = json.loads(lines[-1])
    assert compact[benchmark.COMPACT_MARKER] == benchmark.COMPACT_SCHEMA_VERSION
    assert compact["metric"] == "fake"


def test_cli_project_pipeline_depth(tmp_path):
    """--pipeline-depth varies buffering only: output identical to default."""
    from randomprojection_tpu import cli

    X = np.random.default_rng(0).normal(size=(150, 32)).astype(np.float32)
    xin = str(tmp_path / "x.npy")
    np.save(xin, X)
    outs = []
    for depth, name in (("2", "a.npy"), ("4", "b.npy")):
        yout = str(tmp_path / name)
        cli.main([
            "project", "--input", xin, "--output", yout,
            "--kind", "gaussian", "--n-components", "8",
            "--backend", "jax", "--batch-rows", "50",
            "--pipeline-depth", depth, "--seed", "3",
        ])
        outs.append(np.load(yout))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_cli_argument_validation():
    """Bad --pipeline-depth / --density values are rejected at parse time
    with a clean error, not a deep traceback."""
    from randomprojection_tpu import cli

    for argv in (
        ["project", "--input", "x", "--output", "y", "--pipeline-depth", "0"],
        ["bench", "--density", "0"],
        ["bench", "--density", "1.5"],
        ["bench", "--density", "-0.2"],
    ):
        with pytest.raises(SystemExit):
            cli.build_parser().parse_args(argv)


def test_baseline_numbers_in_sync():
    """BASELINE.md's recorded-numbers block is generated from the latest
    committed BENCH_r*.json (VERDICT r4 weak #1: hand-written prose
    contradicted the driver capture).  Fail if the block and the JSON
    drift — regenerate with `python docs/gen_bench_tables.py`."""
    import pathlib
    import sys as _sys

    repo = pathlib.Path(__file__).resolve().parents[1]
    _sys.path.insert(0, str(repo / "docs"))
    try:
        import gen_bench_tables as g
    finally:
        _sys.path.pop(0)
    current = (repo / "BASELINE.md").read_text()
    lo = current.index(g.BEGIN)
    hi = current.index(g.END) + len(g.END)
    block = current[lo:hi]
    # pin against the source the block itself names — the driver commits
    # BENCH_r{N}.json after the round's last code commit, so the latest
    # file is legitimately newer than the block for one commit at every
    # round boundary (see gen_bench_tables.block_source) — but the lag
    # is bounded to that one round: a block naming an older source than
    # the immediate predecessor IS stale
    import glob as _glob

    src = g.block_source(block)
    files = sorted(_glob.glob(str(repo / "BENCH_r*.json")))
    assert src in files[-2:], (
        f"BASELINE.md bench block was generated from "
        f"{pathlib.Path(src).name}, more than one round behind "
        f"{pathlib.Path(files[-1]).name} — run "
        "`python docs/gen_bench_tables.py`"
    )
    assert block == g.render(src), (
        "BASELINE.md bench block does not match its named BENCH source — "
        "run `python docs/gen_bench_tables.py`"
    )
