"""Double-buffered x DMA + multi-step dispatch fusion (ISSUE 9).

The r14 transform work has two value-preserving execution knobs:

- ``dma``: the fused kernel's x routing — manual double-buffered
  HBM→VMEM DMA (two revolving VMEM slots + semaphores, the default) vs
  the pre-r14 single-buffered automatic pipeline.  Both contract the
  identical mask blocks against the identical x tiles in the identical
  order, so they must be BIT-identical.
- ``fused_project_multistep(steps=K)``: K contiguous row-blocks chained
  through one traced dispatch — must be bit-identical to K separate
  ``fused_sparse_project`` calls on the same row split.

Everything here runs the REAL kernels (DMAs, double buffering, mask
cache, accumulation) under the Pallas interpreter on CPU — the
interpreter substitutes a jnp integer-hash stream for the hardware PRNG
(same distribution and (seed, block) keying, different stream), and
``pallas_sparse_matrix(interpret=True)`` materializes the matching
matrix, so parity against the numpy contraction is exact-shape
meaningful.  On-chip values are covered by ``RP_TEST_TPU=1`` runs of
tests/test_pallas.py.
"""

import numpy as np
import pytest

from randomprojection_tpu.ops import pallas_kernels as pk

OOM_MSG = "Mosaic failed: scoped vmem allocation exceeds the limit"


def _x(n, d, seed=0):
    return np.random.default_rng(seed).normal(size=(n, d)).astype(np.float32)


def _jnp(a):
    import jax.numpy as jnp

    return jnp.asarray(a)


# -- DMA vs single-buffer bit-parity ------------------------------------------


@pytest.mark.parametrize(
    "n,d",
    [
        (70, 700),    # ragged rows AND ragged contraction (d % 512 != 0)
        (64, 512),    # exact one-block shape
        (130, 1100),  # multiple ragged column blocks
        (3, 520),     # fewer rows than any tile
    ],
)
@pytest.mark.parametrize("mxu_mode", ["f32", "split2", "bf16"])
def test_dma_single_parity_ragged(n, d, mxu_mode):
    """DMA and single-buffered routes are bit-identical on every ragged
    (n, d) combination, and both match X @ Rᵀ for the interpreter's
    materialized matrix."""
    x = _x(n, d)
    xj = _jnp(x).astype("bfloat16" if mxu_mode == "bf16" else "float32")
    k = 16
    y_dma = np.asarray(
        pk.fused_sparse_project(
            xj, 11, k, 0.25, mxu_mode=mxu_mode, interpret=True, dma=True
        )
    )
    y_sb = np.asarray(
        pk.fused_sparse_project(
            xj, 11, k, 0.25, mxu_mode=mxu_mode, interpret=True, dma=False
        )
    )
    np.testing.assert_array_equal(y_dma, y_sb)
    assert y_dma.shape == (n, k)
    R = np.asarray(pk.pallas_sparse_matrix(11, k, d, 0.25, interpret=True))
    ref = np.asarray(xj, dtype=np.float32) @ R.T
    tol = dict(rtol=5e-3, atol=0.05) if mxu_mode == "bf16" else dict(
        rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(y_dma, ref, **tol)


def test_dma_default_route_and_explicit_block_n():
    """``dma=None`` resolves to the DMA default (``_DMA_DEFAULT`` is the
    r14 acceptance criterion), and an explicit row tile keeps parity on
    a padded multi-tile grid."""
    assert pk._DMA_DEFAULT is True
    x = _jnp(_x(24, 512))
    y_default = np.asarray(
        pk.fused_sparse_project(x, 3, 8, 0.5, block_n=16, interpret=True)
    )
    y_pinned = np.asarray(
        pk.fused_sparse_project(
            x, 3, 8, 0.5, block_n=16, interpret=True, dma=False
        )
    )
    np.testing.assert_array_equal(y_default, y_pinned)


def test_dma_cache_off_parity():
    """The four (dma × cache) rungs of the degraded ladder all produce
    the identical output — neither knob may change values."""
    x = _jnp(_x(96, 1030, seed=4))
    outs = [
        np.asarray(
            pk.fused_sparse_project(
                x, 9, 24, 1 / 3, block_n=32, interpret=True,
                dma=dma, no_cache=nc,
            )
        )
        for dma in (True, False)
        for nc in (False, True)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


def test_dma_block_offset_shards_same_matrix():
    """Under feature-axis TP each shard regenerates its own column range
    via ``block_offset`` — the DMA route must honor it identically."""
    x = _x(40, 1024, seed=7)
    xj = _jnp(x)
    k = 16
    full = np.asarray(
        pk.fused_sparse_project(xj, 5, k, 0.5, interpret=True, dma=True)
    )
    lo = np.asarray(
        pk.fused_sparse_project(
            _jnp(x[:, :512]), 5, k, 0.5, interpret=True, dma=True
        )
    )
    hi = np.asarray(
        pk.fused_sparse_project(
            _jnp(x[:, 512:]), 5, k, 0.5, block_offset=1, interpret=True,
            dma=True,
        )
    )
    # psum over shards == unsharded contraction (identical streams/order)
    np.testing.assert_allclose(lo + hi, full, rtol=1e-5, atol=1e-5)


# -- multi-step dispatch fusion -----------------------------------------------


@pytest.mark.parametrize("steps", [2, 3, 7])
def test_multistep_bit_identical_to_separate_dispatches(steps):
    """The dispatch-fusion contract: ``steps`` row-blocks through one
    trace == ``steps`` separate dispatches on the same contiguous
    ceil(n/steps) row split, bit-identical (ragged final block
    included)."""
    n = 70
    x = _jnp(_x(n, 700, seed=1))
    y = np.asarray(
        pk.fused_project_multistep(
            x, 13, 16, 0.25, steps=steps, interpret=True
        )
    )
    per = -(-n // steps)
    parts = [
        np.asarray(
            pk.fused_sparse_project(
                x[lo:min(lo + per, n)], 13, 16, 0.25, interpret=True
            )
        )
        for lo in range(0, n, per)
    ]
    np.testing.assert_array_equal(y, np.concatenate(parts, axis=0))


def test_multistep_clamps_and_degenerates():
    """steps > n clamps to the row count; steps=1 is exactly the plain
    dispatch; donate=True changes ownership, never values."""
    x = _jnp(_x(5, 600, seed=2))
    plain = np.asarray(
        pk.fused_sparse_project(x, 1, 8, 0.5, interpret=True)
    )
    one = np.asarray(
        pk.fused_project_multistep(x, 1, 8, 0.5, steps=1, interpret=True)
    )
    np.testing.assert_array_equal(plain, one)
    clamped = np.asarray(
        pk.fused_project_multistep(x, 1, 8, 0.5, steps=99, interpret=True)
    )
    per_row = [
        np.asarray(pk.fused_sparse_project(x[i:i + 1], 1, 8, 0.5,
                                           interpret=True))
        for i in range(5)
    ]
    np.testing.assert_array_equal(clamped, np.concatenate(per_row, axis=0))
    donated = np.asarray(
        pk.fused_project_multistep(
            _jnp(_x(5, 600, seed=2)), 1, 8, 0.5, steps=2, interpret=True,
            donate=True,
        )
    )
    np.testing.assert_array_equal(
        donated,
        np.asarray(pk.fused_project_multistep(
            _jnp(_x(5, 600, seed=2)), 1, 8, 0.5, steps=2, interpret=True,
        )),
    )
    # steps==1 + donate stays on the donating chain (the invalidation
    # contract holds on the degenerate path), values still identical
    donated1 = np.asarray(
        pk.fused_project_multistep(
            _jnp(_x(5, 600, seed=2)), 1, 8, 0.5, steps=1, interpret=True,
            donate=True,
        )
    )
    np.testing.assert_array_equal(donated1, plain)


# -- VMEM-OOM degraded-retry ladder (fake OOM, r6 convention) -----------------


def _fake_oom_on(monkeypatch, trip):
    """Patch the jitted fused impl: rungs matching ``trip(dma, no_cache)``
    raise a classified scoped-VMEM OOM, the rest run the real kernel."""
    real = pk._fused_impl
    calls = []

    def impl(*a, **kw):
        calls.append((kw["dma"], kw["no_cache"]))
        if trip(kw["dma"], kw["no_cache"]):
            raise RuntimeError(OOM_MSG)
        return real(*a, **kw)

    monkeypatch.setattr(pk, "_fused_impl", impl)
    return calls


def test_vmem_oom_dma_falls_back_single_buffered(monkeypatch):
    """A scoped-VMEM OOM on the DMA rung lands on the single-buffered
    tiling (same values), records ``kernel.dma.fallback``, and memoizes
    the key so later dispatches skip the failing route."""
    from randomprojection_tpu.utils import telemetry

    x = _jnp(_x(40, 600, seed=3))
    ref = np.asarray(
        pk.fused_sparse_project(x, 2, 8, 0.5, interpret=True, dma=False)
    )
    key = ((40, 600), None, 8, "f32")
    calls = _fake_oom_on(monkeypatch, lambda dma, nc: dma)
    before = telemetry.registry().snapshot()["counters"].get(
        "kernel.dma.fallbacks", 0
    )
    try:
        got = np.asarray(
            pk.fused_sparse_project(x, 2, 8, 0.5, interpret=True, dma=True)
        )
        np.testing.assert_array_equal(ref, got)
        assert calls == [(True, False), (False, False)]
        assert key in pk._NO_DMA_KEYS
        assert key not in pk._NO_CACHE_KEYS  # cache rung never reached
        after = telemetry.registry().snapshot()["counters"].get(
            "kernel.dma.fallbacks", 0
        )
        assert after == before + 1
        # memoized: the DMA rung is not attempted again for this key
        got2 = np.asarray(
            pk.fused_sparse_project(x, 2, 8, 0.5, interpret=True, dma=True)
        )
        np.testing.assert_array_equal(ref, got2)
        assert calls[2:] == [(False, False)]
    finally:
        pk._NO_DMA_KEYS.discard(key)


def test_vmem_oom_walks_full_ladder_to_no_cache(monkeypatch):
    """When the single-buffered retry ALSO blows VMEM the ladder ends on
    (single-buffered, no-cache) — the regenerate-every-step floor — and
    memoizes both degradations."""
    x = _jnp(_x(48, 520, seed=6))
    ref = np.asarray(
        pk.fused_sparse_project(
            x, 4, 8, 0.5, interpret=True, dma=False, no_cache=True
        )
    )
    key = ((48, 520), None, 8, "f32")
    calls = _fake_oom_on(
        monkeypatch, lambda dma, nc: dma or not nc
    )
    try:
        got = np.asarray(
            pk.fused_sparse_project(x, 4, 8, 0.5, interpret=True, dma=True)
        )
        np.testing.assert_array_equal(ref, got)
        assert calls == [(True, False), (False, False), (False, True)]
        assert key in pk._NO_DMA_KEYS
        assert key in pk._NO_CACHE_KEYS
    finally:
        pk._NO_DMA_KEYS.discard(key)
        pk._NO_CACHE_KEYS.discard(key)


def test_non_vmem_errors_are_not_swallowed(monkeypatch):
    """Only classified VMEM OOMs take the ladder: any other failure
    surfaces unmemoized."""
    x = _jnp(_x(16, 512, seed=8))
    key = ((16, 512), None, 8, "f32")

    def boom(*a, **kw):
        raise RuntimeError("boom")

    monkeypatch.setattr(pk, "_fused_impl", boom)
    with pytest.raises(RuntimeError, match="boom"):
        pk.fused_sparse_project(x, 0, 8, 0.5, interpret=True, dma=True)
    assert key not in pk._NO_DMA_KEYS
    assert key not in pk._NO_CACHE_KEYS


def test_multistep_vmem_oom_ladder(monkeypatch):
    """``fused_project_multistep`` walks the same ladder (its key carries
    the chain length so a failing chained shape never poisons the plain
    dispatch's key)."""
    x = _jnp(_x(30, 600, seed=9))
    ref = np.asarray(
        pk.fused_project_multistep(
            x, 5, 8, 0.5, steps=3, interpret=True, dma=False
        )
    )
    key = ((30, 600), None, 8, "f32", 3)
    real = pk._multistep_impl
    calls = []

    def impl(*a, **kw):
        calls.append((kw["dma"], kw["no_cache"]))
        if kw["dma"]:
            raise RuntimeError(OOM_MSG)
        return real(*a, **kw)

    monkeypatch.setattr(pk, "_multistep_impl", impl)
    try:
        got = np.asarray(
            pk.fused_project_multistep(
                x, 5, 8, 0.5, steps=3, interpret=True, dma=True
            )
        )
        np.testing.assert_array_equal(ref, got)
        assert calls == [(True, False), (False, False)]
        assert key in pk._NO_DMA_KEYS
        assert ((30, 600), None, 8, "f32") not in pk._NO_DMA_KEYS
    finally:
        pk._NO_DMA_KEYS.discard(key)


# -- VMEM budget math ---------------------------------------------------------


def test_reserved_bytes_budgets_dma_value_plane():
    """The DMA route reserves one extra x-tile value plane (the dynamic
    slot gather Mosaic materializes) on top of the two-slot footprint the
    automatic pipeline also pays."""
    for mode in ("f32", "split2", "bf16"):
        itemsize = 2 if mode == "bf16" else 4
        for bn in (256, 512, 1024):
            base = pk._reserved_bytes(bn, 256, mode, itemsize, dma=False)
            with_dma = pk._reserved_bytes(bn, 256, mode, itemsize, dma=True)
            assert with_dma == base + bn * pk.BLOCK_D * itemsize


def test_auto_block_n_never_grows_under_dma():
    """Re-budgeting for the second slot can only shrink (or keep) the
    auto row tile — a DMA tile must never be sized past the budget the
    single-buffered kernel proved."""
    for n, d, k, mode in [
        (131072, 4096, 256, "split2"),
        (16384, 16384, 512, "split2"),
        (8192, 4096, 256, "f32"),
        (1024, 1024, 64, "bf16"),
    ]:
        bn_dma = pk._auto_block_n(n, d, k, mode, dma=True)
        bn_sb = pk._auto_block_n(n, d, k, mode, dma=False)
        assert bn_dma <= bn_sb
        itemsize = 2 if mode == "bf16" else 4
        assert (
            pk._reserved_bytes(bn_dma, k, mode, itemsize, dma=True)
            <= pk._VMEM_LIMIT
        )


# -- backend knobs + telemetry ------------------------------------------------


def test_backend_option_validation():
    from randomprojection_tpu.backends.jax_backend import JaxBackend

    with pytest.raises(ValueError, match="dispatch_steps"):
        JaxBackend(dispatch_steps=0)
    with pytest.raises(ValueError, match="transform_dma"):
        JaxBackend(transform_dma="yes")
    b = JaxBackend(dispatch_steps=4, transform_dma=False)
    assert b.dispatch_steps == 4 and b.transform_dma is False
    assert JaxBackend().dispatch_steps == 1
    assert JaxBackend().transform_dma is None


def test_kernel_dispatch_telemetry_and_doctor(tmp_path):
    """Every host dispatch records its route; the doctor's transform
    section aggregates routes/rows and the dispatch-fusion chain."""
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.trace_report import build_report

    p = str(tmp_path / "dma.jsonl")
    telemetry.configure(p)
    try:
        x = _jnp(_x(20, 600, seed=10))
        pk.fused_sparse_project(x, 0, 8, 0.5, interpret=True)  # default=dma
        pk.fused_sparse_project(x, 0, 8, 0.5, interpret=True, dma=False)
        pk.fused_project_multistep(x, 0, 8, 0.5, steps=2, interpret=True)
    finally:
        telemetry.shutdown()
    report = build_report(p)
    xf = report["transform"]
    # plain dma + the multistep chain (its dispatch event carries steps=2)
    assert xf["kernel_dispatches"] == {"dma": 2, "single": 1}
    assert xf["kernel_rows"] == {"dma": 40, "single": 20}
    assert report["degraded"][
        "kernel.dma.fallback"
    ] == 0  # explicit zero: nothing degraded
    from randomprojection_tpu.utils.telemetry import read_events

    steps = [
        e["steps"] for e in read_events(p)
        if e["event"] == "kernel.dma.dispatch"
    ]
    assert sorted(steps) == [1, 1, 2]


def test_multistep_chain_length_reflects_launches(tmp_path):
    """Telemetry records the launches actually chained, not the knob:
    the clamp + ceil-split can round the chunk count below the request
    (n=10, steps=7 → per=2 → 5 launches)."""
    from randomprojection_tpu.ops.pallas_kernels import (
        multistep_chain_length,
    )
    from randomprojection_tpu.utils import telemetry
    from randomprojection_tpu.utils.telemetry import read_events

    assert multistep_chain_length(10, 7) == 5
    assert multistep_chain_length(70, 3) == 3
    assert multistep_chain_length(4, 8) == 4  # clamped to the row count
    assert multistep_chain_length(1, 5) == 1

    p = str(tmp_path / "chain.jsonl")
    telemetry.configure(p)
    try:
        x = _jnp(_x(10, 520, seed=12))
        pk.fused_project_multistep(x, 0, 8, 0.5, steps=7, interpret=True)
    finally:
        telemetry.shutdown()
    steps = [
        e["steps"] for e in read_events(p)
        if e["event"] == "kernel.dma.dispatch"
    ]
    assert steps == [5]


def test_backend_dispatch_fused_event_registered():
    """The three r14 events are registry members (rp02_dma_bad.py pins
    the negative: a rogue ``kernel.dma.*`` literal fails the lint)."""
    from randomprojection_tpu.utils.telemetry import EVENTS, registered_event

    for name in (
        EVENTS.KERNEL_DMA_DISPATCH,
        EVENTS.KERNEL_DMA_FALLBACK,
        EVENTS.BACKEND_DISPATCH_FUSED,
    ):
        assert registered_event(name)
    assert not registered_event("kernel.dma.bogus")
