"""L0 tests: JL auto-dim + validation (SURVEY.md §5 category 1/4).

Contract anchors: sklearn test_random_projection.py:81-110 (invalid domain),
:347-371 (auto-dim values), :451-456 (32-bit regression).
"""

import numpy as np
import pytest

from randomprojection_tpu import johnson_lindenstrauss_min_dim
from randomprojection_tpu.utils import check_density, check_input_size


def test_invalid_jl_domain():
    for n, eps in [(100, 1.1), (100, 0.0), (100, -0.1), (0, 0.5), (-10, 0.5)]:
        with pytest.raises(ValueError):
            johnson_lindenstrauss_min_dim(n, eps=eps)
    # array-valued invalids raise too
    with pytest.raises(ValueError):
        johnson_lindenstrauss_min_dim(np.array([10, 0]), eps=0.5)
    with pytest.raises(ValueError):
        johnson_lindenstrauss_min_dim(100, eps=np.array([0.5, 1.0]))


def test_jl_matches_sklearn():
    from sklearn.random_projection import (
        johnson_lindenstrauss_min_dim as sk_jl,
    )

    for n in (10, 100, 10_000, 1_000_000):
        for eps in (0.05, 0.1, 0.2, 0.5, 0.999):
            assert johnson_lindenstrauss_min_dim(n, eps=eps) == sk_jl(n, eps=eps)


def test_jl_known_values():
    # sklearn test_random_projection.py:347-371: (n=10, eps=0.5) -> 110
    assert johnson_lindenstrauss_min_dim(10, eps=0.5) == 110
    # 64-bit regression (test_random_projection.py:451-456)
    assert johnson_lindenstrauss_min_dim(100, eps=1e-5) == 368416070986


def test_jl_array_inputs():
    out = johnson_lindenstrauss_min_dim(np.array([10, 10]), eps=0.5)
    np.testing.assert_array_equal(out, [110, 110])
    out = johnson_lindenstrauss_min_dim(10, eps=np.array([0.5, 0.5]))
    np.testing.assert_array_equal(out, [110, 110])
    assert isinstance(johnson_lindenstrauss_min_dim(10, eps=0.5), int)


def test_check_density():
    assert check_density("auto", 1000) == pytest.approx(1 / np.sqrt(1000))
    assert check_density(1 / 3, 100) == pytest.approx(1 / 3)
    assert check_density(1.0, 100) == 1.0
    for bad in (0.0, -0.5, 1.1):
        with pytest.raises(ValueError):
            check_density(bad, 100)


def test_check_input_size():
    check_input_size(5, 10)
    for k, d in [(0, 10), (-1, 10), (5, 0), (5, -3)]:
        with pytest.raises(ValueError):
            check_input_size(k, d)
