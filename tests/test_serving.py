"""Sharded serving tier (ISSUE 8): ShardedSimHashIndex + ShardedTopKServer.

The acceptance contract: sharded ``query_topk`` is bit-identical to
``topk_bruteforce`` on the concatenated corpus — (distance,
lower-global-id) order — for any shard count, including tombstones that
span shard boundaries and a global id range past int32; snapshots
restore under different layouts with bit-identical results (the durable
round-trips live in tests/test_durable.py).  Most tests pin
``topk_impl='scan'`` to keep the suite's compile bill down; the fused
leg is covered once here and continuously by ``make shard-smoke``.
"""

import os

import numpy as np
import pytest

from randomprojection_tpu.models import sketch as sk
from randomprojection_tpu.serving import (
    ShardedSimHashIndex,
    ShardedTopKServer,
    shard_devices,
)
from randomprojection_tpu.utils import telemetry

NB = 4  # packed bytes per code (32 bits) — tiny, compile-friendly


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    codes = rng.integers(0, 256, size=(600, NB), dtype=np.uint8)
    queries = rng.integers(0, 256, size=(16, NB), dtype=np.uint8)
    return codes, queries


def _masked_ref(A, B, dead_ids, m):
    """Brute-force reference with tombstoned columns losing every
    comparison — the same contract the device paths implement."""
    D = sk.pairwise_hamming(A, B).astype(np.int64)
    if len(dead_ids):
        D[:, dead_ids] = B.shape[1] * 8 + 1
    return sk._host_topk_select(D, m)


# ---------------------------------------------------------------------------
# device resolution
# ---------------------------------------------------------------------------


def test_shard_devices_resolution():
    import jax

    local = jax.devices()
    # default: one shard per local device
    assert shard_devices() == local
    # n_shards round-robins when it exceeds the device count
    devs = shard_devices(n_shards=len(local) + 3)
    assert devs[: len(local)] == local
    assert devs[len(local)] == local[0]
    # explicit devices win
    assert shard_devices(devices=local[:2]) == local[:2]
    assert shard_devices(devices=local[:2], n_shards=5) == [
        local[0], local[1], local[0], local[1], local[0]
    ]
    with pytest.raises(ValueError, match="at least one"):
        shard_devices(devices=[])
    with pytest.raises(ValueError, match="n_shards"):
        shard_devices(n_shards=0)


def test_shard_devices_from_mesh():
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    devs = shard_devices(mesh=mesh)
    assert devs == list(jax.devices()[:8])
    # a 2-D mesh: one shard per data-axis index, first device of each slice
    mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                 ("data", "feature"))
    devs2 = shard_devices(mesh=mesh2)
    assert len(devs2) == 4
    assert devs2 == [jax.devices()[i] for i in (0, 2, 4, 6)]
    with pytest.raises(ValueError, match="no 'rows' axis"):
        shard_devices(mesh=mesh, data_axis="rows")
    # mesh fixes the layout by itself: an explicit n_shards or devices
    # alongside it must refuse, not be silently dropped
    with pytest.raises(ValueError, match="cannot be combined"):
        shard_devices(mesh=mesh, n_shards=4)
    with pytest.raises(ValueError, match="cannot be combined"):
        shard_devices(mesh=mesh, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="cannot be combined"):
        ShardedSimHashIndex(
            np.zeros((16, 4), np.uint8), mesh=mesh, n_shards=4
        )


# ---------------------------------------------------------------------------
# parity with brute force
# ---------------------------------------------------------------------------


def test_sharded_fused_parity(corpus):
    """The fused kernel serves PER SHARD (each shard is single-device,
    so the r12 kernel applies where a shard_map-spanning program could
    not) and the merged result is bit-identical to brute force."""
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=3)
    for shard in idx._shards:
        assert shard._chunk_impl(
            queries.shape[0], shard._chunks[0].b.shape[0],
            min(5, shard.n_codes),
        ) == "fused"
    d, i = idx.query_topk(queries, 5)
    rd, ri = sk.topk_bruteforce(queries, codes, 5)
    assert i.dtype == np.int64
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


@pytest.mark.parametrize("n_shards", [1, 2, 5])
def test_sharded_scan_parity_across_layouts(corpus, n_shards):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=n_shards, topk_impl="scan")
    d, i = idx.query_topk(queries, 7)
    rd, ri = sk.topk_bruteforce(queries, codes, 7)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


def test_sharded_tie_heavy_corpus():
    """Few distinct codes → massed ties: the (distance, lower-global-id)
    order must hold exactly across shard boundaries."""
    rng = np.random.default_rng(3)
    base = rng.integers(0, 256, size=(4, NB), dtype=np.uint8)
    codes = base[rng.integers(0, 4, size=300)]
    queries = base[rng.integers(0, 4, size=8)]
    idx = ShardedSimHashIndex(codes, n_shards=4, topk_impl="scan")
    d, i = idx.query_topk(queries, 9)
    rd, ri = sk.topk_bruteforce(queries, codes, 9)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


def test_add_keeps_insertion_order_and_balance(corpus):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes[:100], n_shards=4, topk_impl="scan")
    idx.add(codes[100:350])
    idx.add(codes[350:])
    assert idx.n_codes == 600
    sizes = idx.stats()["shard_rows"]
    assert max(sizes) - min(sizes) <= 1, sizes
    d, i = idx.query_topk(queries, 6)
    rd, ri = sk.topk_bruteforce(queries, codes, 6)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


def test_empty_shards_are_skipped(corpus):
    _, queries = corpus
    rng = np.random.default_rng(5)
    tiny = rng.integers(0, 256, size=(5, NB), dtype=np.uint8)
    idx = ShardedSimHashIndex(tiny, n_shards=8, topk_impl="scan")
    assert sorted(idx.stats()["shard_rows"], reverse=True)[:5] == [1] * 5
    d, i = idx.query_topk(queries, 3)
    rd, ri = sk.topk_bruteforce(queries, tiny, 3)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# ---------------------------------------------------------------------------
# tombstones across shard boundaries
# ---------------------------------------------------------------------------


def test_tombstones_span_shard_boundaries(corpus):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=4, topk_impl="scan")
    # 4 shards of 150 rows: [120, 330) crosses two shard boundaries
    dead = np.arange(120, 330)
    assert idx.delete(dead) == 210
    assert idx.delete(dead) == 0  # idempotent
    assert idx.n_deleted == 210 and idx.n_live == 390
    d, i = idx.query_topk(queries, 8)
    rd, ri = _masked_ref(queries, codes, dead, 8)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


def test_delete_validation(corpus):
    codes, _ = corpus
    idx = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    with pytest.raises(ValueError, match="integers"):
        idx.delete(np.array([1.5]))
    with pytest.raises(ValueError, match=r"\[0, 600\)"):
        idx.delete([600])
    assert idx.delete([]) == 0


def test_m_clamps_to_live_and_error_paths(corpus):
    codes, queries = corpus
    small = codes[:40]
    idx = ShardedSimHashIndex(small, n_shards=3, topk_impl="scan")
    idx.delete(np.arange(30, 40))
    d, i = idx.query_topk(queries, 64)  # m > n_live clamps
    assert d.shape == (16, 30) and i.shape == (16, 30)
    rd, ri = _masked_ref(queries, small, np.arange(30, 40), 30)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))
    idx.delete(np.arange(30))
    with pytest.raises(ValueError, match="all deleted"):
        idx.query_topk(queries, 3)
    empty = ShardedSimHashIndex(
        np.empty((0, NB), np.uint8), n_shards=2, topk_impl="scan"
    )
    with pytest.raises(ValueError, match="empty index"):
        empty.query_topk(queries, 3)
    with pytest.raises(ValueError, match="positive int"):
        idx.query_topk(queries, 0)
    with pytest.raises(ValueError, match="queries must be"):
        idx.query_topk(np.zeros((2, NB + 1), np.uint8), 3)


# ---------------------------------------------------------------------------
# global-int64 id space
# ---------------------------------------------------------------------------


def test_id_offset_past_int32(corpus):
    """The int64 global id space, proven without a 2-billion-row
    fixture: with id_offset past 2^31 every returned id exceeds int32
    and the merge order still matches brute force exactly."""
    codes, queries = corpus
    off = 2**31 + 19
    idx = ShardedSimHashIndex(
        codes, n_shards=4, topk_impl="scan", id_offset=off
    )
    d, i = idx.query_topk(queries, 7)
    rd, ri = sk.topk_bruteforce(queries, codes, 7)
    assert np.array_equal(d, rd)
    assert np.array_equal(i, ri.astype(np.int64) + off)
    assert int(i.min()) > 2**31
    # delete speaks offset ids too — and validates in offset space
    assert idx.delete(np.array([off, off + 1])) == 2
    with pytest.raises(ValueError, match=str(off + 600)):
        idx.delete([off + 600])
    d2, i2 = idx.query_topk(queries, 7)
    rd2, ri2 = _masked_ref(queries, codes, np.array([0, 1]), 7)
    assert np.array_equal(d2, rd2)
    assert np.array_equal(i2, ri2.astype(np.int64) + off)


def test_per_shard_capacity_error_names_shard():
    """The 2^31-1 refusal is now a per-shard invariant with a pointed
    error naming the shard and the int64 growth path."""
    codes = np.random.default_rng(0).integers(
        0, 256, size=(16, NB), dtype=np.uint8
    )
    shard = sk.SimHashIndex(codes, label="shard 3/8 on FakeDevice(3)")
    shard.n_codes = 2**31 - 10  # simulate a near-capacity shard
    with pytest.raises(ValueError) as ei:
        shard.add(codes)
    msg = str(ei.value)
    assert "shard 3/8 on FakeDevice(3)" in msg
    assert "ShardedSimHashIndex" in msg and "int64" in msg


# ---------------------------------------------------------------------------
# dense analysis surface + compaction
# ---------------------------------------------------------------------------


def test_dense_query_global_column_order(corpus):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=3, topk_impl="scan")
    idx.add(codes[:50])  # second segment per shard
    full = np.concatenate([codes, codes[:50]])
    assert np.array_equal(idx.query(queries), sk.pairwise_hamming(
        queries, full
    ))
    est = idx.query_cosine(queries)
    assert est.shape == (16, 650)


def test_compact_folds_and_remaps(corpus):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=3, topk_impl="scan")
    dead = np.arange(100, 260)
    idx.delete(dead)
    mapping = idx.compact()
    live = np.delete(np.arange(600), dead)
    assert np.array_equal(mapping, live)
    assert idx.n_deleted == 0 and idx.n_codes == 440
    assert all(len(s._chunks) <= 1 for s in idx._shards)
    d, i = idx.query_topk(queries, 6)
    rd, ri = sk.topk_bruteforce(queries, codes[live], 6)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# ---------------------------------------------------------------------------
# replica-aware server
# ---------------------------------------------------------------------------


def test_sharded_server_round_robin_bit_identical(corpus):
    codes, queries = corpus
    r1 = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    r2 = ShardedSimHashIndex(codes, n_shards=3, topk_impl="scan")
    rd, ri = sk.topk_bruteforce(queries, codes, 5)
    with ShardedTopKServer([r1, r2], 5, max_delay_s=0.0) as srv:
        assert srv.n_replicas == 2
        # max_delay_s=0 -> one dispatch per request -> strict round-robin
        for k in range(4):
            d, i = srv.query(queries[k * 4 : (k + 1) * 4])
            assert np.array_equal(d, rd[k * 4 : (k + 1) * 4])
            assert np.array_equal(i, ri.astype(np.int64)[k * 4 : (k + 1) * 4])
        stats = srv.stats()
    assert stats["replicas"] == 2
    assert stats["replica_batches"] == [2, 2]
    assert stats["requests"] == 4


def test_sharded_server_validates_replicas(corpus):
    codes, _ = corpus
    r1 = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    r2 = ShardedSimHashIndex(codes[:500], n_shards=2, topk_impl="scan")
    with pytest.raises(ValueError, match="replica 1 disagrees"):
        ShardedTopKServer([r1, r2], 5, start=False)
    # same n_bytes but a different ragged bit width changes distances,
    # so it must refuse too — results would be routing-dependent
    r3 = ShardedSimHashIndex(
        codes, n_shards=2, n_bits=codes.shape[1] * 8 - 3, topk_impl="scan"
    )
    with pytest.raises(ValueError, match="n_bits"):
        ShardedTopKServer([r1, r3], 5, start=False)
    with pytest.raises(ValueError, match="at least one replica"):
        ShardedTopKServer([], 5, start=False)


def test_plain_topk_server_accepts_sharded_index(corpus):
    """The base micro-batcher needs only the query_topk surface, so a
    sharded index drops in without the replica layer."""
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    with sk.TopKServer(idx, 4, max_delay_s=0.0) as srv:
        d, i = srv.query(queries)
    rd, ri = sk.topk_bruteforce(queries, codes, 4)
    assert np.array_equal(d, rd) and np.array_equal(i, ri.astype(np.int64))


# ---------------------------------------------------------------------------
# telemetry: shard events feed the doctor's serving section
# ---------------------------------------------------------------------------


def test_shard_events_and_doctor_serving_section(tmp_path, corpus):
    from randomprojection_tpu.utils.trace_report import (
        build_report,
        render_report,
    )

    codes, queries = corpus
    tel = str(tmp_path / "events.jsonl")
    idx = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    telemetry.configure(tel)
    try:
        with ShardedTopKServer(idx, 5, max_delay_s=0.0) as srv:
            srv.query(queries)
        idx.query_topk(queries, 5)
    finally:
        telemetry.shutdown()
    names = [e["event"] for e in telemetry.read_events(tel)]
    assert "shard.topk_tile" in names
    assert "shard.merge" in names
    assert "serve.shard.batch" in names
    report = build_report(tel)
    sv = report["serving"]
    assert sv["shard_tiles"] >= 2
    assert sv["shard_dispatches"] == 2 * sv["shard_tiles"]
    assert sv["shard_merges"] == sv["shard_tiles"]
    assert sv["shard_batches"] == 1
    assert sv["shard_replicas_used"] == [0]
    assert report["unregistered_events"] == {}
    rendered = render_report(report)
    assert "sharded tier:" in rendered and "replica routing:" in rendered
    # counters on the default registry
    assert telemetry.registry().counter("serve.shard.batches") >= 1
    assert telemetry.registry().counter("shard.dispatches") >= 2


def test_sharded_index_stats(corpus):
    codes, queries = corpus
    idx = ShardedSimHashIndex(codes, n_shards=2, topk_impl="scan")
    idx.query_topk(queries, 3)
    s = idx.stats()
    assert s["shards"] == 2 and s["merges"] >= 1
    assert s["merge_wall_s"] >= 0.0
    assert sum(s["shard_rows"]) == 600


# ---------------------------------------------------------------------------
# construction validation
# ---------------------------------------------------------------------------


def test_construction_validation():
    with pytest.raises(ValueError, match="codes must be"):
        ShardedSimHashIndex(np.zeros((2, 2, 2), np.uint8), n_shards=2)
    with pytest.raises(ValueError, match="id_offset"):
        ShardedSimHashIndex(
            np.zeros((2, NB), np.uint8), n_shards=2, id_offset=-1
        )
    with pytest.raises(ValueError, match="n_bits"):
        ShardedSimHashIndex(
            np.zeros((2, NB), np.uint8), n_shards=2, n_bits=NB * 8 + 1
        )
    with pytest.raises(ValueError, match="device= pins"):
        import jax

        sk.SimHashIndex(
            np.zeros((2, NB), np.uint8), device=jax.devices()[0],
            mesh=object(),
        )
