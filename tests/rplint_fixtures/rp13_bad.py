"""RP13 fixture: torn-artifact writes, unfsynced replaces, and a
manifest committed before its chunks.

Expected active findings (lint under relpath ``durable.py``):
- raw open(final_path, "w") in-place write
- os.replace reachable without flush/fsync on the staged tmp
- manifest commit not dominated by the chunk writes
- os.replace with no directory fsync reachable after it
plus one pragma-suppressed raw-write twin; the conformant twins must
stay silent.
"""
import json
import os


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def raw_final_write(path, rec):
    with open(path, "w") as f:  # VIOLATION: in-place final write
        json.dump(rec, f)


def replace_without_fsync(path, rec):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)  # VIOLATION: tmp bytes never flushed/fsynced
    _fsync_dir(os.path.dirname(path))


def replace_no_dirfsync(path, rec):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # VIOLATION: no directory fsync after


def manifest_before_chunks(entries, index):
    _write_manifest(index)  # VIOLATION: committed before the spills
    for lo, codes in entries:
        _write_npy_atomic(lo, codes)


def ok_commit(path, rec):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # ok: fsynced, directory fsync below
    _fsync_dir(os.path.dirname(path))


def ok_manifest_last(entries, index):
    for lo, codes in entries:
        _write_npy_atomic(lo, codes)
    if index:
        _write_npy_atomic(0, index)
    # ok: dominated by both writes via their loop/if headers (the
    # zero-trip/nothing-to-spill shapes still commit a truthful
    # manifest)
    _write_manifest(index)


def suppressed_raw_write(path, rec):
    # rplint: allow[RP13] — fixture: suppression case
    with open(path, "w") as f:  # suppressed
        json.dump(rec, f)
