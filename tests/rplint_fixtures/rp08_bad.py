"""RP08 fixture: seeded thread/queue-protocol violations (the rule is
flow-sensitive and runs on every module, so no virtual relpath is
needed).

Expected findings: one thread unjoined on the exception path, one
thread never joined at all (the module HAS a ``.join(`` so per-line
RP04 stays quiet about it), one conditionally-skipped shutdown
sentinel, one commit-before-yield — plus one pragma-suppressed twin of
the exception-path case."""
import queue
import threading


def unjoined_on_exception_path(items):
    t = threading.Thread(target=print, daemon=True)
    t.start()  # VIOLATION: the raise below skips the join
    for item in items:
        if item is None:
            raise ValueError("bad item")
    t.join()
    return items


def second_thread_never_joined(work):
    a = threading.Thread(target=print, daemon=True)
    b = threading.Thread(target=print, daemon=True)
    a.start()
    b.start()  # VIOLATION: b is never joined (a's join satisfies RP04)
    try:
        work()
    finally:
        a.join()


def joined_in_finally_ok(work):
    t = threading.Thread(target=print, daemon=True)
    t.start()  # ok: every path (return, raise, fall-through) joins
    try:
        work()
        if not work:
            return None
    finally:
        t.join(timeout=5.0)
    return work


def pool_joined_ok(n, work):
    workers = [
        threading.Thread(target=print, daemon=True) for _ in range(n)
    ]
    for t in workers:
        t.start()  # ok: the finally joins the whole pool
    try:
        work()
    finally:
        for t in workers:
            t.join(timeout=5.0)


class BadServer:
    _SENTINEL = object()

    def __init__(self):
        self._q = queue.Queue(maxsize=8)
        self._pending = 0

    def close(self):  # VIOLATION: sentinel enqueue is conditional
        if self._pending:
            self._q.put(self._SENTINEL)


class GoodServer:
    _SENTINEL = object()

    def __init__(self):
        self._q = queue.Queue(maxsize=8)
        self._closed = threading.Event()

    def close(self):  # ok: only the closed-flag guard may skip the put
        if self._closed.is_set():
            return
        self._closed.set()
        self._q.put(self._SENTINEL)


def commit_before_yield(source, cursor):
    for lo, batch in source:
        cursor.rows_done = lo + len(batch)  # VIOLATION: commit before ack
        yield lo, batch


def ack_after_yield_ok(source, cursor):
    for lo, batch in source:
        yield lo, batch
        cursor.rows_done = lo + len(batch)  # ok: consumer acked the batch


def unjoined_suppressed(items):
    t = threading.Thread(target=print, daemon=True)
    # rplint: allow[RP08] — fixture: suppression case
    t.start()  # suppressed
    for item in items:
        if item is None:
            raise ValueError("bad item")
    t.join()
    return items
