"""RP04 fixture: a module that starts a thread and never joins one."""
import threading

t = threading.Thread(target=print, daemon=True)  # VIOLATION: no .join(
t.start()
