"""RP07 fixture: seeded DMA-discipline violations (linted under the
virtual relpath ``ops/pallas_kernels.py`` so the kernel-module scoping
and the ``_reserved_bytes`` budget cross-check apply).

Expected findings: one unbudgeted VMEM allocation, one never-waited
copy family, two conditional-wait starts (warm-up + in-loop), one
slot re-target (phase +2 on 2 revolving slots), one modulus mismatch
(% 4 vs declared 2-slot scratch) — plus one pragma-suppressed twin of
the never-waited case."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_HEADROOM = 3 << 20


def _reserved_bytes(block_n, k):
    """The module's VMEM budget (the RP07 cross-check target)."""
    return 2 * block_n * 128 * 4 + 2 * block_n * k * 4 + _VMEM_HEADROOM


def _launch(kernel, block_n, k, depth):
    scratch = [
        pltpu.VMEM((2, block_n, 128), jnp.float32),  # budgeted, 2 slots
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.VMEM((depth, k, 128), jnp.float32),  # VIOLATION: unbudgeted
    ]
    return kernel, scratch


def _kernel_unwaited(x_hbm, o_ref, buf, sem, *, n):
    def tile_copy(t):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(t, 8)], buf.at[t % 2], sem.at[t % 2]
        )

    tile_copy(0).start()  # VIOLATION: this family is never waited

    def body(t, _):
        tile_copy(t + 1).start()
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _kernel_conditional_wait(x_hbm, o_ref, buf, sem, *, n):
    def tile_copy(t):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(t, 8)], buf.at[t % 2], sem.at[t % 2]
        )

    tile_copy(0).start()  # VIOLATION: the wait below is conditional

    def body(t, _):
        @pl.when(t + 1 < n)
        def _():
            tile_copy(t + 1).start()  # VIOLATION: wait not on all paths

        @pl.when(t > 0)
        def _():
            tile_copy(t).wait()  # skipped when t == 0

        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _kernel_retarget(x_hbm, o_ref, buf, sem, *, n):
    def tile_copy(t):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(t, 8)], buf.at[t % 2], sem.at[t % 2]
        )

    tile_copy(0).start()

    def body(t, _):
        tile_copy(t + 2).start()  # VIOLATION: +2 phase on 2 slots
        tile_copy(t).wait()
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _kernel_modulus(x_hbm, o_ref, buf, sem, *, n):
    def tile_copy(t):  # VIOLATION: % 4 but the scratch declares 2 slots
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(t, 8)], buf.at[t % 4], sem.at[t % 4]
        )

    tile_copy(0).start()

    def body(t, _):
        tile_copy(t + 1).start()
        tile_copy(t).wait()
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def _kernel_unwaited_suppressed(x_hbm, o_ref, buf, sem, *, n):
    def tile_copy(t):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(t, 8)], buf.at[t % 2], sem.at[t % 2]
        )

    # rplint: allow[RP07] — fixture: suppression case
    tile_copy(0).start()  # suppressed

    def body(t, _):
        tile_copy(t + 1).start()
        return 0

    jax.lax.fori_loop(0, n, body, 0)
