"""RP10 fixture: seeded cross-thread shared-state races (linted under
a concurrency-module relpath, e.g. ``streaming.py``).

Expected findings: an unlocked cross-role read/write pair, a
one-side-only locked pair, a write published *after* ``start()``, and
a lock-consistency violation in a thread-free class — plus one
pragma-suppressed twin.  The ok-twins (same lock on every access path,
queue.Queue handoff, init-only writes that dominate the start) produce
nothing."""
import queue
import threading


class UnlockedTallies:
    def __init__(self):
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for _ in range(10):
            self._count += 1  # VIOLATION: main reads this with no lock

    def snapshot(self):
        return self._count

    def close(self):
        self._thread.join(timeout=5.0)


class OneSideLocked:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._lock:
            self._total += 1  # VIOLATION: read side skips the lock

    def read_side(self):
        return self._total

    def close(self):
        self._thread.join(timeout=5.0)


class LockedOk:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._lock:
            self._n += 1  # ok: every access path holds the same lock

    def read_side(self):
        with self._lock:
            return self._n

    def close(self):
        self._thread.join(timeout=5.0)


class QueueHandoffOk:
    def __init__(self):
        self._results = queue.Queue(maxsize=8)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self._results.put(1)  # ok: the value crosses roles via the queue

    def drain(self):
        return self._results.get()

    def close(self):
        self._thread.join(timeout=5.0)


class InitOnlyOk:
    def __init__(self, cfg):
        self._cfg = dict(cfg)  # ok: the write dominates the start()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        return len(self._cfg)

    def peek(self):
        return self._cfg

    def close(self):
        self._thread.join(timeout=5.0)


class WriteAfterStart:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._late = 1  # VIOLATION: published after start(), not init-only

    def _run(self):
        return self._late

    def close(self):
        self._thread.join(timeout=5.0)


class InconsistentNoThreads:
    """No thread constructed here — the lock-consistency leg."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def reset(self):
        self._n = 0  # VIOLATION: locked in bump(), bare write here


class SuppressedTallies:
    def __init__(self):
        self._hits = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        # rplint: allow[RP10] — fixture: suppression case
        self._hits += 1

    def peek(self):
        return self._hits

    def close(self):
        self._thread.join(timeout=5.0)
