"""RP11 fixture: seeded lock-order and blocking-under-lock violations
(linted under a concurrency-module relpath, e.g. ``streaming.py``).

Expected findings: one direct lock-order cycle, one cycle closed
through a call one level deep, and three blocking calls under a lock
(queue.put / thread.join / future.result) — plus one pragma-suppressed
blocking put.  The ok-twins (acyclic nesting, put_nowait, string and
path joins) produce nothing."""
import os
import queue
import threading


class OrderCycle:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:  # acquires a -> b ...
                return 1

    def ba(self):
        with self._b:
            with self._a:  # VIOLATION: ... and b -> a elsewhere
                return 2


class OrderOk:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:  # ok: every path agrees on a -> b
                return 1

    def two(self):
        with self._a, self._b:
            return 2


class CallLevelCycle:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def _take_y(self):
        with self._y:
            return 1

    def xy(self):
        with self._x:
            return self._take_y()  # x -> y through the call ...

    def yx(self):
        with self._y:
            with self._x:  # VIOLATION: ... and y -> x directly
                return 2


class BlockingUnderLock:
    _SENTINEL = "stop"

    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(maxsize=2)
        self._t = threading.Thread(target=print, daemon=True)

    def enqueue(self):
        with self._lock:
            self._q.put(self._SENTINEL)  # VIOLATION: blocking put

    def halt(self):
        with self._lock:
            self._t.join(timeout=5.0)  # VIOLATION: join under lock

    def wait(self, fut):
        with self._lock:
            return fut.result()  # VIOLATION: future.result under lock

    def ok_paths(self, items):
        with self._lock:
            self._q.put_nowait(1)  # ok: non-blocking
            name = os.path.join("a", "b")  # ok: not a thread join
            return ",".join(str(i) for i in items) + name  # ok: str join

    def suppressed(self):
        with self._lock:
            # rplint: allow[RP11] — fixture: suppression case
            self._q.put(self._SENTINEL)

    def drain(self):
        self._t.join(timeout=5.0)
