"""RP02 fixture: events off the registry (linted against a synthetic
registry knowing only ``good.event`` and the ``fam.`` family)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def emits(x):
    telemetry.emit("rogue.event", x=1)  # VIOLATION: unregistered literal
    telemetry.emit(EVENTS.NOPE, x=1)  # VIOLATION: unknown constant
    telemetry.emit(f"other.{x}", x=1)  # VIOLATION: unregistered family
    telemetry.emit("good.event")  # ok
    telemetry.emit(EVENTS.GOOD)  # ok
    telemetry.emit(f"fam.{x}")  # ok
    name = "dynamic"
    telemetry.emit(name)  # informational: unresolvable-emit (never fatal)
    # rplint: allow[RP02] — fixture: suppression case
    telemetry.emit("rogue.event2", x=1)  # suppressed
