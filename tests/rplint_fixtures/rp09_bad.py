"""RP09 fixture: host syncs hidden one call behind hot loops (linted
under the virtual relpath ``streaming.py`` so the hot-module scoping
applies).

Expected findings: one module-function helper call and one
``self.``-method call, each reaching a host sync from a loop body —
plus one pragma-suppressed twin.  The direct syncs RP03 owns are
deliberately absent, and the same helper called OUTSIDE a loop stays
silent."""
import numpy as np


def _materialize(y):
    return np.asarray(y)  # the hidden host sync


def _shape_of(y):
    return y.shape  # clean helper: no sync


def hot_loop(batches):
    out = []
    for y in batches:
        out.append(_materialize(y))  # VIOLATION: helper-hidden sync
        _shape_of(y)  # ok: callee performs no sync
    return out


def cold_call(y):
    return _materialize(y)  # ok: not inside a loop


class Tier:
    def _fetch(self, y):
        return float(y.sum())  # the hidden host sync

    def drain(self, ys):
        acc = 0.0
        for y in ys:
            acc += self._fetch(y)  # VIOLATION: method-hidden sync
        return acc


def hot_loop_suppressed(batches):
    out = []
    for y in batches:
        # rplint: allow[RP09] — fixture: suppression case
        out.append(_materialize(y))  # suppressed
    return out
