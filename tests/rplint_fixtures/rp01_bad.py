"""RP01 fixture: unbalanced spans and a hand-rolled span event."""
from randomprojection_tpu.utils import telemetry


def do_work():
    pass


def leaky():
    # straight-line end: the span leaks when do_work raises
    s = telemetry.start_span("work")  # VIOLATION
    do_work()
    telemetry.end_span(s)


def discarded():
    telemetry.start_span("work")  # VIOLATION: handle discarded


def handrolled():
    telemetry.emit("span_start", name="fake")  # VIOLATION


def suppressed_leak():
    # rplint: allow[RP01] — fixture: suppression case
    s = telemetry.start_span("work")
    do_work()
    telemetry.end_span(s)


def balanced():
    s = telemetry.start_span("work")
    try:
        do_work()
    finally:
        telemetry.end_span(s)


def escaping_return():
    return telemetry.start_span("work")


def escaping_queue(q):
    s = telemetry.start_span("work")
    q.put((0, s))
