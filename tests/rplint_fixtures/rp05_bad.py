"""RP05 fixture: clocks and hidden-global RNG (linted under the virtual
relpath ``ops/fixture.py`` so the determinism scoping applies)."""
import random
import time

import numpy as np


def kernel(n):
    t = time.time()  # VIOLATION
    a = random.random()  # VIOLATION
    b = np.random.rand(n)  # VIOLATION
    rng = np.random.default_rng(0)  # ok: Generator construction
    c = rng.normal(size=n)
    t2 = time.perf_counter()  # ok
    # rplint: allow[RP05] — fixture: suppression case
    d = np.random.rand(n)  # suppressed
    return t, a, b, c, t2, d
