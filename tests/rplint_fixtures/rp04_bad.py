"""RP04 fixture: implicit daemon flag and unbounded queues (this module
does contain a ``.join(``, so only those two classes fire)."""
import queue
import threading


def spawn():
    t = threading.Thread(target=print)  # VIOLATION: no daemon=
    q = queue.Queue()  # VIOLATION: unbounded
    sq = queue.SimpleQueue()  # VIOLATION: unbounded by construction
    bounded = queue.Queue(maxsize=2)  # ok
    t2 = threading.Thread(target=print, daemon=True)  # ok
    t.start()
    t2.start()
    t.join()
    t2.join()
    # rplint: allow[RP04] — fixture: suppression case
    q2 = queue.Queue()  # suppressed
    return q, bounded, q2, sq
