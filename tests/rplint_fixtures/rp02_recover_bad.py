"""RP02 fixture (ISSUE 6 satellite): a recovery path emitting an event
name that is NOT in ``telemetry.EVENTS`` — the drift the central
registry exists to catch.  Linted against the REAL registry (unlike
``rp02_bad.py``'s synthetic one), so it also proves the shipped
registry does not silently grow a matching name."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def resume_with_unregistered_event(path, rows_done):
    # VIOLATION: a recovery event dodging the registry — invisible to
    # trace_report's recovery section and the degraded audit
    telemetry.emit("recover.rogue_replay", path=path, rows_done=rows_done)
    # ok: the registered resume event
    telemetry.emit(EVENTS.RECOVER_RESUME, path=path, rows_done=rows_done)
