"""RP02 fixture (ISSUE 15 satellite): an LSH candidate-tier path
emitting an ``index.lsh.*`` event name that is NOT in
``telemetry.EVENTS``.  Linted against the REAL registry — the
``index.lsh`` namespace deliberately has NO family prefix, so every
candidate-tier event must be individually registered (a family would
wave rogue names through, and the doctor's candidate-generation
section would silently miss them)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def probe_with_unregistered_event(queries, candidates):
    # VIOLATION: a candidate-tier event dodging the registry —
    # invisible to the doctor's candidate-generation section
    telemetry.emit("index.lsh.rogue_probe", queries=queries, n=candidates)
    # ok: the registered per-tile candidate-generation record
    telemetry.emit(
        EVENTS.INDEX_LSH_DISPATCH, queries=queries, candidates=candidates
    )
