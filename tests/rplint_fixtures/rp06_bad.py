"""RP06 fixture: silently-swallowed broad handlers (linted under the
virtual relpath ``streaming.py`` so the pipeline scoping applies)."""
from randomprojection_tpu.utils import telemetry


def swallow(fn):
    try:
        fn()
    except Exception:  # VIOLATION
        pass


def swallow_suppressed(fn):
    try:
        fn()
    # rplint: allow[RP06] — fixture: suppression case
    except Exception:
        pass


def ok_reraise(fn):
    try:
        fn()
    except Exception:
        raise


def ok_emit(fn):
    try:
        fn()
    except Exception as e:
        telemetry.emit("x.error", error=repr(e))


def ok_narrow(fn):
    try:
        fn()
    except ValueError:  # narrow handlers are the caller's business
        pass
