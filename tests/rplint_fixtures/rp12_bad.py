"""RP12 fixture: leaked acquires and the r17 acquire-ordering shape.

Expected active findings (lint under any relpath):
- subscription leaked on the early-return path
- open() handle leaked on the raise path
- mkdtemp dir leaked on the early-return path
- MetricsServer acquired unprotected while a subscription is live
plus one pragma-suppressed leak twin; the ok twins must stay silent.
"""
import shutil
import tempfile

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.metrics_server import MetricsServer


def work(*args):
    return args


def leak_subscription(fn, flag):
    sub = telemetry.subscribe(fn)  # VIOLATION: early return leaks it
    if flag:
        return None
    sub.close()
    return None


def leak_open(path):
    f = open(path)  # VIOLATION: the raise path leaks the handle
    data = f.read()
    if not data:
        raise ValueError(path)
    f.close()
    return data


def leak_tmpdir(flag):
    d = tempfile.mkdtemp()  # VIOLATION: early return leaks the dir
    if flag:
        return None
    shutil.rmtree(d)
    return None


def ordering_pair(fn, aggregator):
    sub = telemetry.subscribe(fn)
    # VIOLATION below: if MetricsServer raises, sub leaks (r17 shape)
    server = MetricsServer(port=0, aggregator=aggregator)
    try:
        work(server)
    finally:
        server.close()
        sub.close()


def ok_with(path):
    with open(path) as f:  # ok: context-managed
        return f.read()


def ok_escape(fn):
    sub = telemetry.subscribe(fn)
    return sub  # ok: the handle escapes to the caller


def ok_guarded(fn, flag):
    sub = None
    try:
        if flag:
            sub = telemetry.subscribe(fn)  # ok: guarded release below
        work(flag)
    finally:
        if sub is not None:
            sub.close()


def ok_ordering(fn, aggregator):
    sub = telemetry.subscribe(fn)
    try:
        # ok: exception-protected — the handler releases sub
        server = MetricsServer(port=0, aggregator=aggregator)
    except BaseException:
        sub.close()
        raise
    try:
        work(server)
    finally:
        server.close()
        sub.close()


def suppressed_leak(fn, flag):
    # rplint: allow[RP12] — fixture: suppression case
    sub = telemetry.subscribe(fn)  # suppressed
    if flag:
        return None
    sub.close()
    return None
