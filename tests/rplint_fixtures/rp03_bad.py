"""RP03 fixture: per-iteration host syncs (linted under the virtual
relpath ``streaming.py`` so the hot-module scoping applies)."""
import jax
import numpy as np


def hot(handles, y):
    out = []
    for h in handles:
        out.append(np.asarray(h))  # VIOLATION
        y.block_until_ready()  # VIOLATION
        v = float(y.sum())  # VIOLATION
        g = jax.device_get(y)  # VIOLATION
        # rplint: allow[RP03] — fixture: suppression case
        out.append(np.asarray(h))  # suppressed
    ok_outside = np.asarray(handles)  # ok: not inside a loop
    ok_scalar = float(v)  # ok: float() on a plain name
    return out, g, ok_outside, ok_scalar
