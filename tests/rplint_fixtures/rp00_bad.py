"""RP00 fixture: malformed pragmas (each line below is one finding)."""

X = 1  # rplint: allow[RP03]
Y = 2  # rplint: allowing things informally
Z = 3  # rplint: allow[RP99] — no such rule
