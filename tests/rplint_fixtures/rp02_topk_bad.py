"""RP02 fixture (ISSUE 7 satellite): a serving-kernel path emitting a
``topk.kernel.*`` event name that is NOT in ``telemetry.EVENTS``.
Linted against the REAL registry — the topk.kernel namespace
deliberately has NO family prefix, so every kernel event must be
individually registered (a family would wave rogue names through)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def dispatch_with_unregistered_event(queries, m):
    # VIOLATION: a kernel event dodging the registry — invisible to the
    # doctor's serving section and the degraded audit
    telemetry.emit("topk.kernel.rogue_dispatch", queries=queries, m=m)
    # ok: the registered dispatch event
    telemetry.emit(EVENTS.TOPK_KERNEL_DISPATCH, queries=queries, m=m)
