"""RP14 fixture: silent and unmemoized fallback rungs plus a
counter-only fallback.

Expected active findings (lint under relpath ``ann/lsh.py``):
- silent classified rung (no emit, no recorder)
- classified rung that emits but never memoizes the degraded key
- counter_inc("...fallback...") with no adjacent event emit
plus one pragma-suppressed silent-rung twin; the ok twins (including
the ladder shape whose memo sits after the loop) must stay silent.
Every handler re-raises on unclassified errors so RP06 stays quiet —
this fixture isolates the RP14 legs.
"""

_NO_FUSED_KEYS = set()


def silent_rung(plan, key, fallback):
    try:
        return plan(key)
    except Exception as e:  # VIOLATION: doctor-invisible fallback
        if not isinstance(e, MemoryError):
            raise
        return fallback(key)


def unmemoized_rung(plan, key, fallback):
    try:
        return plan(key)
    except Exception as e:  # VIOLATION: no degraded-key memo
        if not isinstance(e, MemoryError):
            raise
        telemetry.emit(EVENTS.INDEX_LSH_FALLBACK, key=key)
        return fallback(key)


def counter_only(registry_, key):
    # VIOLATION below: counter with no adjacent degraded-event emit
    registry_.counter_inc("index.lsh.fallbacks")
    return key


def ok_rung(plan, key, fallback):
    try:
        return plan(key)
    except Exception as e:  # ok: emits and memoizes in the handler
        if not isinstance(e, MemoryError):
            raise
        _NO_FUSED_KEYS.add(key)
        telemetry.emit(EVENTS.INDEX_LSH_FALLBACK, key=key)
        return fallback(key)


def ok_ladder(plans, key, no_fused_keys):
    for idx, plan in enumerate(plans):
        try:
            out = plan(key)
        except Exception as e:  # ok: memo reachable after the ladder
            if idx == len(plans) - 1 or not isinstance(e, MemoryError):
                raise
            telemetry.emit(EVENTS.INDEX_LSH_FALLBACK, key=key, rung=idx)
            continue
        if idx:
            no_fused_keys.add(key)
        return out
    raise RuntimeError("unreachable")


def ok_counter(key):
    counter_inc("index.lsh.fallbacks")  # ok: emit is adjacent
    telemetry.emit(EVENTS.INDEX_LSH_FALLBACK, key=key)
    return key


def suppressed_rung(plan, key, fallback):
    try:
        return plan(key)
    # rplint: allow[RP14] — fixture: suppression case
    except Exception as e:  # suppressed
        if not isinstance(e, MemoryError):
            raise
        return fallback(key)
