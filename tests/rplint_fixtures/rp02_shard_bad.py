"""RP02 fixture (ISSUE 8 satellite): a sharded-serving path emitting a
``shard.*`` event name that is NOT in ``telemetry.EVENTS``.  Linted
against the REAL registry — the shard / serve.shard namespace
deliberately has NO family prefix, so every sharded-tier event must be
individually registered (a family would wave rogue names through)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def merge_with_unregistered_event(shards, candidates):
    # VIOLATION: a sharded-tier event dodging the registry — invisible
    # to the doctor's serving section
    telemetry.emit("shard.rogue_merge", shards=shards, n=candidates)
    # ok: the registered cross-shard merge event
    telemetry.emit(EVENTS.SHARD_MERGE, shards=shards, candidates=candidates)
