"""RP02 fixture (ISSUE r17 satellite): live-plane emitters using
``telemetry.subscriber.*`` / ``serve.latency.*`` / ``loadgen.*`` event
names that are NOT in ``telemetry.EVENTS``.  Linted against the REAL
registry — the live-plane namespaces deliberately have NO family
prefix, so every subscriber/latency/loadgen event must be individually
registered (a family would wave rogue names past the doctor's latency
section and the degraded audit)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def overflowing_subscriber(dropped):
    # VIOLATION: a subscriber-plane event dodging the registry —
    # invisible to the degraded-event audit
    telemetry.emit("telemetry.subscriber.rogue_overflow", dropped=dropped)
    # ok: the registered overload event
    telemetry.emit(EVENTS.TELEMETRY_SUBSCRIBER_DROPPED, dropped=dropped)


def serving_latency(total_s):
    # VIOLATION: a latency event the doctor's latency section never reads
    telemetry.emit("serve.latency.rogue_window", total_s=total_s)
    # ok: the registered per-request latency record
    telemetry.emit(EVENTS.SERVE_LATENCY_REQUEST, total_s=total_s)


def loadgen_summary(requests):
    # VIOLATION: a loadgen event outside the registry
    telemetry.emit("loadgen.rogue_tick", requests=requests)
    # ok: the registered run summary
    telemetry.emit(EVENTS.LOADGEN_RUN, requests=requests)
