"""RP02 fixture (ISSUE r20 satellite): health-plane emitters using
``health.*`` event names that are NOT in ``telemetry.EVENTS``.  Linted
against the REAL registry — the health namespace deliberately has NO
family prefix, so every verdict/dump event must be individually
registered (a family would wave rogue detector names past the doctor's
health-verdict section and the flight-recorder audit)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def rogue_detector(burn):
    # VIOLATION: a verdict event dodging the registry — invisible to
    # the doctor's health section and the /metrics firing gauges
    telemetry.emit("health.rogue_burn", status="firing", burn=burn)
    # ok: the registered burn-rate verdict
    telemetry.emit(EVENTS.HEALTH_SLO_BURN, status="firing", burn=burn)


def rogue_dump(path):
    # VIOLATION: a flight-dump event outside the registry
    telemetry.emit("health.rogue_dump", path=path)
    # ok: the registered flight-recorder dump record
    telemetry.emit(EVENTS.HEALTH_FLIGHT_DUMP, path=path)
