"""RP02 fixture (ISSUE 19 / r21 satellite): a tiered-residency path
emitting an ``index.tier.*`` event name that is NOT in
``telemetry.EVENTS``.  Linted against the REAL registry — the
``index.tier`` namespace deliberately has NO family prefix, so every
residency event must be individually registered (a family would wave
rogue names through, and the doctor's residency section would silently
miss them)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def fetch_with_unregistered_event(rows, nbytes):
    # VIOLATION: a residency event dodging the registry — invisible to
    # the doctor's residency section and the degraded audit
    telemetry.emit("index.tier.rogue_prefetch", rows=rows, bytes=nbytes)
    # ok: the registered cold-fetch record
    telemetry.emit(
        EVENTS.INDEX_TIER_FETCH, rows=rows, bytes=nbytes,
        wall_s=0.0, overlap_s=0.0, source="host", sync=False,
        promote=False,
    )
