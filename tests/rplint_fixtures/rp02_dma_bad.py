"""RP02 fixture (ISSUE 9 satellite): a transform-kernel path emitting a
``kernel.dma.*`` event name that is NOT in ``telemetry.EVENTS``.
Linted against the REAL registry — the kernel.dma namespace deliberately
has NO family prefix, so every transform-route event must be
individually registered (a family would wave rogue names through the
doctor's transform section and the degraded audit)."""
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS


def dispatch_with_unregistered_event(rows, steps):
    # VIOLATION: a DMA-route event dodging the registry — invisible to
    # the doctor's transform section and the degraded-event audit
    telemetry.emit("kernel.dma.rogue_retry", rows=rows, steps=steps)
    # ok: the registered route-record and fallback events
    telemetry.emit(EVENTS.KERNEL_DMA_DISPATCH, rows=rows, steps=steps)
    telemetry.emit(EVENTS.KERNEL_DMA_FALLBACK, rows=rows)
