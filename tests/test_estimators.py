"""Estimator-layer tests (SURVEY.md §5 categories 1, 3, 4, 5).

Contract source: sklearn test_random_projection.py (TRP.py in SURVEY.md),
re-expressed against the new API.  Cross-backend parity is exercised here
via the backend-parametrized tests (and in test_kernels.py /
test_sklearn_parity.py at the kernel and contract levels).
"""

import numpy as np
import pytest
import scipy.sparse as sp

from randomprojection_tpu import (
    DataDimensionalityWarning,
    GaussianRandomProjection,
    NotFittedError,
    SparseRandomProjection,
)

ALL_ESTIMATORS = [GaussianRandomProjection, SparseRandomProjection]


def make_data(n=50, d=1000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    X[X < 0.5] = 0.0  # sparsify so CSR inputs are meaningful
    return X, sp.csr_array(X)


# ---------------------------------------------------------------------------
# Category 1: validation / edge cases (TRP.py:81-110, 236-270, 385-418)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_transform_before_fit_raises(Est):
    X, _ = make_data()
    with pytest.raises(NotFittedError):
        Est(backend="numpy").transform(X)
    with pytest.raises(NotFittedError):
        Est(backend="numpy").inverse_transform(X[:, :10])


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_auto_dim_overflow_raises(Est):
    # JL bound at (n=1000, eps=0.1) >> d=100 → must raise (TRP.py:251-270)
    X = np.ones((1000, 100))
    est = Est(n_components="auto", eps=0.1, backend="numpy")
    with pytest.raises(ValueError, match="larger than the original space"):
        est.fit(X)


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_fixed_k_above_d_warns(Est):
    X = np.ones((10, 20))
    est = Est(n_components=50, random_state=0, backend="numpy")
    with pytest.warns(DataDimensionalityWarning):
        est.fit(X)
    assert est.n_components_ == 50


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_transform_wrong_width_raises(Est):
    X, _ = make_data(20, 100)
    est = Est(n_components=10, random_state=0, backend="numpy").fit(X)
    with pytest.raises(ValueError, match="features"):
        est.transform(X[:, :50])


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_invalid_n_components_raises(Est):
    X, _ = make_data(20, 100)
    for bad in (0, -3, 1.5, "many"):
        with pytest.raises(ValueError):
            Est(n_components=bad, backend="numpy").fit(X)


def test_invalid_density_raises():
    X, _ = make_data(20, 100)
    for bad in (0.0, -0.1, 1.1):
        with pytest.raises(ValueError):
            SparseRandomProjection(
                n_components=5, density=bad, backend="numpy"
            ).fit(X)


def test_fit_uses_only_shape():
    # fit must not look at values: NaNs in X cannot break it (SURVEY.md §4.1)
    X = np.full((30, 40), np.nan)
    est = GaussianRandomProjection(n_components=5, random_state=0, backend="numpy")
    est.fit(X)
    assert est.n_components_ == 5


def test_fit_schema_matches_fit():
    X, _ = make_data(50, 200)
    a = GaussianRandomProjection(n_components=7, random_state=3, backend="numpy")
    b = GaussianRandomProjection(n_components=7, random_state=3, backend="numpy")
    a.fit(X)
    b.fit_schema(50, 200, dtype=X.dtype)
    np.testing.assert_array_equal(a.components_, b.components_)


# ---------------------------------------------------------------------------
# Category 3: the JL contract keystone (TRP.py:273-308)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_jl_contract(Est, backend):
    """Every pairwise squared distance preserved within (1-eps, 1+eps)."""
    eps = 0.2
    rng = np.random.default_rng(42)
    X = rng.normal(size=(8, 5000))
    # seed fixed to a known-good value: the JL guarantee is probabilistic,
    # so the test pins a seed that satisfies it (sklearn does the same)
    est = Est(n_components="auto", eps=eps, random_state=1, backend=backend)
    Y = est.fit(X).transform(X)
    assert est.n_components_ == est.spec_.n_components

    def pdists2(A):
        A = np.asarray(A, dtype=np.float64)
        diff = A[:, None, :] - A[None, :, :]
        return (diff**2).sum(-1)

    orig, proj = pdists2(X), pdists2(Y)
    iu = np.triu_indices(8, k=1)
    ratio = proj[iu] / orig[iu]
    assert ratio.min() > 1 - eps, ratio.min()
    assert ratio.max() < 1 + eps, ratio.max()


# ---------------------------------------------------------------------------
# Category 4: API behavior (TRP.py:311-448)
# ---------------------------------------------------------------------------


def test_output_sparsity_matrix_numpy_backend():
    # dense in → dense out; sparse in → sparse out unless dense_output
    Xd, Xs = make_data(40, 300)
    est = SparseRandomProjection(
        n_components=10, random_state=0, backend="numpy", dense_output=False
    ).fit(Xd)
    assert isinstance(est.transform(Xd), np.ndarray)
    assert sp.issparse(est.transform(Xs))
    est_dense = SparseRandomProjection(
        n_components=10, random_state=0, backend="numpy", dense_output=True
    ).fit(Xd)
    assert isinstance(est_dense.transform(Xs), np.ndarray)


def test_auto_dim_and_density_resolution():
    # (n=10, eps=0.5) → k=110; density 'auto' at d=1000 → 1/sqrt(1000)
    X = np.ones((10, 1000))
    est = SparseRandomProjection(n_components="auto", eps=0.5, random_state=0,
                                 backend="numpy").fit(X)
    assert est.n_components_ == 110
    assert est.density_ == pytest.approx(1 / np.sqrt(1000))


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_determinism(Est, backend):
    X, _ = make_data(30, 200)
    est = Est(n_components=8, random_state=7, backend=backend).fit(X)
    Y1 = np.asarray(est.transform(X))
    Y2 = np.asarray(est.transform(X))
    np.testing.assert_array_equal(Y1, Y2)
    # refit with the same seed → identical matrix and outputs
    est2 = Est(n_components=8, random_state=7, backend=backend).fit(X)
    np.testing.assert_array_equal(Y1, np.asarray(est2.transform(X)))


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_matrix_stream_independent_of_data_stream(backend):
    """Using one seed for BOTH the data generator and random_state must not
    correlate R with the data (regression: unsalted streams made R equal
    the first k rows of X, inflating self-projection distances 5x)."""
    n, d, k = 2000, 256, 32
    X = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
    est = GaussianRandomProjection(k, random_state=0, backend=backend).fit(X)
    Y = np.asarray(est.transform(X))
    # per-row norm ratio ~ chi2_k/k: no row may blow past a ~6-sigma bound
    ratio = (Y**2).sum(1) / (X**2).sum(1)
    assert ratio.max() < 1 + 8 * np.sqrt(2 / k), ratio.max()
    # and R must not be a scaled copy of any leading X rows
    R = np.asarray(est.components_as_numpy())
    corr = np.abs(
        (R / np.linalg.norm(R, axis=1, keepdims=True))
        @ (X[:k] / np.linalg.norm(X[:k], axis=1, keepdims=True)).T
    )
    assert corr.max() < 0.5, corr.max()


def test_unseeded_refits_differ_but_are_reproducible():
    X, _ = make_data(30, 200)
    a = GaussianRandomProjection(n_components=8, backend="numpy").fit(X)
    b = GaussianRandomProjection(n_components=8, backend="numpy").fit(X)
    assert not np.array_equal(a.components_, b.components_)
    # the drawn seed is stored: the fitted model itself is deterministic
    np.testing.assert_array_equal(a.transform(X), a.transform(X))


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
def test_sparse_vs_dense_input_same_matrix(Est):
    Xd, Xs = make_data(30, 200)
    a = Est(n_components=8, random_state=1, backend="numpy").fit(Xd)
    b = Est(n_components=8, random_state=1, backend="numpy").fit(Xs)
    Ra = a.components_
    Rb = b.components_
    if sp.issparse(Ra):
        assert (Ra != Rb).nnz == 0
    else:
        np.testing.assert_array_equal(Ra, Rb)
    np.testing.assert_allclose(
        np.asarray(a.transform(Xd)),
        np.asarray(sp.csr_array(b.transform(Xs)).todense())
        if sp.issparse(b.transform(Xs))
        else np.asarray(b.transform(Xs)),
        rtol=1e-10,
    )


# ---------------------------------------------------------------------------
# Category 5: numerics (TRP.py:484-584)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
@pytest.mark.parametrize("precompute", [True, False])
@pytest.mark.parametrize("n,d,k", [(100, 200, 50), (60, 500, 64)])
def test_inverse_roundtrip(Est, precompute, n, d, k):
    # transform(inverse_transform(Y)) == Y because R·pinv(R) = I_k (k ≤ d)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d))
    est = Est(
        n_components=k,
        random_state=0,
        backend="numpy",
        compute_inverse_components=precompute,
    ).fit(X)
    Y = np.asarray(est.transform(X))
    Xhat = est.inverse_transform(Y)
    assert Xhat.shape == (n, d)
    Y2 = np.asarray(est.transform(Xhat))
    np.testing.assert_allclose(Y2, Y, rtol=1e-7, atol=1e-10)
    if precompute:
        assert est.inverse_components_.shape == (d, k)


@pytest.mark.parametrize("Est", ALL_ESTIMATORS)
@pytest.mark.parametrize(
    "in_dtype,out_dtype",
    [(np.float32, np.float32), (np.float64, np.float64), (np.int64, np.float64)],
)
def test_dtype_policy_numpy_backend(Est, in_dtype, out_dtype):
    X = np.random.default_rng(0).normal(size=(20, 100)).astype(in_dtype)
    est = Est(n_components=5, random_state=0, backend="numpy").fit(X)
    Y = est.transform(X.astype(est.spec_.np_dtype))
    assert np.asarray(Y).dtype == out_dtype


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_f32_f64_agreement(backend):
    X = np.random.default_rng(0).normal(size=(30, 300))
    def run(dtype):
        est = GaussianRandomProjection(
            n_components=16, random_state=5, backend=backend
        ).fit(X.astype(dtype))
        return np.asarray(est.transform(X.astype(dtype)), dtype=np.float64)
    np.testing.assert_allclose(run(np.float32), run(np.float64), atol=1e-4)


# ---------------------------------------------------------------------------
# bfloat16 input policy (TPU-native dtype extension)
# ---------------------------------------------------------------------------


def test_bfloat16_in_bfloat16_out_both_backends():
    """bf16 in → bf16 out (halves h2d bytes, SURVEY §7 R3); R stays f32 on
    both backends so only the OUTPUT is quantized; results agree with the
    f32 pipeline at bf16 rounding (~0.4%).  IEEE float16 keeps the sklearn
    promotion-to-f64 contract."""
    import ml_dtypes

    from randomprojection_tpu import GaussianRandomProjection

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X32 = np.random.default_rng(0).normal(size=(200, 128)).astype(np.float32)
    X16 = X32.astype(bf16)
    for backend in ("numpy", "jax"):
        est = GaussianRandomProjection(16, random_state=0, backend=backend)
        Y16 = np.asarray(est.fit(X16).transform(X16))
        assert Y16.dtype == bf16, (backend, Y16.dtype)
        assert est.spec_.np_dtype == bf16
        Y32 = np.asarray(
            GaussianRandomProjection(16, random_state=0, backend=backend)
            .fit(X32).transform(X32)
        )
        np.testing.assert_allclose(
            Y16.astype(np.float32), Y32, rtol=2e-2, atol=2e-2
        )

    # float16 still promotes to f64 (sklearn contract)
    est = GaussianRandomProjection(16, random_state=0, backend="numpy")
    est.fit(X32.astype(np.float16))
    assert est.spec_.np_dtype == np.dtype(np.float64)


def test_bfloat16_sparse_split2_jax():
    """bf16 input composes with the sparse kernel and split2 precision."""
    import ml_dtypes

    from randomprojection_tpu import SparseRandomProjection

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X = np.random.default_rng(1).normal(size=(100, 256)).astype(np.float32)
    est = SparseRandomProjection(
        16, density=1 / 3, random_state=0, backend="jax",
        backend_options={"precision": "split2"},
    ).fit(X.astype(bf16))
    Y = np.asarray(est.transform(X.astype(bf16)))
    assert Y.dtype == bf16
    R = est.components_as_numpy()
    np.testing.assert_allclose(
        Y.astype(np.float32), X @ R.T.astype(np.float32), rtol=3e-2, atol=3e-2
    )


def test_bfloat16_sparse_numpy_and_dtype_parity_guards():
    """Review regressions: (a) sparse kind on numpy backend accepts bf16;
    (b) f32-fit + f64-transform still returns f64 (sklearn parity — the
    bf16 edge cast must not leak); (c) an f32-fitted estimator handed a
    bf16 array returns f32 (the spec, not the input, owns the out dtype);
    (d) numpy/jax inverse_transform agree on bf16 output dtype."""
    import ml_dtypes

    from randomprojection_tpu import GaussianRandomProjection, SparseRandomProjection

    bf16 = np.dtype(ml_dtypes.bfloat16)
    X32 = np.random.default_rng(0).normal(size=(80, 128)).astype(np.float32)
    X16 = X32.astype(bf16)

    # (a) sparse kind, numpy backend, bf16 in -> bf16 out
    est = SparseRandomProjection(
        16, density=1 / 3, random_state=0, backend="numpy"
    ).fit(X16)
    Y = est.transform(X16)
    assert np.asarray(Y).dtype == bf16

    # (b) f32 fit, f64 transform input: numpy backend follows numpy
    # promotion (f64 out, sklearn parity — the bf16 edge cast must not
    # leak); the jax backend's documented policy is output-cast-to-spec
    # (f32) since TPUs execute in f32 regardless
    est_np = GaussianRandomProjection(16, random_state=0, backend="numpy").fit(X32)
    assert np.asarray(est_np.transform(X32.astype(np.float64))).dtype == np.float64
    est_jx = GaussianRandomProjection(16, random_state=0, backend="jax").fit(X32)
    assert np.asarray(est_jx.transform(X32.astype(np.float64))).dtype == np.float32

    # (c) f32 fit, bf16 input -> f32 out (spec owns the output dtype)
    for est in (est_np, est_jx):
        Yb = np.asarray(est.transform(X16))
        assert Yb.dtype == np.float32, Yb.dtype

    # (d) inverse_transform dtype agrees across backends for bf16 fits
    inv_dtypes = set()
    for backend in ("numpy", "jax"):
        est = GaussianRandomProjection(
            16, random_state=0, backend=backend, compute_inverse_components=True
        ).fit(X16)
        Xhat = est.inverse_transform(np.asarray(est.transform(X16)))
        inv_dtypes.add(np.asarray(Xhat).dtype)
    assert inv_dtypes == {bf16}, inv_dtypes


def test_device_resident_input_stays_on_device():
    """A jax-array input short-circuits host materialization: output is a
    device handle with identical values to the host-input path (the
    device-resident contract used by on-device pipelines)."""
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu import GaussianRandomProjection

    X = np.random.default_rng(0).normal(size=(50, 64)).astype(np.float32)
    est = GaussianRandomProjection(8, random_state=0, backend="jax").fit(X)
    y_host = np.asarray(est.transform(X))
    y_dev = est.transform(jnp.asarray(X))
    assert isinstance(y_dev, jax.Array)  # no host round-trip
    np.testing.assert_array_equal(np.asarray(y_dev), y_host)
    # inverse_transform likewise keeps device inputs on device
    inv = est.inverse_transform(y_dev)
    assert isinstance(inv, jax.Array)
    np.testing.assert_array_equal(
        np.asarray(inv), np.asarray(est.inverse_transform(y_host))
    )
