# Repo verification entry points (ISSUE r8 satellite; r9 added the
# staged-ingest leg; r10 the static-analysis gate; r11/ISSUE 11 the
# flow-sensitive rules + baseline-diffed CI gate).
#
#   make verify        rplint static analysis (plain + baseline-diffed),
#                      the crash-recovery smoke (subprocess
#                      SIGKILL/resume fault matrix), then the tier-1
#                      suite (the ROADMAP.md command) + a doctor smoke
#                      run, so the telemetry/report path cannot rot
#   make lint          rplint (analysis/rplint.py via `cli lint`): span
#                      balance, event-registry drift, hot-path host
#                      syncs (syntactic + one call deep), thread hygiene
#                      + flow-sensitive shutdown protocol, ops/
#                      determinism, silent swallows, Pallas DMA
#                      copy/wait/budget discipline, cross-thread
#                      shared-state races (RP10) and lock-order
#                      deadlock analysis (RP11) — non-zero on any
#                      unsuppressed finding
#   make lint-ci       `cli lint --json --baseline .rplint_baseline.json`:
#                      fails only on findings NOT in the committed
#                      baseline (rule+path+message matching, so line
#                      drift never re-flags) — the gate new strict rules
#                      land behind; exit 2 = internal error, never
#                      silent success off a partial run.  To accept
#                      intended new findings: re-run with
#                      --update-baseline (rewrites the baseline in
#                      place, pruning stale entries) and commit it.
#   make tier1         just the test suite
#   make kernel-smoke  interpreter-mode fused top-k kernel (ISSUE 7) on
#                      a toy index, parity-asserted against the scan
#                      path and host brute force — run before tier-1 so
#                      a broken serving kernel fails fast
#   make transform-smoke  interpreter-mode fused transform kernel
#                      (ISSUE 9): the double-buffered x DMA route ==
#                      the single-buffered tiling == the numpy
#                      contraction of the matching materialized matrix
#                      on a toy ragged shape, and the multi-step
#                      dispatch chain == separate dispatches — run
#                      before tier-1 so a broken transform route fails
#                      fast
#   make shard-smoke   sharded serving tier (ISSUE 8) on the virtual
#                      8-device CPU mesh: fused-per-shard == scan ==
#                      brute force, cross-shard tombstones and >int32
#                      global ids bit-identical
#   make ann-smoke     multi-probe LSH candidate tier (ISSUE 15) on the
#                      interpreter: full-probe coverage == exact ==
#                      brute force (single-device + 8-shard, cross-shard
#                      tombstones), the density-fallback rung exact, and
#                      partial-probe distances true Hamming; plus the
#                      device-fused probe path (ISSUE 16) bit-identical
#                      to the host path (multi-chunk, tombstones,
#                      ragged n_bits, 8-shard) via the same interpreter
#   make tier-smoke    tiered hot/cold residency (ISSUE 19 / r21): a
#                      corpus 4× an artificially capped HBM budget
#                      answers bit-identically to a fully resident index
#                      on the exact + LSH paths (tombstones, disk-tier
#                      memmap spills, snapshot round-trip with verified
#                      residency block, injected upload-failure rung,
#                      8-shard all-cold merge)
#   make recover-smoke subprocess kill/resume harness at toy shapes:
#                      SIGKILL the durable ingest at every injected
#                      point, restart, assert the recovered index is
#                      bit-identical to an uninterrupted run (ISSUE 6)
#   make doctor-smoke  generate real telemetry files via the CLI (a
#                      single-worker run AND a staged --ingest-workers
#                      run) and run `doctor` on them; asserts the staged
#                      run's report computes a bubble fraction
#   make live-smoke    live observability plane (ISSUE r17): a real
#                      stream-bench with --metrics-port, one HTTP scrape
#                      taken WHILE it runs, asserted to be valid
#                      OpenMetrics with histogram buckets + the new
#                      quantile summary lines and a nonzero span-derived
#                      live gauge (spans flowed through the in-process
#                      subscriber with no JSONL file involved)
#   make health-smoke  health plane (ISSUE r20): a real loadgen overload
#                      fires the SLO burn-rate detector and clears on
#                      recovery (GET /health 503→200, firing+cleared
#                      events on the JSONL), an induced stall trips the
#                      watchdog inside its timeout and dumps the flight
#                      recorder, and a SIGTERM'd stream-bench leaves a
#                      postmortem `doctor --postmortem` renders with the
#                      last-active stage

SHELL := /bin/bash
PYTHON ?= python
SMOKE_DIR := /tmp/rp_verify

.PHONY: verify lint lint-ci tier1 kernel-smoke transform-smoke shard-smoke \
        ann-smoke tier-smoke recover-smoke doctor-smoke live-smoke \
        health-smoke

verify: lint lint-ci kernel-smoke transform-smoke shard-smoke ann-smoke \
        tier-smoke recover-smoke live-smoke health-smoke tier1 doctor-smoke

lint:
	$(PYTHON) -m randomprojection_tpu lint

lint-ci:
	$(PYTHON) -m randomprojection_tpu lint --json \
	  --baseline .rplint_baseline.json > .rplint_ci.json \
	  || { rc=$$?; rm -f .rplint_ci.json; \
	       $(PYTHON) -m randomprojection_tpu lint --baseline .rplint_baseline.json; \
	       echo "lint-ci: to ACCEPT intended new findings (and prune stale baseline entries), run:"; \
	       echo "  $(PYTHON) -m randomprojection_tpu lint --baseline .rplint_baseline.json --update-baseline"; \
	       echo "then commit the rewritten .rplint_baseline.json."; \
	       exit $$rc; }
	@$(PYTHON) -c "import json; r = json.load(open('.rplint_ci.json')); \
	print('lint-ci: %d file(s) in %.3fs (process-pool fan-out)' % (r['files'], r['wall_s']))"
	@rm -f .rplint_ci.json
	@echo "lint-ci OK: zero non-baselined findings"
	@echo "  (baseline workflow: 'lint --baseline .rplint_baseline.json --update-baseline' rewrites the baseline in place; '--sarif PATH' emits SARIF 2.1.0 for CI annotation)"

kernel-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import numpy as np; \
	from randomprojection_tpu.models import sketch as sk; \
	rng = np.random.default_rng(0); \
	B = rng.integers(0, 256, size=(1500, 8), dtype=np.uint8); \
	A = rng.integers(0, 256, size=(32, 8), dtype=np.uint8); \
	idx = sk.SimHashIndex(B); \
	assert idx._chunk_impl(32, 1500, 7) == 'fused', 'fused not default'; \
	d, i = idx.query_topk(A, 7); \
	rd, ri = sk.topk_bruteforce(A, B, 7); \
	assert (d == rd).all() and (i == ri).all(), 'fused/brute mismatch'; \
	scan = sk.SimHashIndex(B, topk_impl='scan'); \
	ds, js = scan.query_topk(A, 7); \
	assert (ds == rd).all() and (js == ri).all(), 'scan/brute mismatch'; \
	print('kernel-smoke OK: fused (interpret) == scan == brute force')"

transform-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import numpy as np; \
	import jax.numpy as jnp; \
	from randomprojection_tpu.ops import pallas_kernels as pk; \
	assert pk._DMA_DEFAULT, 'DMA not the default transform route'; \
	x = np.random.default_rng(0).normal(size=(70, 700)).astype(np.float32); \
	xj = jnp.asarray(x); \
	yd = np.asarray(pk.fused_sparse_project(xj, 7, 16, 0.25, interpret=True, dma=True)); \
	ys = np.asarray(pk.fused_sparse_project(xj, 7, 16, 0.25, interpret=True, dma=False)); \
	assert (yd == ys).all(), 'DMA / single-buffered mismatch'; \
	R = np.asarray(pk.pallas_sparse_matrix(7, 16, 700, 0.25, interpret=True)); \
	np.testing.assert_allclose(yd, x @ R.T, rtol=1e-4, atol=1e-4); \
	ym = np.asarray(pk.fused_project_multistep(xj, 7, 16, 0.25, steps=3, interpret=True)); \
	per = -(-70 // 3); \
	parts = [np.asarray(pk.fused_sparse_project(xj[lo:lo+per], 7, 16, 0.25, interpret=True)) for lo in range(0, 70, per)]; \
	assert (ym == np.concatenate(parts)).all(), 'multistep / separate-dispatch mismatch'; \
	print('transform-smoke OK: dma == single-buffered == numpy ref; multistep == K dispatches')"

shard-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) -m randomprojection_tpu.serving.smoke

ann-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) -m randomprojection_tpu.ann.smoke

tier-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) -m randomprojection_tpu.tier_smoke

recover-smoke:
	rm -rf $(SMOKE_DIR)_recover && mkdir -p $(SMOKE_DIR)_recover
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu recover --smoke \
	  $(SMOKE_DIR)_recover
	@echo "recover-smoke OK"

tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

live-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu.utils.live_smoke

health-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu.utils.health_smoke

doctor-smoke:
	rm -rf $(SMOKE_DIR) && mkdir -p $(SMOKE_DIR)
	$(PYTHON) -c "import numpy as np; np.save('$(SMOKE_DIR)/x.npy', np.random.default_rng(0).normal(size=(256, 64)).astype(np.float32))"
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu project \
	  --input $(SMOKE_DIR)/x.npy --output $(SMOKE_DIR)/y.npy \
	  --kind gaussian --n-components 8 --backend numpy --batch-rows 64 \
	  --telemetry-jsonl $(SMOKE_DIR)/events.jsonl \
	  --openmetrics $(SMOKE_DIR)/metrics.om
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu doctor $(SMOKE_DIR)/events.jsonl
	@grep -q '# EOF' $(SMOKE_DIR)/metrics.om || { echo 'openmetrics output missing # EOF'; exit 1; }
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu project \
	  --input $(SMOKE_DIR)/x.npy --output $(SMOKE_DIR)/y_staged.npy \
	  --kind gaussian --n-components 8 --backend numpy --batch-rows 64 \
	  --ingest-workers 2 \
	  --telemetry-jsonl $(SMOKE_DIR)/staged.jsonl
	JAX_PLATFORMS=cpu $(PYTHON) -m randomprojection_tpu doctor \
	  $(SMOKE_DIR)/staged.jsonl --json | $(PYTHON) -c "import json,sys; \
	  r = json.load(sys.stdin); \
	  assert r['traces']['batches'] > 0, 'staged run produced no batch traces'; \
	  b = r['batch']['bubble']; \
	  assert isinstance(b.get('pct'), (int, float)), 'no bubble fraction computed'; \
	  print('staged doctor OK: bubble %.2f%% of batch wall' % b['pct'])"
	@echo "doctor-smoke OK"
